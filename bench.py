#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extras": {...}}

Headline: single-client sync task throughput, directly comparable to the
reference's ray_perf.py microbenchmark ("single client tasks sync",
reference: python/ray/_private/ray_perf.py:174; recorded value 1006.9
tasks/s in release/release_logs/2.9.3/microbenchmark.json).

Also measured (extras): async task throughput, actor call throughput,
object-store put bandwidth, and a Llama train-step MFU benchmark.

Robustness contract (the driver runs this unattended):
  * every phase is individually try/except'ed with its own timeout — one
    hang or crash cannot erase numbers already measured;
  * the train phase runs in a watchdogged subprocess: a normal-site
    interpreter first (TPU plugin registered, real-chip MFU), killed
    after a hard deadline; on any failure a ``python -S`` CPU fallback
    (plugin-free, tiny model) still records train numbers;
  * the JSON line is ALWAYS printed, with per-phase errors in
    extras["errors"].
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))


def bench_tasks_sync(ray_tpu, n=300):
    @ray_tpu.remote
    def e():
        return b"ok"

    ray_tpu.get(e.remote(), timeout=60)  # warm lease
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(e.remote(), timeout=60)
    return n / (time.perf_counter() - t0)

def bench_tasks_async(ray_tpu, n=2000):
    @ray_tpu.remote
    def e():
        return b"ok"

    ray_tpu.get([e.remote() for _ in range(50)], timeout=60)
    t0 = time.perf_counter()
    ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
    return n / (time.perf_counter() - t0)

def bench_actor(ray_tpu, n_sync=300, n_async=2000):
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.m.remote(), timeout=60)
    t0 = time.perf_counter()
    for _ in range(n_sync):
        ray_tpu.get(a.m.remote(), timeout=60)
    sync = n_sync / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n_async)], timeout=120)
    return sync, n_async / (time.perf_counter() - t0)

def bench_burst_then_async(ray_tpu, burst=2000, n=2000):
    """Burst-independence phase (round-5 verdict top finding): 2000
    BLOCKING sync round trips used to train the owner's per-function
    service-time estimator into serializing dispatch, collapsing the
    async rate that follows from ~5k/s to ~1.5k/s.  With depth driven by
    worker-reported execution time this rate must track
    tasks_async_per_s (the fresh-process async run) within noise."""
    @ray_tpu.remote
    def e():
        return b"ok"

    ray_tpu.get(e.remote(), timeout=60)
    for _ in range(burst):
        ray_tpu.get(e.remote(), timeout=60)
    t0 = time.perf_counter()
    ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
    return n / (time.perf_counter() - t0)

def _client_bench(address: str, n: int, ready_file: str = ""):
    """One concurrent driver (runs as a subprocess): connect to the
    shared cluster, fire n async tasks, print one parseable line.
    With a ready_file, clients barrier on it after warming so every
    burst window overlaps — the union-window aggregate then measures
    contention, not per-client interpreter startup skew."""
    import ray_tpu

    ray_tpu.init(address=address)

    @ray_tpu.remote
    def e():
        return b"ok"

    ray_tpu.get([e.remote() for _ in range(50)], timeout=60)
    if ready_file:
        print("CLIENTREADY", flush=True)
        deadline = time.time() + 60
        while not os.path.exists(ready_file) and time.time() < deadline:
            time.sleep(0.01)
    t0 = time.time()  # absolute: the parent unions windows across clients
    ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
    t1 = time.time()
    print("CLIENTJSON " + json.dumps(
        {"tasks": n, "wall_s": round(t1 - t0, 4),
         "start": round(t0, 4), "end": round(t1, 4)}))
    ray_tpu.shutdown()

def _head_scaling_probe(ray_tpu):
    """Best-effort head-side sample after a client-count round: the
    sched-latency SLO p99 and per-shard ingest loop lag (the sharded
    head's 'which plane is hot' signal)."""
    try:
        snap = ray_tpu.api._worker().head.call("autoscaler_snapshot",
                                               timeout=15)
    except Exception:
        return None, {}
    p99 = (snap.get("signals") or {}).get("sched_queued_p99_ms")
    lags = {name: round(float(p.get("lag_s", 0.0)) * 1000.0, 3)
            for name, p in ((snap.get("shards") or {}).get("planes")
                            or {}).items()}
    return p99, lags

def bench_head_scaling(ray_tpu, n=800, pairs=2, counts=(2, 4, 8, 16),
                       probe=True):
    """Head-scalability phase (ISSUE 8, extended by ISSUE 18): aggregate
    multi-driver task throughput at 2..16 concurrent clients sharing one
    cluster.  Every client's lease requests, task-event flushes, and
    heartbeat-fed directory traffic land on the same head/agent — this
    is the phase that shows whether one control-plane structure is the
    ceiling.  Cycled BEST-OF ALTERNATING rounds per the slow-box
    protocol; scaling_efficiency_pct is per-client throughput retained
    from 2 to 8 clients (100 * rate8 / (4 * rate2)).  Also emits the
    sched_p99_ms_by_clients curve and per-shard ingest loop lag sampled
    right after each client count's best round."""
    rates = {c: [] for c in counts}
    p99_curve = {}
    shard_lag = {}
    for _ in range(pairs):
        for c in counts:
            rates[c].append(bench_multi_client(ray_tpu, clients=c, n=n))
            if probe:
                p99, lags = _head_scaling_probe(ray_tpu)
                if p99 is not None:
                    p99_curve[str(c)] = p99
                if lags:
                    shard_lag = lags
    best = {c: max(v) for c, v in rates.items()}
    eff = 100.0 * best[8] / (4 * best[2]) if best.get(2) else 0.0
    out = {
        "multi_client_2_tasks_per_s": round(best[2], 1),
        "multi_client_tasks_per_s": round(best[8], 1),
        "scaling_efficiency_pct": round(eff, 1),
    }
    if 4 in best:
        out["multi_client_4_tasks_per_s"] = round(best[4], 1)
    if 16 in best:
        out["multi_client_16_tasks_per_s"] = round(best[16], 1)
        out["scaling_efficiency_16_pct"] = round(
            100.0 * best[16] / (8 * best[2]), 1) if best.get(2) else 0.0
    if p99_curve:
        out["sched_p99_ms_by_clients"] = p99_curve
    if shard_lag:
        out["head_shard_loop_lag_ms"] = shard_lag
    return out

def _head_scaling_ab_bench(shards: int):
    """Runs as a subprocess: its OWN cluster with RT_HEAD_INGEST_SHARDS
    pinned, a reduced 2/8-client ladder, one JSON line out — the
    single-loop (shards=0) side of the head scale-out A/B.  The main
    phase's numbers come from the default (sharded) head; this run is
    the control."""
    os.environ["RT_HEAD_INGEST_SHARDS"] = str(shards)
    import ray_tpu

    ray_tpu.init(num_cpus=max(4, os.cpu_count() or 4),
                 object_store_memory=256 * 1024 * 1024)
    try:
        out = bench_head_scaling(ray_tpu, pairs=2, counts=(2, 8),
                                 probe=False)
        print("HEADSCALEJSON " + json.dumps({
            "head_ingest_shards": shards,
            "multi_client_2_tasks_per_s":
                out["multi_client_2_tasks_per_s"],
            "multi_client_tasks_per_s": out["multi_client_tasks_per_s"],
            "scaling_efficiency_pct": out["scaling_efficiency_pct"],
        }))
    finally:
        ray_tpu.shutdown()

def bench_head_scaling_single_loop_ab():
    """The A/B control: the same multi-client ladder against a
    single-loop head (head_ingest_shards=0) in a subprocess cluster.
    Keys are suffixed _single_loop; scaling_efficiency_vs_single_loop_x
    is the headline ratio (> 1 = the shards pay for themselves)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--head-scaling-bench", "0"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    for line in proc.stdout.splitlines():
        if line.startswith("HEADSCALEJSON "):
            r = json.loads(line[len("HEADSCALEJSON "):])
            return {
                "multi_client_tasks_per_s_single_loop":
                    r["multi_client_tasks_per_s"],
                "scaling_efficiency_pct_single_loop":
                    r["scaling_efficiency_pct"],
            }
    raise RuntimeError(
        f"head-scaling A/B rc={proc.returncode}: {proc.stderr[-400:]}")

def bench_multi_client(ray_tpu, clients=3, n=1000):
    """Aggregate throughput with several concurrent DRIVER processes
    sharing one cluster — the owners contend for the same agents'
    leases, which is where history-dependent dispatch and greedy lease
    retention show up as cross-client interference.  The rate is total
    tasks over the UNION of the clients' measured burst windows
    (min start → max end, absolute stamps on one host clock), so
    interpreter/jax startup — seconds per client, pure noise for the
    control-plane question — stays out of the denominator, while
    non-overlapping windows can't overstate the aggregate."""
    addr = "%s:%d" % tuple(ray_tpu.api._worker().head_addr)
    ready_file = os.path.join(
        "/tmp", f"rt-bench-go-{os.getpid()}-{time.monotonic_ns()}")
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py"), "--client-bench",
         addr, str(n), ready_file], stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, cwd=REPO)
        for _ in range(clients)]
    # start barrier: wait for every client to finish init+warm, then
    # release them together so the measured windows overlap.  select()
    # with a deadline: a wedged client must not hang the whole phase
    import select as _select

    deadline = time.time() + 120
    for p in procs:
        ready = False
        while time.time() < deadline:
            r, _w, _x = _select.select([p.stdout], [], [], 1.0)
            if not r:
                continue
            line = p.stdout.readline()
            if not line:
                break  # EOF: client died during init
            if line.startswith("CLIENTREADY"):
                ready = True
                break
            # anything else (forwarded worker log lines — log_to_driver
            # is on by default) is noise: keep reading
        if not ready:
            p.kill()
    open(ready_file, "w").close()
    total = 0
    starts, ends = [], []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=180)
            except subprocess.TimeoutExpired:
                p.kill()
                continue
            for line in out.splitlines():
                if line.startswith("CLIENTJSON "):
                    r = json.loads(line[len("CLIENTJSON "):])
                    total += r["tasks"]
                    starts.append(r["start"])
                    ends.append(r["end"])
    finally:
        try:
            os.unlink(ready_file)
        except OSError:
            pass
    if total == 0 or not starts:
        raise RuntimeError("no concurrent client completed")
    return total / max(1e-9, max(ends) - min(starts))

def bench_trace_overhead(ray_tpu, n=1500, pairs=3):
    """Tracing cost phase: async task throughput with tracing fully
    sampled vs. disabled, as a percent throughput loss.  Only the
    driver's env needs toggling: the root sampling decision happens at
    submit time, and worker-side execute spans obey the propagated
    sampled flag, so RT_* in this process controls the whole pipeline.

    Protocol: alternate off/on measurement pairs and compare BEST-OF
    rates.  Machine-load noise on a shared box swings identical runs by
    ±30%+, far more than the effect being measured; best-of discards
    slow outliers symmetrically, so the reported number converges on
    the true per-task cost instead of whichever run got unlucky.
    Must stay < 5% at the default sampling ratio (tracing is on by
    default — its cost is a perf budget item like burst_async_per_s)."""
    @ray_tpu.remote
    def e():
        return b"ok"

    def measure():
        ray_tpu.get([e.remote() for _ in range(100)], timeout=60)  # warm
        t0 = time.perf_counter()
        ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
        return n / (time.perf_counter() - t0)

    saved = {k: os.environ.get(k)
             for k in ("RT_TRACING_ENABLED", "RT_TRACE_SAMPLING_RATIO")}
    on_rates, off_rates = [], []
    try:
        for _ in range(pairs):
            os.environ["RT_TRACING_ENABLED"] = "false"
            time.sleep(0.3)  # let the tracing config TTL cache refresh
            off_rates.append(measure())
            os.environ["RT_TRACING_ENABLED"] = "true"
            os.environ["RT_TRACE_SAMPLING_RATIO"] = "1.0"
            time.sleep(0.3)
            on_rates.append(measure())
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    on, off = max(on_rates), max(off_rates)
    return {
        "traced_async_per_s": round(on, 1),
        "untraced_async_per_s": round(off, 1),
        # negative = tracing measured faster (noise); report as-is
        "trace_overhead_pct": round(100.0 * (off - on) / off, 2),
    }

def bench_profile_overhead(ray_tpu, n=1200, pairs=2):
    """Sampling-profiler cost phase: async task throughput with the
    in-process sampler running at the default hz on the DRIVER (the
    submit hot path — the process an operator would actually profile
    while hunting the tasks/s plateau) vs. not running, as a percent
    throughput loss.  BEST-OF alternating pairs per the slow-box
    protocol, same as trace_overhead.  Budget: < 5% at
    profiler_default_hz — the profiler must be cheap enough to switch
    on against a production incident."""
    from ray_tpu._private import profiling

    @ray_tpu.remote
    def e():
        return b"ok"

    def measure():
        ray_tpu.get([e.remote() for _ in range(100)], timeout=60)  # warm
        t0 = time.perf_counter()
        ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
        return n / (time.perf_counter() - t0)

    on_rates, off_rates = [], []
    for _ in range(pairs):
        off_rates.append(measure())
        started = profiling.start_sampler()
        try:
            on_rates.append(measure())
        finally:
            if started.get("ok"):
                profiling.stop_sampler()
    on, off = max(on_rates), max(off_rates)
    return {
        "profiled_async_per_s": round(on, 1),
        "unprofiled_async_per_s": round(off, 1),
        # negative = profiler measured faster (noise); report as-is
        "profile_overhead_pct": round(100.0 * (off - on) / off, 2),
    }

def bench_memory_scan_overhead(ray_tpu, n=2500, pairs=2, live_objects=10_000):
    """Memory-accounting cost phase: async task throughput while the
    head's periodic leak scan (running at its DEFAULT cadence) joins a
    10k-entry driver reference table every interval, vs the same scan
    over an emptied table.  The differential is what `rtpu memory`
    accounting costs a busy owner: each scan serves rpc_memory_summary
    off the driver's IO loop (10k ref records built under the ref-table
    lock) plus the agent/worker fan-out.  BEST-OF alternating pairs per
    the slow-box protocol (see bench_trace_overhead).  Budget:
    memory_scan_overhead_pct < 5."""
    @ray_tpu.remote
    def e():
        return b"ok"

    # every measured window must SPAN the scan cadence, or best-of
    # selection just picks whichever run dodged the scans entirely
    from ray_tpu._private.config import config as _cfg
    min_window = 1.2 * float(_cfg.memory_scan_interval_s)

    def measure():
        ray_tpu.get([e.remote() for _ in range(100)], timeout=60)  # warm
        t0 = time.perf_counter()
        done = 0
        while True:
            ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
            done += n
            elapsed = time.perf_counter() - t0
            if elapsed >= min_window:
                return done / elapsed

    on_rates, off_rates = [], []
    for _ in range(pairs):
        off_rates.append(measure())
        live = [ray_tpu.put(i) for i in range(live_objects)]
        try:
            on_rates.append(measure())
        finally:
            del live  # refs release; the table shrinks back
    on, off = max(on_rates), max(off_rates)
    return {
        "scan_loaded_async_per_s": round(on, 1),
        "scan_unloaded_async_per_s": round(off, 1),
        # negative = loaded measured faster (noise); report as-is
        "memory_scan_overhead_pct": round(100.0 * (off - on) / off, 2),
    }


def _serve_http_get(host, port, conns, total, path, timeout_s=120):
    """Drive the Serve proxy with `conns` keep-alive connections issuing
    `total` GET requests between them; returns (rps, p99_ms)."""
    import asyncio

    lat = []
    errors = [0]
    counter = [0]

    async def client():
        try:
            reader, writer = await asyncio.open_connection(host, port)
        except OSError:
            errors[0] += 1
            return
        req = f"GET {path} HTTP/1.1\r\nHost: bench\r\n\r\n".encode()
        try:
            while counter[0] < total:
                counter[0] += 1
                t0 = time.perf_counter()
                writer.write(req)
                await writer.drain()
                status = await reader.readline()
                clen = 0
                while True:
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                    if h.lower().startswith(b"content-length:"):
                        clen = int(h.split(b":", 1)[1])
                if clen:
                    await reader.readexactly(clen)
                if b"200" in status:
                    lat.append(time.perf_counter() - t0)
                else:
                    errors[0] += 1
        except (OSError, asyncio.IncompleteReadError):
            errors[0] += 1
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def run():
        await asyncio.wait_for(
            asyncio.gather(*[client() for _ in range(conns)]),
            timeout=timeout_s)

    t0 = time.perf_counter()
    asyncio.run(run())
    wall = time.perf_counter() - t0
    if not lat:
        raise RuntimeError(f"no serve responses ({errors[0]} errors)")
    lat.sort()
    p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1000.0
    return len(lat) / wall, p99

def _serve_sse_items(host, port, conns, rounds, path, timeout_s=120):
    """SSE items/s: each connection issues `rounds` back-to-back
    chunked requests on ONE keep-alive connection (exercising
    keep-alive-after-SSE, async plane only)."""
    import asyncio

    items = [0]

    async def client():
        reader, writer = await asyncio.open_connection(host, port)
        req = (f"GET {path} HTTP/1.1\r\nHost: bench\r\n"
               "Accept: text/event-stream\r\n\r\n").encode()
        try:
            for _ in range(rounds):
                writer.write(req)
                await writer.drain()
                while True:  # status + headers
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        break
                while True:  # chunks
                    size = int((await reader.readline()).strip() or b"0", 16)
                    if size == 0:
                        await reader.readline()  # trailing CRLF
                        break
                    await reader.readexactly(size + 2)  # data + CRLF
                    items[0] += 1
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def run():
        await asyncio.wait_for(
            asyncio.gather(*[client() for _ in range(conns)]),
            timeout=timeout_s)

    t0 = time.perf_counter()
    asyncio.run(run())
    if not items[0]:
        raise RuntimeError("no SSE items received")
    return items[0] / (time.perf_counter() - t0)

def bench_serve(ray_tpu, pairs=2, conns=64, total=1200):
    """Serve data-plane phases: keep-alive HTTP RPS + p99 through the
    proxy, async event-loop ingress vs the executor-thread baseline
    (legacy_threads=True), measured BEST-OF ALTERNATING PAIRS per the
    slow-box protocol.  Also: SSE streaming items/s and a 256-in-flight
    completion check (the old thread pool capped in-flight at ~32)."""
    from ray_tpu import serve

    @serve.deployment(name="echo_bench", num_replicas=2,
                      max_ongoing_requests=32)
    def echo_bench(x):
        return {"ok": 1}

    @serve.deployment(name="sse_bench")
    def sse_bench(x):
        for i in range(25):
            yield i

    serve.run(echo_bench.bind())
    serve.run(sse_bench.bind())
    out = {}
    try:
        thread_rates, async_rates, async_p99 = [], [], []
        for _ in range(pairs):
            for legacy in (True, False):
                try:
                    serve.shutdown_http()
                except Exception:
                    pass
                host, port = serve.start_http(legacy_threads=legacy)
                _serve_http_get(host, port, 4, 40, "/echo_bench?x=1")  # warm
                rps, p99 = _serve_http_get(host, port, conns, total,
                                           "/echo_bench?x=1")
                (thread_rates if legacy else async_rates).append(rps)
                if not legacy:
                    async_p99.append(p99)
        out["serve_rps"] = round(max(async_rates), 1)
        out["serve_rps_thread_baseline"] = round(max(thread_rates), 1)
        out["serve_async_vs_threads"] = round(
            max(async_rates) / max(thread_rates), 2)
        out["serve_p99_ms"] = round(min(async_p99), 2)
        # stream + high-inflight phases ride the async plane just started
        host, port = serve.proxy_addresses()[0]
        out["serve_stream_items_per_s"] = round(
            _serve_sse_items(host, port, 8, 3, "/sse_bench?x=1"), 1)
        rps256, _ = _serve_http_get(host, port, 256, 256, "/echo_bench?x=1")
        out["serve_inflight_256_ok"] = rps256 > 0
    finally:
        try:
            serve.shutdown_http()
        except Exception:
            pass
        for name in ("echo_bench", "sse_bench"):
            try:
                serve.delete(name)
            except Exception:
                pass
    return out

def _llm_stream_load(host, port, path, n_streams, payload_fn,
                     timeout_s=600):
    """Drive `n_streams` concurrent SSE generation requests; returns
    (total_token_items, wall_s, per-stream TTFT list, error_count)."""
    import asyncio

    ttfts = []
    tokens = [0]
    errors = [0]

    async def client(i):
        body = json.dumps(payload_fn(i)).encode()
        req = (f"POST {path} HTTP/1.1\r\nHost: bench\r\n"
               f"Content-Type: application/json\r\n"
               f"Accept: text/event-stream\r\n"
               f"Content-Length: {len(body)}\r\n\r\n").encode() + body
        try:
            reader, writer = await asyncio.open_connection(host, port)
        except OSError:
            errors[0] += 1
            return
        try:
            t0 = time.perf_counter()
            writer.write(req)
            await writer.drain()
            status = await reader.readline()
            if b"200" not in status:
                errors[0] += 1
                return
            while True:  # headers
                h = await reader.readline()
                if h in (b"\r\n", b"\n", b""):
                    break
            first = None
            while True:  # chunks
                size = int((await reader.readline()).strip() or b"0", 16)
                if size == 0:
                    await reader.readline()
                    break
                data = await reader.readexactly(size + 2)
                if first is None:
                    first = time.perf_counter() - t0
                try:
                    tokens[0] += len(json.loads(data[:-2]).get("tokens")
                                     or [])
                except ValueError:
                    pass
            if first is not None:
                ttfts.append(first)
        except (OSError, asyncio.IncompleteReadError, ValueError):
            errors[0] += 1
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def run():
        await asyncio.wait_for(
            asyncio.gather(*[client(i) for i in range(n_streams)]),
            timeout=timeout_s)

    t0 = time.perf_counter()
    asyncio.run(run())
    return tokens[0], time.perf_counter() - t0, ttfts, errors[0]

def bench_llm_serve(ray_tpu, pairs=2, streams=64, big_streams=256):
    """LLM serving-tier A/B (ISSUE 11): continuous batching (ONE pinned
    decode loop, token-boundary lane refill, paged KV) vs the
    ``@serve.batch`` static-batching baseline (fixed 8-wide batch runs
    to its longest member, disbands, re-dispatches), same model +
    params + SSE streaming contract + item chunking on both sides,
    BEST-OF ALTERNATING PAIRS per the slow-box protocol.

    The A/B runs at ``big_streams`` (256) concurrent streams — 4x the
    continuous path's 64 decode lanes, so lanes REFILL at token
    boundaries while the baseline pays padding-to-longest and
    batch-boundary re-dispatch; a decode-heavy variable-length
    workload (32..96 new tokens, mean ~64).  Contract:
    ``llm_continuous_vs_batch_x`` >= 2 at 64+ concurrent streams with
    zero shed-gate 503s below KV-page capacity.  A 64-stream
    continuous run reports unqueued TTFT."""
    from ray_tpu import serve
    from ray_tpu.serve.api import Deployment
    from ray_tpu.serve.llm import _LLMBatchCallable

    model = {"vocab_size": 128, "dim": 64, "n_layers": 2, "n_heads": 4,
             "n_kv_heads": 2, "hidden_dim": 128, "max_seq_len": 128}
    engine_kw = dict(model=model, page_size=16, prefill_chunk=32, seed=7)
    prompt = [7, 3, 11, 5]
    pages_per_seq = 8  # ceil(128/16)

    def payload(i):
        return {"tokens": prompt, "max_new_tokens": 32 + (i * 37) % 65,
                "request_id": f"bench-{i}-{time.monotonic_ns()}"}

    def expected(n_streams):
        return sum(32 + (i * 37) % 65 for i in range(n_streams))

    out = {}

    def p99(vals):
        vals = sorted(vals)
        return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

    try:
        # continuous: 64 decode lanes, pages for all of them at worst
        # case; everything beyond queues and refills lanes at token
        # boundaries
        serve.run(serve.llm_deployment(
            "llm_cb", max_ongoing_requests=big_streams + 8,
            max_batch=64, num_pages=1 + 64 * pages_per_seq,
            max_queue=big_streams, stream_flush_tokens=16, **engine_kw))
        # baseline gets RIGHT-SIZED shapes for its batch (a
        # static-batching server would compile [8,*], not [64,*]) — the
        # A/B measures the batching policy, not a shape handicap
        base = Deployment(_LLMBatchCallable, "llm_sb",
                          max_ongoing_requests=big_streams + 8)
        serve.run(base.bind(max_batch_size=8, batch_wait_timeout_s=0.005,
                            num_pages=1 + 8 * pages_per_seq, max_batch=8,
                            prefill_lanes=8, stream_flush_tokens=16,
                            **engine_kw))

        # ---- engine-level A/B (in-process, no serving transport):
        # isolates the BATCHING POLICY — in this sandbox the
        # serving-level numbers below are dominated by per-syscall
        # transport costs shared by both sides, which pins their ratio
        # toward 1 regardless of policy (see BENCH_r07 notes; the
        # driver box collapses transport ~1000x, pulling the serving
        # ratio toward this engine ratio)
        from ray_tpu.serve.llm import LLMEngine

        def eng_reqs(r, n):
            return [{"tokens": prompt,
                     "max_new_tokens": 32 + (i * 37) % 65,
                     "request_id": f"eng-{r}-{i}"} for i in range(n)]

        e_cont = LLMEngine(num_pages=1 + 64 * pages_per_seq, max_batch=64,
                           prefill_lanes=8, max_queue=300, **engine_kw)
        e_stat = LLMEngine(num_pages=1 + 8 * pages_per_seq, max_batch=8,
                           prefill_lanes=8, max_queue=300, **engine_kw)
        e_cont.generate_batch(eng_reqs("w", 2))
        e_stat.generate_batch(eng_reqs("x", 2))
        ec, es = [], []
        n_eng = big_streams
        etotal = sum(32 + (i * 37) % 65 for i in range(n_eng))
        for r in range(pairs):
            t0 = time.perf_counter()
            e_cont.generate_batch(eng_reqs(f"c{r}", n_eng))
            ec.append(etotal / (time.perf_counter() - t0))
            reqs = eng_reqs(f"s{r}", n_eng)
            t0 = time.perf_counter()
            for b in range(0, n_eng, 8):
                e_stat.generate_batch(reqs[b:b + 8])
            es.append(etotal / (time.perf_counter() - t0))
        out["llm_engine_tokens_per_s"] = round(max(ec), 1)
        out["llm_engine_batch_tokens_per_s"] = round(max(es), 1)
        out["llm_engine_continuous_vs_batch_x"] = round(
            max(ec) / max(es), 2)
        host, port = serve.start_http()
        # warm both paths (jit compiles on first request)
        _llm_stream_load(host, port, "/llm_cb", 2, payload)
        _llm_stream_load(host, port, "/llm_sb", 2, payload)
        cont, batch, ttft99, bttft99 = [], [], [], []
        for _ in range(pairs):
            toks, wall, ttfts, errs = _llm_stream_load(
                host, port, "/llm_cb", big_streams, payload)
            if errs or toks < expected(big_streams):
                raise RuntimeError(
                    f"continuous run incomplete: {toks} tokens, "
                    f"{errs} errors (shed below capacity?)")
            cont.append(toks / wall)
            ttft99.append(p99(ttfts))
            btoks, bwall, bttfts, berrs = _llm_stream_load(
                host, port, "/llm_sb", big_streams, payload)
            if berrs or btoks < expected(big_streams):
                raise RuntimeError(
                    f"baseline run incomplete: {btoks} tokens, "
                    f"{berrs} errors")
            batch.append(btoks / bwall)
            bttft99.append(p99(bttfts))
        out["llm_tokens_per_s"] = round(max(cont), 1)
        out["llm_batch_tokens_per_s"] = round(max(batch), 1)
        out["llm_continuous_vs_batch_x"] = round(max(cont) / max(batch), 2)
        out["llm_ttft_p99_ms"] = round(min(ttft99) * 1000.0, 1)
        # the latency half of the story: a static batch's first token
        # waits for its WHOLE batch to finish
        out["llm_batch_ttft_p99_ms"] = round(min(bttft99) * 1000.0, 1)
        # at-capacity TTFT: 64 streams fit the 64 lanes outright on
        # the continuous path, while the static baseline's first token
        # still waits out its batch — the latency half of the win
        toks, wall, ttfts, errs = _llm_stream_load(
            host, port, "/llm_cb", streams, payload)
        out["llm_tokens_per_s_64"] = round(toks / wall, 1)
        out["llm_sse_errors"] = errs
        if ttfts:
            out["llm_ttft_p99_ms_64"] = round(p99(ttfts) * 1000.0, 1)
        btoks, bwall, bttfts, berrs = _llm_stream_load(
            host, port, "/llm_sb", streams, payload)
        if bttfts and not berrs:
            out["llm_batch_ttft_p99_ms_64"] = round(
                p99(bttfts) * 1000.0, 1)

        # ---- 80%-shared-prefix workload (ISSUE 16): copy-on-write
        # prefix sharing A/B at `streams` concurrent SSE streams.  80%
        # of requests carry the same 64-token system prompt + a 4-token
        # unique tail; 20% are fully unique 68-token prompts.  Same
        # engine shape both sides, only llm_prefix_sharing differs —
        # the ratios isolate the sharing policy (sandbox protocol:
        # ratios-only for timings; byte/percent counts are exact).
        sys_prompt = [((i * 13) % 120) + 1 for i in range(64)]
        plen = 68

        def px_payload(kind):
            def make(i):
                if (i % 10) < 8:
                    toks = sys_prompt + [1 + (i % 11), 2 + ((i * 3) % 13),
                                         3 + ((i * 7) % 17), 4 + (i % 5)]
                else:
                    toks = [((i * 29 + j * 7) % 120) + 1
                            for j in range(plen)]
                return {"tokens": toks,
                        "max_new_tokens": 16 + (i * 37) % 17,
                        "request_id": f"{kind}{i}-{time.monotonic_ns()}"}
            return make

        for name, share in (("llm_px", True), ("llm_npx", False)):
            serve.run(serve.llm_deployment(
                name, max_ongoing_requests=streams + 8, max_batch=8,
                num_pages=1 + 64 * pages_per_seq, max_queue=streams,
                stream_flush_tokens=16, prefix_sharing=share,
                **engine_kw))
        _llm_stream_load(host, port, "/llm_px", 2, px_payload("w"))
        _llm_stream_load(host, port, "/llm_npx", 2, px_payload("w"))
        px_ttft, npx_ttft, n_req = [], [], 2  # warm streams count too
        for _ in range(pairs):
            toks, wall, ttfts, errs = _llm_stream_load(
                host, port, "/llm_px", streams, px_payload("p"))
            if errs:
                raise RuntimeError(f"prefix-sharing run: {errs} errors")
            px_ttft.append(p99(ttfts))
            btoks, bwall, bttfts, berrs = _llm_stream_load(
                host, port, "/llm_npx", streams, px_payload("n"))
            if berrs:
                raise RuntimeError(f"no-sharing run: {berrs} errors")
            npx_ttft.append(p99(bttfts))
            n_req += streams
        px = ray_tpu.get(
            serve.get_handle("llm_px").method("stats")(), timeout=30)
        npx = ray_tpu.get(
            serve.get_handle("llm_npx").method("stats")(), timeout=30)
        # prefill tokens COMPUTED per request = prompt tokens submitted
        # minus tokens attached from shared pages (acceptance: >= 2x
        # drop vs the no-sharing engine at 80% shared)
        px_prefill = (plen * n_req - px["prefix_tokens_shared"]) / n_req
        npx_prefill = (plen * n_req - npx["prefix_tokens_shared"]) / n_req
        out["llm_prefix_hit_pct"] = round(
            100.0 * px["prefix_hits"] / n_req, 1)
        out["llm_prefix_prefill_drop_x"] = round(
            npx_prefill / px_prefill, 2)
        out["llm_prefix_kv_bytes_per_stream"] = int(
            px["pages_allocated_total"] * px["kv_page_bytes"] / n_req)
        out["llm_nosharing_kv_bytes_per_stream"] = int(
            npx["pages_allocated_total"] * npx["kv_page_bytes"] / n_req)
        out["llm_prefix_kv_pages_drop_x"] = round(
            npx["pages_allocated_total"] / px["pages_allocated_total"], 2)
        out["llm_prefix_ttft_p99_vs_nosharing_x"] = round(
            min(npx_ttft) / min(px_ttft), 2)

        # ---- paged decode A/B (ISSUE 19): decode-step cost vs context.
        # The Pallas paged kernel walks USED pages only, so (a) growing
        # a config's max_seq_len 4x leaves short-context step cost
        # ~flat, while the dense reference gathers + softmaxes the full
        # [B, max_seq] context every step; (b) within one config, paged
        # step cost follows the sequence's actual context length.
        # In-process engines (no transport), alternating pairs,
        # best-of; ratios only per the sandbox protocol — the driver
        # box is authoritative for absolute step times.
        ab_model = {"vocab_size": 128, "dim": 128, "n_layers": 2,
                    "n_heads": 8, "n_kv_heads": 4, "hidden_dim": 256}

        def mk_eng(impl, max_seq):
            pps = -(-max_seq // 16)
            return LLMEngine(model=dict(ab_model, max_seq_len=max_seq),
                             page_size=16, prefill_chunk=32, seed=7,
                             num_pages=1 + 8 * pps, max_batch=8,
                             prefill_lanes=8, max_queue=64,
                             attention_impl=impl)

        def step_cost(eng, prompt_len, new_toks, tag):
            p = [((i * 13) % 120) + 1 for i in range(prompt_len)]
            reqs = [{"tokens": p, "max_new_tokens": new_toks,
                     "request_id": f"{tag}-{i}"} for i in range(8)]
            s0 = eng.stats()
            eng.generate_batch(reqs)
            s1 = eng.stats()
            steps = s1["decode_steps"] - s0["decode_steps"]
            return (s1["decode_secs"] - s0["decode_secs"]) / max(steps, 1)

        grid = [(impl, ms) for impl in ("paged", "dense")
                for ms in (128, 512)]
        engines = {key: mk_eng(*key) for key in grid}
        for key, eng in engines.items():
            step_cost(eng, 16, 4, f"ab-warm-{key[0]}-{key[1]}")
        cost = {key: min(step_cost(engines[key], 16, 32,
                                   f"ab{r}-{key[0]}-{key[1]}")
                         for r in range(pairs))
                for key in grid}
        pg = cost[("paged", 512)] / cost[("paged", 128)]
        dg = cost[("dense", 512)] / cost[("dense", 128)]
        # max context grew 4x: paged should be ~1x (sub-linear), dense
        # heads toward 4x (linear in max context)
        out["llm_decode_maxctx_growth_paged_x"] = round(pg, 2)
        out["llm_decode_maxctx_growth_dense_x"] = round(dg, 2)
        out["llm_decode_paged_vs_dense_growth_x"] = round(dg / pg, 2)
        # step-latency-vs-USED-context curve at max_seq_len=512, each
        # impl normalized to its own shortest-context point: paged
        # follows used pages, dense sits at full-context cost from the
        # first token
        curve = {}
        for impl in ("paged", "dense"):
            pts = {plen: min(step_cost(engines[(impl, 512)], plen, 8,
                                       f"cv{r}-{impl}-{plen}")
                             for r in range(pairs))
                   for plen in (16, 64, 160, 320)}
            base = pts[16]
            curve[impl] = {str(k): round(v / base, 2)
                           for k, v in pts.items()}
        out["llm_decode_step_vs_ctx_paged_x"] = curve["paged"]
        out["llm_decode_step_vs_ctx_dense_x"] = curve["dense"]
    finally:
        try:
            serve.shutdown_http()
        except Exception:
            pass
        for name in ("llm_cb", "llm_sb", "llm_px", "llm_npx"):
            try:
                serve.delete(name)
            except Exception:
                pass
    return out

def bench_dag(ray_tpu, pairs=2, n=400, depth=8):
    """Compiled-graph phases: a 3-stage actor chain executed through the
    channel-compiled path (pinned actor loops over mutable shm channels,
    zero per-call task submission) vs the dynamic CompiledDAG baseline
    (real task submission per stage per execute), alternating pairs and
    reporting BEST-OF per the slow-box protocol.  The contract is
    `dag_vs_dynamic` >= 5x.  `dag_execute_p99_ms` comes from serial
    execute+get round trips on the compiled path."""
    from collections import deque

    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Stage:
        def step(self, x):
            return x + 1

    def build():
        with InputNode() as inp:
            out = inp
            for _ in range(3):
                out = Stage.bind().step.bind(out)
        return out

    def measure(use_channels):
        c = build().experimental_compile(max_in_flight=depth,
                                         use_channels=use_channels)
        get = (lambda ref: ref.get(timeout=60)) if use_channels \
            else (lambda ref: ray_tpu.get(ref, timeout=60))
        try:
            for _ in range(20):  # warm: leases/loops + channel attach
                get(c.execute(0))
            window = deque()  # keep `depth` executes in flight
            t0 = time.perf_counter()
            for i in range(n):
                if len(window) >= depth:
                    get(window.popleft())
                window.append(c.execute(i))
            while window:
                get(window.popleft())
            rate = n / (time.perf_counter() - t0)
            lats = []
            for i in range(200):
                t1 = time.perf_counter()
                get(c.execute(i))
                lats.append(time.perf_counter() - t1)
            lats.sort()
            p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))] * 1000.0
            return rate, p99
        finally:
            c.teardown()

    comp_rates, dyn_rates, comp_p99 = [], [], []
    for _ in range(pairs):
        for use_channels in (False, True):
            rate, p99 = measure(use_channels)
            if use_channels:
                comp_rates.append(rate)
                comp_p99.append(p99)
            else:
                dyn_rates.append(rate)
    best, base = max(comp_rates), max(dyn_rates)
    return {
        "dag_execute_per_s": round(best, 1),
        "dag_execute_dynamic_per_s": round(base, 1),
        "dag_vs_dynamic": round(best / base, 2),
        "dag_execute_p99_ms": round(min(comp_p99), 3),
    }

def bench_small_ops(ray_tpu, n=1000):
    """Small-object put/get ops/s (reference: ray_perf.py:120-122,
    'single client get/put' — 10,181.6 / 5,545.0 ops/s recorded)."""
    payload = b"x" * 100
    t0 = time.perf_counter()
    refs = [ray_tpu.put(payload) for _ in range(n)]
    put_rate = n / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    for r in refs:
        ray_tpu.get(r, timeout=60)
    get_rate = n / (time.perf_counter() - t0)
    return put_rate, get_rate

def bench_pg_churn(ray_tpu, n=40):
    """Placement group create+remove rate (reference:
    microbenchmark.json 'placement group create/removal' 796.6/s)."""
    from ray_tpu.util import placement_group, remove_placement_group

    t0 = time.perf_counter()
    for _ in range(n):
        pg = placement_group([{"CPU": 1}])
        pg.wait(timeout=30)
        remove_placement_group(pg)
    return n / (time.perf_counter() - t0)

def bench_put_gbps(ray_tpu, mb=100, iters=5):
    import numpy as np

    data = np.random.rand(mb * 1024 * 1024 // 8)
    refs = []
    t0 = time.perf_counter()
    for _ in range(iters):
        refs.append(ray_tpu.put(data))
    dt = time.perf_counter() - t0
    del refs
    return iters * mb / 1024 / dt

def bench_xfer(pairs=2, mb=256):
    """Bulk object-plane phase: two in-process node agents (plus a head)
    on one event loop; a `mb`-MB object is pulled cross-agent via the
    bulk transfer plane vs the legacy obj_chunk RPC path, alternating
    rpc/bulk pairs and reporting BEST-OF per the slow-box protocol (the
    ratio is the contract: bulk must be >= 3x the RPC baseline)."""
    import asyncio

    from ray_tpu._private.head import HeadService
    from ray_tpu._private.node_agent import NodeAgent

    size = mb * 1024 * 1024
    session = os.path.join("/tmp", f"rt-xferbench-{os.getpid()}")
    os.makedirs(session, exist_ok=True)
    payload = os.urandom(size)
    saved = os.environ.get("RT_OBJECT_TRANSFER_ENABLED")

    async def run():
        head = HeadService()
        head_port = await head.start()
        agents = []
        for i in range(2):
            ag = NodeAgent(("127.0.0.1", head_port), session, {"CPU": 1},
                           arena_path=os.path.join(session, f"arena-{i}"),
                           capacity=size + (64 << 20))
            await ag.start()
            agents.append(ag)
        a, b = agents
        rates = {"bulk": [], "rpc": []}
        try:
            for i in range(pairs):
                for plane in ("rpc", "bulk"):
                    os.environ["RT_OBJECT_TRANSFER_ENABLED"] = \
                        "true" if plane == "bulk" else "false"
                    oid = f"bench-{plane}-{i}"
                    loc = a.store.create(oid, size)
                    a.store.arena.view[
                        loc["offset"]:loc["offset"] + size] = payload
                    a.store.seal(oid)
                    t0 = time.perf_counter()
                    r = await asyncio.wait_for(
                        b.rpc_ensure_local(oid, src=[a.host, a.port]),
                        timeout=300)
                    dt = time.perf_counter() - t0
                    if not r.get("ok"):
                        raise RuntimeError(f"{plane} pull failed: {r}")
                    rates[plane].append(size / dt / 1e9)
                    # the puller's unpin is a oneway still in flight:
                    # wait it out so the freed arena space is reusable
                    # by the next round's create
                    for _ in range(200):
                        e = a.store.objects.get(oid)
                        if e is None or not e.pinned:
                            break
                        await asyncio.sleep(0.02)
                    b.store.free([oid])
                    a.store.free([oid])
        finally:
            for ag in agents:
                await ag.stop()
            await head.stop()
        return rates

    try:
        rates = asyncio.run(run())
    finally:
        if saved is None:
            os.environ.pop("RT_OBJECT_TRANSFER_ENABLED", None)
        else:
            os.environ["RT_OBJECT_TRANSFER_ENABLED"] = saved
    bulk, rpc = max(rates["bulk"]), max(rates["rpc"])
    return {
        "xfer_gb_per_s": round(bulk, 3),
        "xfer_rpc_baseline_gb_per_s": round(rpc, 3),
        "xfer_vs_rpc": round(bulk / rpc, 2),
    }

def _locality_bench(n=10):
    """Runs as a subprocess: 2-worker-node cluster, scatter `n` 2MB
    objects across them, then unconstrained gather tasks — reports the
    fraction routed to their argument's holder (and that held args were
    never transferred)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, resources={"s0": 1})
    cluster.add_node(num_cpus=2, resources={"s1": 1})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(3)
        import numpy as np

        @ray_tpu.remote
        def produce():
            import os as _os

            return _os.environ["RT_NODE_ID"], np.ones(
                300_000, dtype=np.float64)  # 2.4MB: plasma + directory

        @ray_tpu.remote
        def consume(pair):
            import os as _os

            holder, arr = pair
            return _os.environ["RT_NODE_ID"] == holder and arr.sum() > 0

        # scatter: pin producers alternately to the two worker nodes
        refs = []
        for i in range(n):
            shard = f"s{i % 2}"
            refs.append(produce.options(resources={shard: 0.01}).remote())
        ray_tpu.wait(refs, num_returns=len(refs), timeout=60)
        # gather: unconstrained consumers — locality should route each
        # to its argument's holder
        hits = ray_tpu.get([consume.remote(r) for r in refs], timeout=60)
        pct = 100.0 * sum(bool(h) for h in hits) / len(hits)
        print("LOCJSON " + json.dumps({"locality_hit_pct": round(pct, 1)}))
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()

def bench_locality_subprocess():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--locality-bench"],
        capture_output=True, text=True, timeout=240, cwd=REPO)
    for line in proc.stdout.splitlines():
        if line.startswith("LOCJSON "):
            return json.loads(line[len("LOCJSON "):])
    raise RuntimeError(
        f"locality bench rc={proc.returncode}: {proc.stderr[-400:]}")

def _chaos_bench(total_s=9.0, kill_at_s=2.5, conns=8):
    """Runs as a subprocess: 2 worker agents + a head node, steady Serve
    HTTP load, one agent SIGKILLed mid-run.  Reports availability (non-
    503/non-error success over the WHOLE run), post-kill p99 latency
    (the recovery tail), and how long the controller took to re-heal the
    replica set."""
    import asyncio
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 4})
    workers = [cluster.add_node(num_cpus=0, resources={"chaos": 2})
               for _ in range(2)]
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(3)

        # replicas can only land on the two chaos nodes (the head node
        # has no "chaos" resource); SPREAD puts one on each
        @serve.deployment(name="chaos_echo", num_replicas=2,
                          max_ongoing_requests=32,
                          ray_actor_options={
                              "num_cpus": 0, "resources": {"chaos": 1},
                              "scheduling_strategy": "SPREAD"})
        def chaos_echo(x):
            return {"ok": 1}

        serve.run(chaos_echo.bind())
        host, port = serve.start_http()
        _serve_http_get(host, port, 4, 40, "/chaos_echo?x=1")  # warm

        # which agent hosts a replica? (kill one that actually does)
        actors = ray_tpu.api._worker().head.call("list_actors",
                                                 timeout=30)["actors"]
        replica_nodes = {a["node_id"] for a in actors
                         if a.get("name", "").startswith("serve:chaos_echo")}
        victim = next(w for w in workers if w.node_id in replica_nodes)

        results = []  # (t_start_rel, ok, latency_s)
        t0 = time.perf_counter()
        kill_done = [0.0]
        reheal_done = [0.0]

        def alive_replicas():
            actors = ray_tpu.api._worker().head.call("list_actors",
                                                     timeout=10)["actors"]
            return sum(1 for a in actors
                       if a.get("name", "").startswith("serve:chaos_echo")
                       and a["state"] == "ALIVE")

        def killer():
            time.sleep(kill_at_s)
            cluster.remove_node(victim)  # SIGKILL; workers die via PDEATHSIG
            kill_done[0] = time.perf_counter() - t0
            # re-heal is measured from ACTOR state at the head (the dead
            # replica goes DEAD the moment the node dies, the replacement
            # goes ALIVE when its constructor passes) — NOT from the
            # controller's replica-handle list, which swaps the dead
            # handle for the replacement in one reconcile round and so
            # never observably drops below 2
            dropped = False
            while time.perf_counter() - t0 < total_s + 20:
                try:
                    n = alive_replicas()
                    if not dropped and n < 2:
                        dropped = True
                    elif dropped and n >= 2:
                        reheal_done[0] = time.perf_counter() - t0
                        return
                except Exception:
                    pass
                time.sleep(0.1)

        async def client():
            req = (b"GET /chaos_echo?x=1 HTTP/1.1\r\nHost: bench\r\n\r\n")
            # reconnect-and-keep-counting: a severed connection records a
            # failure and the client RESUMES, so availability really is
            # measured over the whole run (a client that stopped at the
            # first break would freeze the denominator at kill time)
            while time.perf_counter() - t0 < total_s:
                try:
                    reader, writer = await asyncio.open_connection(host,
                                                                   port)
                except OSError:
                    results.append((time.perf_counter() - t0, False, 0.0))
                    await asyncio.sleep(0.05)
                    continue
                try:
                    while time.perf_counter() - t0 < total_s:
                        ts = time.perf_counter()
                        writer.write(req)
                        await writer.drain()
                        status = await reader.readline()
                        if not status:
                            # clean EOF: ONE failure for the break, then
                            # reconnect (writing to the dead socket would
                            # double-count it via the OSError path)
                            results.append((ts - t0, False, 0.0))
                            break
                        clen = 0
                        while True:
                            h = await reader.readline()
                            if h in (b"\r\n", b"\n", b""):
                                break
                            if h.lower().startswith(b"content-length:"):
                                clen = int(h.split(b":", 1)[1])
                        if clen:
                            await reader.readexactly(clen)
                        dt = time.perf_counter() - ts
                        results.append((ts - t0, b"200" in status, dt))
                except (OSError, asyncio.IncompleteReadError):
                    results.append((time.perf_counter() - t0, False, 0.0))
                finally:
                    try:
                        writer.close()
                    except Exception:
                        pass

        async def drive():
            await asyncio.wait_for(
                asyncio.gather(*[client() for _ in range(conns)],
                               return_exceptions=True),
                timeout=total_s + 60)

        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        asyncio.run(drive())
        kt.join(timeout=30)
        total = len(results)
        ok = sum(1 for _, good, _ in results if good)
        post_kill = sorted(dt for ts, good, dt in results
                           if good and ts >= kill_done[0] > 0)
        p99 = post_kill[min(len(post_kill) - 1,
                            int(0.99 * len(post_kill)))] if post_kill else 0.0
        out = {
            "chaos_requests_total": total,
            "chaos_availability_pct": round(100.0 * ok / max(total, 1), 2),
            "chaos_p99_recovery_s": round(p99, 4),
            "chaos_reheal_s": round(
                max(0.0, reheal_done[0] - kill_done[0]), 2)
            if reheal_done[0] else -1.0,
        }
        print("CHAOSJSON " + json.dumps(out))
    finally:
        try:
            serve.shutdown_http()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def _tail_bench(baseline_s=2.5, stall_s=3.0, post_s=6.0, conns=8):
    """Runs as a subprocess: 2 Serve replicas of an IDEMPOTENT echo
    deployment with p99-hedging, steady HTTP load, and one replica's
    worker chaos-STALLED (busy-hung, not killed — the gray failure)
    mid-run via the worker.stall site.  Contract: p99 over the stalled
    window stays within 2x the all-healthy baseline and ZERO requests
    fail — hedged duplicates absorb the requests that hit the gray
    replica and its circuit breaker evicts it from routing within a few
    hedge delays, instead of 3 health-probe periods."""
    import asyncio

    import ray_tpu
    from ray_tpu import serve

    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        class TailEcho:
            def __call__(self, x):
                return {"ok": 1}

            def wid(self):
                from ray_tpu._private.worker import global_worker_or_none

                return global_worker_or_none().worker_id

        serve.run(serve.deployment(
            TailEcho, name="tail_echo", num_replicas=2,
            max_ongoing_requests=32, idempotent=True,
            hedge_after_s="p99").bind())
        host, port = serve.start_http()
        _serve_http_get(host, port, 4, 50, "/tail_echo?x=1")  # warm

        w = ray_tpu.api._worker()
        replicas = [a for a in w.head.call("list_actors",
                                           timeout=30)["actors"]
                    if a.get("name", "").startswith("serve:tail_echo")
                    and a["state"] == "ALIVE"]
        victim_wid = ray_tpu.get(ray_tpu.get_actor(
            replicas[0]["name"]).handle_request.remote("wid", (), {}),
            timeout=30)

        results = []  # (t_rel, ok, latency_s)
        t0 = time.perf_counter()
        stall_at = [0.0]
        total_s = baseline_s + post_s

        async def injector():
            await asyncio.sleep(baseline_s)
            stall_at[0] = time.perf_counter() - t0
            w.head.call("chaos", op="inject",
                        rule={"site": "worker.stall", "action": "stall",
                              "target": victim_wid, "count": 1,
                              "delay_s": stall_s}, timeout=30)

        async def client():
            req = b"GET /tail_echo?x=1 HTTP/1.1\r\nHost: bench\r\n\r\n"
            while time.perf_counter() - t0 < total_s:
                try:
                    reader, writer = await asyncio.open_connection(host,
                                                                   port)
                except OSError:
                    results.append((time.perf_counter() - t0, False, 0.0))
                    await asyncio.sleep(0.05)
                    continue
                try:
                    while time.perf_counter() - t0 < total_s:
                        ts = time.perf_counter()
                        writer.write(req)
                        await writer.drain()
                        status = await reader.readline()
                        if not status:
                            results.append((ts - t0, False, 0.0))
                            break
                        clen = 0
                        while True:
                            h = await reader.readline()
                            if h in (b"\r\n", b"\n", b""):
                                break
                            if h.lower().startswith(b"content-length:"):
                                clen = int(h.split(b":", 1)[1])
                        if clen:
                            await reader.readexactly(clen)
                        dt = time.perf_counter() - ts
                        results.append((ts - t0, b"200" in status, dt))
                except (OSError, asyncio.IncompleteReadError):
                    results.append((time.perf_counter() - t0, False, 0.0))
                finally:
                    try:
                        writer.close()
                    except Exception:
                        pass

        async def drive():
            await asyncio.wait_for(
                asyncio.gather(injector(),
                               *[client() for _ in range(conns)],
                               return_exceptions=True),
                timeout=total_s + 60)

        asyncio.run(drive())
        # the contract is only meaningful if the stall actually fired:
        # a failed injection would measure healthy traffic twice and
        # report a vacuous pass.  Fired counts ride agent heartbeats to
        # the head (~3s period) — wait one out.
        deadline = time.perf_counter() + 15
        fired = 0
        while time.perf_counter() < deadline and not fired:
            st = w.head.call("chaos", op="status", timeout=30)
            fired = sum(int(r.get("fired", 0)) for r in st["rules"])
            if not fired:
                time.sleep(0.5)
        if not fired:
            raise RuntimeError("worker.stall rule never fired; the "
                               "tail numbers would be vacuous")

        def p99(vals):
            if not vals:
                return 0.0
            vals = sorted(vals)
            return vals[min(len(vals) - 1, int(0.99 * len(vals)))]

        # healthy = COMPLETED before the stall landed: a request still
        # in flight when the stall hit would smuggle multi-second
        # latencies into the baseline and make the <=2x ratio vacuous
        healthy = [dt for ts, ok, dt in results
                   if ok and stall_at[0] > 0 and ts + dt < stall_at[0]]
        stalled = [dt for ts, ok, dt in results
                   if ok and ts >= stall_at[0] > 0]
        failed = sum(1 for _ts, ok, _dt in results if not ok)
        base_p99, stall_p99 = p99(healthy), p99(stalled)
        out = {
            "tail_requests_total": len(results),
            "tail_failed_requests": failed,
            "tail_p99_healthy_ms": round(base_p99 * 1000, 2),
            "tail_p99_stalled_ms": round(stall_p99 * 1000, 2),
            # the acceptance ratio: <= 2.0 with zero failures means the
            # hedge + circuit breaker absorbed the gray replica
            "tail_p99_ratio": round(stall_p99 / max(base_p99, 1e-9), 2),
        }
        print("TAILJSON " + json.dumps(out))
    finally:
        try:
            serve.shutdown_http()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass


def bench_tail_subprocess():
    """Launch the tail-tolerance phase in a plugin-free CPU subprocess
    (its own in-process cluster; the chaos stall must never touch the
    main bench cluster's workers)."""
    from __graft_entry__ import _clean_subprocess_env

    env = _clean_subprocess_env(1)
    proc = subprocess.run(
        [sys.executable, "-S", os.path.join(REPO, "bench.py"),
         "--tail-bench"], env=env, capture_output=True, text=True,
        timeout=300, cwd=REPO)
    for line in proc.stdout.splitlines():
        if line.startswith("TAILJSON "):
            return json.loads(line[len("TAILJSON "):])
    raise RuntimeError(
        f"tail bench rc={proc.returncode}: {proc.stderr[-400:]}")


def _autoscale_bench(total_s=18.0, conns=16):
    """Runs as a subprocess: a 1-node AutoscalingCluster (head only),
    Serve deployment with num_replicas="auto" whose replicas can only
    land on autoscaled worker nodes, ramped HTTP load.  The replica
    autoscaler scales on ongoing requests, replica infeasibility parks
    as PENDING-actor demand, the node autoscaler launches workers to
    resolve it, and when the load stops the fleet drains back through
    the graceful-drain state machine.  Reports availability over the
    WHOLE run (incl. both scale events), p99 latency, and the
    scale-up / drain latencies."""
    import asyncio
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import AutoscalingCluster

    cluster = AutoscalingCluster(
        head_resources={"CPU": 2},
        worker_node_types={
            "serve-worker": {"resources": {"CPU": 2}, "min_workers": 0,
                             "max_workers": 3}},
        idle_timeout_s=1.5, update_period_s=0.3)
    ray_tpu.init(address=cluster.address)
    try:
        @serve.deployment(name="auto_echo", num_replicas="auto",
                          max_ongoing_requests=32,
                          autoscaling_config={
                              "min_replicas": 1, "max_replicas": 3,
                              "target_ongoing_requests": 2,
                              "upscale_consecutive": 2,
                              # longer than any mid-load ongoing dip:
                              # the drain event the phase measures is
                              # the one AFTER the load stops
                              "downscale_delay_s": 8.0},
                          ray_actor_options={"num_cpus": 2})
        def auto_echo(x):
            time.sleep(0.02)  # enough service time to sustain ongoing
            return {"ok": 1}

        serve.run(auto_echo.bind())  # first replica = first node launch
        host, port = serve.start_http()
        _serve_http_get(host, port, 2, 20, "/auto_echo?x=1")  # warm

        results = []  # (t_rel, ok, latency_s)
        t0 = time.perf_counter()
        scale_up_done = [0.0]
        drain_done = [0.0]
        peak_nodes = [0]
        baseline_nodes = len(cluster.provider.non_terminated_nodes())

        def watcher():
            # scale-up latency: load start -> a SECOND worker node live;
            # drain latency: load stop -> fleet back at one node
            while time.perf_counter() - t0 < total_s + 90:
                n = len(cluster.provider.non_terminated_nodes())
                peak_nodes[0] = max(peak_nodes[0], n)
                tr = time.perf_counter() - t0
                if not scale_up_done[0] and n > baseline_nodes:
                    scale_up_done[0] = tr
                if tr > total_s and scale_up_done[0] \
                        and n <= baseline_nodes:
                    drain_done[0] = tr
                    return
                time.sleep(0.1)

        async def client():
            req = b"GET /auto_echo?x=1 HTTP/1.1\r\nHost: bench\r\n\r\n"
            while time.perf_counter() - t0 < total_s:
                try:
                    reader, writer = await asyncio.open_connection(host,
                                                                   port)
                except OSError:
                    results.append((time.perf_counter() - t0, False, 0.0))
                    await asyncio.sleep(0.05)
                    continue
                try:
                    while time.perf_counter() - t0 < total_s:
                        ts = time.perf_counter()
                        writer.write(req)
                        await writer.drain()
                        status = await reader.readline()
                        if not status:
                            results.append((ts - t0, False, 0.0))
                            break
                        clen = 0
                        while True:
                            h = await reader.readline()
                            if h in (b"\r\n", b"\n", b""):
                                break
                            if h.lower().startswith(b"content-length:"):
                                clen = int(h.split(b":", 1)[1])
                        if clen:
                            await reader.readexactly(clen)
                        results.append(
                            (ts - t0, b"200" in status,
                             time.perf_counter() - ts))
                except (OSError, asyncio.IncompleteReadError):
                    results.append((time.perf_counter() - t0, False, 0.0))
                finally:
                    try:
                        writer.close()
                    except Exception:
                        pass

        async def drive():
            await asyncio.wait_for(
                asyncio.gather(*[client() for _ in range(conns)],
                               return_exceptions=True),
                timeout=total_s + 60)

        wt = threading.Thread(target=watcher, daemon=True)
        wt.start()
        asyncio.run(drive())
        wt.join(timeout=120)
        total = len(results)
        ok = sum(1 for _, good, _ in results if good)
        lats = sorted(dt for _, good, dt in results if good and dt > 0)
        p99 = lats[min(len(lats) - 1, int(0.99 * len(lats)))] \
            if lats else 0.0
        out = {
            "autoscale_requests_total": total,
            "autoscale_availability_pct": round(
                100.0 * ok / max(total, 1), 2),
            "autoscale_p99_ms": round(p99 * 1000, 2),
            "scale_up_latency_s": round(scale_up_done[0], 2)
            if scale_up_done[0] else -1.0,
            "drain_latency_s": round(drain_done[0] - total_s, 2)
            if drain_done[0] else -1.0,
            # +1: the head node is not provider-managed
            "autoscale_peak_nodes": 1 + peak_nodes[0],
        }
        st = cluster.status()
        out["autoscale_scale_ups"] = st["scale_up_total"]
        out["autoscale_scale_downs"] = st["scale_down_total"]
        print("AUTOSCALEJSON " + json.dumps(out))
    finally:
        try:
            serve.shutdown_http()
        except Exception:
            pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def bench_autoscale_subprocess():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--autoscale-bench"],
        capture_output=True, text=True, timeout=420, cwd=REPO)
    for line in proc.stdout.splitlines():
        if line.startswith("AUTOSCALEJSON "):
            return json.loads(line[len("AUTOSCALEJSON "):])
    raise RuntimeError(
        f"autoscale bench rc={proc.returncode}: {proc.stderr[-400:]}")


def _oom_bench(n_tasks=60, alloc_mb=220, hold_s=0.25):
    """Runs as a subprocess: a head (0 CPUs) + 3 worker agents, each
    under a VIRTUAL 512MB memory envelope
    (memory_monitor_node_total_bytes — per-agent watchdog accounting
    sums only that agent's worker RSS, so several "nodes" on one host
    stay isolated and the real machine is never stressed).  The
    workload overcommits ~2x: two 220MB allocators per 512MB node push
    past the 0.85 threshold, the watchdog kills the ballooning worker
    with a typed receipt, and the owner's separate OOM budget retries
    with jittered backoff until pressure clears.  Contracts: ZERO agent
    deaths (the watchdog fires, never the kernel), >= 99% task success,
    and an always-OOM poison class quarantined within
    poison_task_threshold kills (typed PoisonedTaskError, not worker
    churn)."""
    MB = 1024 * 1024
    threshold = 5
    os.environ.update({
        "RT_MEMORY_MONITOR_NODE_TOTAL_BYTES": str(512 * MB),
        "RT_MEMORY_USAGE_THRESHOLD": "0.85",
        "RT_MEMORY_MONITOR_REFRESH_MS": "50",
        "RT_MEMORY_MONITOR_MIN_KILL_INTERVAL_MS": "150",
        "RT_TASK_OOM_RETRIES": "30",
        "RT_TASK_RETRY_DELAY_MS": "50",
        "RT_TASK_OOM_RETRY_MAX_BACKOFF_MS": "1000",
        "RT_POISON_TASK_THRESHOLD": str(threshold),
        "RT_POISON_TASK_TTL_S": "120",
    })
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 0})
    workers = [cluster.add_node(num_cpus=2) for _ in range(3)]
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(4)

        @ray_tpu.remote(max_retries=0, name="oom_bench_alloc")
        def allocator(i):
            hoard = bytearray(alloc_mb * MB)
            for off in range(0, len(hoard), 4096):
                hoard[off] = 1  # touched pages: real RSS
            time.sleep(hold_s)
            return i

        t0 = time.perf_counter()
        refs = [allocator.remote(i) for i in range(n_tasks)]
        ok = 0
        failures = []
        for i, r in enumerate(refs):
            try:
                assert ray_tpu.get(r, timeout=300) == i
                ok += 1
            except Exception as exc:  # noqa: BLE001
                failures.append(f"{type(exc).__name__}: {exc}"[:120])
        wall = time.perf_counter() - t0

        # poison phase: a class that ALWAYS balloons past the threshold
        # and never finishes — must quarantine within `threshold` kills
        # instead of churning workers forever
        @ray_tpu.remote(max_retries=0, name="oom_bench_poison")
        def poison():
            hoard = bytearray(520 * MB)
            for off in range(0, len(hoard), 4096):
                hoard[off] = 1
            time.sleep(300)
            return len(hoard)

        poisoned_type = ""
        try:
            ray_tpu.get(poison.remote(), timeout=240)
        except Exception as exc:  # noqa: BLE001
            poisoned_type = type(exc).__name__
        head = ray_tpu.api._worker().head
        q = head.call("quarantine", op="list")["entries"]
        poison_entry = next(
            (e for e in q.values() if e["name"] == "oom_bench_poison"), {})
        agents_alive = sum(1 for w in workers if w.alive)
        out = {
            "oom_tasks_total": n_tasks,
            "oom_task_success_pct": round(100.0 * ok / n_tasks, 2),
            "oom_workload_wall_s": round(wall, 1),
            "oom_agents_alive": agents_alive,          # contract: 3
            "oom_poison_error": poisoned_type,         # PoisonedTaskError
            "oom_poison_kills": poison_entry.get("kills", -1),
            "oom_poison_quarantined": bool(
                poison_entry.get("quarantined")),
            "oom_failures": failures[:3],
        }
        print("OOMJSON " + json.dumps(out))
    finally:
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def bench_oom_subprocess():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--oom-bench"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    for line in proc.stdout.splitlines():
        if line.startswith("OOMJSON "):
            return json.loads(line[len("OOMJSON "):])
    raise RuntimeError(
        f"oom bench rc={proc.returncode}: {proc.stderr[-400:]}")


def bench_chaos_subprocess():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--chaos-bench"],
        capture_output=True, text=True, timeout=300, cwd=REPO)
    for line in proc.stdout.splitlines():
        if line.startswith("CHAOSJSON "):
            return json.loads(line[len("CHAOSJSON "):])
    raise RuntimeError(
        f"chaos bench rc={proc.returncode}: {proc.stderr[-400:]}")


def _train_bench_loop(force_cpu=False):
    """Runs in a watchdogged subprocess; prints one JSON line."""
    import dataclasses

    import jax

    platform = jax.devices()[0].platform
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh, shard_batch
    from ray_tpu.train.gspmd import build_llama_train_state, param_count

    if platform == "tpu" and not force_cpu:
        # ~600M params fills the v5e MXU; remat leaves HBM headroom
        cfg = dataclasses.replace(LlamaConfig.bench_1b(), remat=True)
        batch, seq, steps = 8, 1024, 20
    else:
        cfg, batch, seq, steps = LlamaConfig.tiny(), 4, 128, 5
    mesh = make_mesh(MeshSpec(dp=-1), devices=jax.devices()[:1])
    params, opt, step_fn, _ = build_llama_train_state(
        cfg, mesh, batch_size=batch, seq_len=seq)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                cfg.vocab_size, dtype="int32")
    tokens = shard_batch(mesh, tokens)  # place once, outside the loop
    for _ in range(3):  # compile + settle donation aliasing
        params, opt, loss = step_fn(params, opt, tokens)
    float(loss)  # hard sync (block_until_ready is lazy over the tunnel)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = step_fn(params, opt, tokens)
    float(loss)
    dt = time.perf_counter() - t0
    tokens_per_s = steps * batch * seq / dt
    n_params = param_count(params)
    # MFU: 6 * params * tokens/s over peak flops (v5e: 197e12 bf16)
    peak = 197e12 if platform == "tpu" else 0
    mfu = (6 * n_params * tokens_per_s / peak) if peak else 0.0
    print("TRAINJSON " + json.dumps(
        {"platform": platform, "train_tokens_per_s": round(tokens_per_s, 1),
         "params": n_params, "mfu_pct": round(100 * mfu, 2),
         "loss": float(loss)}))

def _pipeline_bench_loop():
    """MPMD pipeline bench body: runs in a plugin-free CPU subprocess
    (its own in-process cluster + 2 stage actors), prints one JSON line.

    Best-of alternating pairs per the slow-box protocol: each round
    measures the single-program baseline THEN the 2-stage pipeline on
    the same global batch, so drift hits both sides equally.  Reports
    steady-state pp_tokens_per_s / pp_step_p99_ms / pipeline_bubble_pct
    and the single-program rate for the honest comparison (on one host
    the pipeline adds channel hops for no extra compute, so the ratio
    gauges overhead; on real multi-chip topologies pp multiplies the
    in-stage mesh instead)."""
    import numpy as np

    import ray_tpu
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.train.pipeline import TrainPipeline

    cfg = LlamaConfig.tiny()
    mb, m, seq, steps, pairs = 2, 4, 64, 8, 2
    B = mb * m
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, size=(B, seq),
                          dtype=np.int32)

    def measure_sp():
        import jax

        from ray_tpu.parallel.mesh import MeshSpec, make_mesh, shard_batch
        from ray_tpu.train.gspmd import build_llama_train_state

        mesh = make_mesh(MeshSpec(dp=-1), devices=jax.devices()[:1])
        params, opt, step_fn, _ = build_llama_train_state(
            cfg, mesh, batch_size=B, seq_len=seq)
        toks = shard_batch(mesh, tokens)
        for _ in range(3):
            params, opt, loss = step_fn(params, opt, toks)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(steps):
            params, opt, loss = step_fn(params, opt, toks)
        float(loss)
        return steps * B * seq / (time.perf_counter() - t0)

    def measure_pp():
        pipe = TrainPipeline(cfg, pp=2, microbatch_size=mb,
                             num_microbatches=m, seq_len=seq,
                             devices_per_stage=1, step_timeout=120.0)
        try:
            for _ in range(3):  # warm: stage jits + channel attach
                pipe.step(tokens)
            walls, bubbles = [], []
            for _ in range(steps):
                out = pipe.step(tokens)
                walls.append(out["wall_s"])
                bubbles.append(out["bubble_pct"])
            rate = steps * B * seq / sum(walls)
            return rate, walls, bubbles
        finally:
            pipe.teardown()

    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        sp_rates, pp_rates = [], []
        all_walls, best_bubbles = [], []
        for _ in range(pairs):
            sp_rates.append(measure_sp())
            rate, walls, bubbles = measure_pp()
            if not pp_rates or rate > max(pp_rates):
                best_bubbles = bubbles
            pp_rates.append(rate)
            all_walls.extend(walls)
        all_walls.sort()
        p99 = all_walls[min(len(all_walls) - 1,
                            int(0.99 * len(all_walls)))] * 1000.0
        print("PIPEJSON " + json.dumps({
            "pp_tokens_per_s": round(max(pp_rates), 1),
            "pp_step_p99_ms": round(p99, 2),
            "pipeline_bubble_pct": round(
                sorted(best_bubbles)[len(best_bubbles) // 2], 2),
            "pp_single_program_tokens_per_s": round(max(sp_rates), 1),
        }))
    finally:
        ray_tpu.shutdown()


def bench_pipeline_subprocess():
    """Launch the pipeline bench in a plugin-free CPU interpreter (the
    pp stages are actor subprocesses of ITS cluster, so the phase is
    tier-1-safe on CPU and never contends for the chip)."""
    from __graft_entry__ import _clean_subprocess_env

    env = _clean_subprocess_env(8)
    proc = subprocess.run(
        [sys.executable, "-S", os.path.join(REPO, "bench.py"),
         "--pipeline-bench"], env=env, capture_output=True, text=True,
        timeout=480, cwd=REPO)
    for line in proc.stdout.splitlines():
        if line.startswith("PIPEJSON "):
            return json.loads(line[len("PIPEJSON "):])
    raise RuntimeError(
        f"pipeline bench rc={proc.returncode}: {proc.stderr[-400:]}")


def _run_train_subprocess(extras, errors):
    """TPU attempt under a hard deadline, then plugin-free CPU fallback."""
    from __graft_entry__ import _clean_subprocess_env

    def attempt(cmd, env, deadline):
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=deadline, cwd=REPO)
        for line in proc.stdout.splitlines():
            if line.startswith("TRAINJSON "):
                return json.loads(line[len("TRAINJSON "):])
        raise RuntimeError(
            f"train bench rc={proc.returncode}: {proc.stderr[-400:]}")

    try:
        # normal interpreter: sitecustomize registers the TPU plugin
        extras.update(attempt([sys.executable, os.path.join(REPO, "bench.py"),
                               "--train-bench"], dict(os.environ), 480))
        return
    except Exception as exc:  # noqa: BLE001 — timeout, crash, no chip
        errors["train_tpu"] = f"{type(exc).__name__}: {exc}"[:300]
    try:
        env = _clean_subprocess_env(1)
        extras.update(attempt(
            [sys.executable, "-S", os.path.join(REPO, "bench.py"),
             "--train-bench", "--cpu"], env, 240))
    except Exception as exc:  # noqa: BLE001
        errors["train_cpu"] = f"{type(exc).__name__}: {exc}"[:300]

def main():
    sys.path.insert(0, REPO)
    import ray_tpu

    extras = {}
    errors = {}
    sync = 0.0

    def phase(name, fn):
        try:
            fn()
        except Exception as exc:  # noqa: BLE001
            errors[name] = f"{type(exc).__name__}: {exc}"[:300]

    started = False
    try:
        ray_tpu.init(num_cpus=max(4, os.cpu_count() or 4),
                     object_store_memory=1024 * 1024 * 1024)
        started = True
    except Exception as exc:  # noqa: BLE001
        errors["init"] = f"{type(exc).__name__}: {exc}"[:300]

    if started:
        def tasks_sync():
            nonlocal sync
            sync = bench_tasks_sync(ray_tpu)

        phase("tasks_sync", tasks_sync)
        phase("tasks_async", lambda: extras.__setitem__(
            "tasks_async_per_s", round(bench_tasks_async(ray_tpu), 1)))

        def actors():
            a_sync, a_async = bench_actor(ray_tpu)
            extras["actor_sync_per_s"] = round(a_sync, 1)
            extras["actor_async_per_s"] = round(a_async, 1)

        phase("actors", actors)

        def small_ops():
            p, g = bench_small_ops(ray_tpu)
            extras["put_small_per_s"] = round(p, 1)
            extras["get_small_per_s"] = round(g, 1)

        phase("small_ops", small_ops)
        phase("pg_churn", lambda: extras.__setitem__(
            "pg_create_remove_per_s", round(bench_pg_churn(ray_tpu), 1)))
        phase("put", lambda: extras.__setitem__(
            "put_gb_per_s", round(bench_put_gbps(ray_tpu), 2)))
        phase("dag", lambda: extras.update(bench_dag(ray_tpu)))
        # burst-sequence + multi-client phases LAST among task phases:
        # the sync burst is deliberate history pollution, and proving the
        # earlier numbers unaffected by ordering is part of the contract
        phase("trace_overhead", lambda: extras.update(
            bench_trace_overhead(ray_tpu)))
        phase("profile_overhead", lambda: extras.update(
            bench_profile_overhead(ray_tpu)))
        phase("memory_scan_overhead", lambda: extras.update(
            bench_memory_scan_overhead(ray_tpu)))
        phase("burst_async", lambda: extras.__setitem__(
            "burst_async_per_s", round(bench_burst_then_async(ray_tpu), 1)))
        phase("head_scaling", lambda: extras.update(
            bench_head_scaling(ray_tpu)))
        # single-client async AFTER the multi-client storm: residue from
        # eight drivers' worth of leases/events must not depress a fresh
        # burst (the multi-client cousin of burst_async_per_s)
        phase("post_scaleout_async", lambda: extras.__setitem__(
            "post_scaleout_async_per_s",
            round(bench_tasks_async(ray_tpu), 1)))
        # serve phases after the task phases: a serve regression (proxy
        # wedge, deploy failure) can never zero out the numbers above —
        # phase() catches it and the internal asyncio drivers carry
        # their own hard timeouts
        phase("serve", lambda: extras.update(bench_serve(ray_tpu)))
        # LLM serving tier LAST among in-cluster phases: its replicas
        # hold resident KV pools + hundreds of exec threads, and the
        # phase() guard keeps any serving wedge from zeroing the rest
        phase("llm_serve", lambda: extras.update(bench_llm_serve(ray_tpu)))
        try:
            ray_tpu.shutdown()
        except Exception as exc:  # noqa: BLE001
            errors["shutdown"] = f"{type(exc).__name__}: {exc}"[:300]

    # head scale-out A/B control: the same 2/8-client ladder against a
    # single-loop head (head_ingest_shards=0) in its own subprocess
    # cluster, after shutdown so both sides of the comparison owned the
    # whole box; the sharded side is the head_scaling phase above
    phase("head_scaling_single_loop", lambda: extras.update(
        bench_head_scaling_single_loop_ab()))
    if extras.get("scaling_efficiency_pct_single_loop"):
        extras["scaling_efficiency_vs_single_loop_x"] = round(
            extras.get("scaling_efficiency_pct", 0.0)
            / extras["scaling_efficiency_pct_single_loop"], 2)

    # post-shutdown phases: the object-plane pair runs its own
    # in-process agents and the locality workload its own subprocess
    # cluster — neither shares state with the main cluster above
    phase("xfer", lambda: extras.update(bench_xfer()))
    phase("locality", lambda: extras.update(bench_locality_subprocess()))
    # chaos_recovery: SIGKILL one of two agents under steady Serve load;
    # contract: chaos_availability_pct >= 99 (handle-level dead-replica
    # retry keeps clients whole while the controller re-heals)
    phase("chaos_recovery", lambda: extras.update(bench_chaos_subprocess()))
    # tail_tolerance: chaos-stall one of two Serve replicas under load;
    # contract: tail_p99_ratio <= 2.0 (stalled-window p99 vs healthy
    # baseline) with tail_failed_requests == 0 — hedging + the circuit
    # breaker absorb the gray replica
    phase("tail_tolerance", lambda: extras.update(bench_tail_subprocess()))
    # autoscale: ramp Serve HTTP load against a 1-node autoscaling
    # cluster; contract: autoscale_availability_pct >= 99 through both
    # the scale-up and the drain-based scale-down event
    phase("autoscale", lambda: extras.update(bench_autoscale_subprocess()))
    # oom_resilience: 3 virtual-envelope nodes, a workload overcommitting
    # node memory ~2x; contracts: zero agent deaths (watchdog kills, not
    # the kernel), >= 99% task success via the separate OOM retry
    # budget, and a poison class quarantined within
    # poison_task_threshold kills with a typed error
    phase("oom_resilience", lambda: extras.update(bench_oom_subprocess()))

    # pipeline phase: CPU-only subprocess cluster (2 MPMD stages over
    # channels vs the single-program baseline, best-of alternating pairs)
    phase("pipeline", lambda: extras.update(bench_pipeline_subprocess()))

    # train runs AFTER shutdown so the chip is free for the subprocess
    _run_train_subprocess(extras, errors)

    if errors:
        extras["errors"] = errors
    print(json.dumps({
        "metric": "single-client sync tasks/s (ray_perf.py:174 equivalent)",
        "value": round(sync, 1),
        "unit": "tasks/s",
        "vs_baseline": round(sync / 1006.9, 3),
        "extras": extras,
    }))

if __name__ == "__main__":
    if "--train-bench" in sys.argv:
        _train_bench_loop(force_cpu="--cpu" in sys.argv)
    elif "--pipeline-bench" in sys.argv:
        sys.path.insert(0, REPO)
        _pipeline_bench_loop()
    elif "--locality-bench" in sys.argv:
        sys.path.insert(0, REPO)
        _locality_bench()
    elif "--chaos-bench" in sys.argv:
        sys.path.insert(0, REPO)
        _chaos_bench()
    elif "--tail-bench" in sys.argv:
        sys.path.insert(0, REPO)
        _tail_bench()
    elif "--autoscale-bench" in sys.argv:
        sys.path.insert(0, REPO)
        _autoscale_bench()
    elif "--oom-bench" in sys.argv:
        sys.path.insert(0, REPO)
        _oom_bench()
    elif "--head-scaling-bench" in sys.argv:
        sys.path.insert(0, REPO)
        i = sys.argv.index("--head-scaling-bench")
        _head_scaling_ab_bench(int(sys.argv[i + 1]))
    elif "--client-bench" in sys.argv:
        sys.path.insert(0, REPO)
        i = sys.argv.index("--client-bench")
        _client_bench(sys.argv[i + 1], int(sys.argv[i + 2]),
                      sys.argv[i + 3] if len(sys.argv) > i + 3 else "")
    else:
        main()
