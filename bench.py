#!/usr/bin/env python
"""Benchmark harness. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extras": {...}}

Headline: single-client sync task throughput, directly comparable to the
reference's ray_perf.py microbenchmark ("single client tasks sync",
reference: python/ray/_private/ray_perf.py:174; recorded value 1006.9
tasks/s in release/release_logs/2.9.3/microbenchmark.json).

Also measured (extras): async task throughput, actor call throughput,
object-store put bandwidth, and a Llama train-step throughput inside a
worker (on the real TPU chip when one is attached; CPU otherwise).

The driver process never imports jax — the TPU is claimed by the worker
actor that runs the train benchmark.
"""

from __future__ import annotations

import json
import os
import sys
import time


def bench_tasks_sync(ray_tpu, n=300):
    @ray_tpu.remote
    def e():
        return b"ok"

    ray_tpu.get(e.remote(), timeout=60)  # warm lease
    t0 = time.perf_counter()
    for _ in range(n):
        ray_tpu.get(e.remote(), timeout=60)
    return n / (time.perf_counter() - t0)


def bench_tasks_async(ray_tpu, n=2000):
    @ray_tpu.remote
    def e():
        return b"ok"

    ray_tpu.get([e.remote() for _ in range(50)], timeout=60)
    t0 = time.perf_counter()
    ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
    return n / (time.perf_counter() - t0)


def bench_actor(ray_tpu, n_sync=300, n_async=2000):
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.m.remote(), timeout=60)
    t0 = time.perf_counter()
    for _ in range(n_sync):
        ray_tpu.get(a.m.remote(), timeout=60)
    sync = n_sync / (time.perf_counter() - t0)
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n_async)], timeout=120)
    return sync, n_async / (time.perf_counter() - t0)


def bench_put_gbps(ray_tpu, mb=100, iters=5):
    import numpy as np

    data = np.random.rand(mb * 1024 * 1024 // 8)
    refs = []
    t0 = time.perf_counter()
    for _ in range(iters):
        refs.append(ray_tpu.put(data))
    dt = time.perf_counter() - t0
    del refs
    return iters * mb / 1024 / dt


def _train_bench_loop():
    """Runs inside a worker actor; imports jax there (claims the chip)."""
    import dataclasses

    import jax

    platform = jax.devices()[0].platform
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh, shard_batch
    from ray_tpu.train.gspmd import build_llama_train_state, param_count

    if platform == "tpu":
        # ~600M params fills the v5e MXU; remat leaves HBM headroom
        # (measured 52.5% MFU at this point; no-remat is 53.1% but runs
        # within ~1.5 GB of the 16 GB limit)
        cfg = dataclasses.replace(LlamaConfig.bench_1b(), remat=True)
        batch, seq, steps = 8, 1024, 20
    else:
        cfg, batch, seq, steps = LlamaConfig.tiny(), 4, 128, 5
    mesh = make_mesh(MeshSpec(dp=-1), devices=jax.devices()[:1])
    params, opt, step_fn, _ = build_llama_train_state(
        cfg, mesh, batch_size=batch, seq_len=seq)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (batch, seq), 0,
                                cfg.vocab_size, dtype="int32")
    tokens = shard_batch(mesh, tokens)  # place once, outside the loop
    for _ in range(3):  # compile + settle donation aliasing
        params, opt, loss = step_fn(params, opt, tokens)
    float(loss)  # hard sync (block_until_ready is lazy over the tunnel)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt, loss = step_fn(params, opt, tokens)
    float(loss)
    dt = time.perf_counter() - t0
    tokens_per_s = steps * batch * seq / dt
    n_params = param_count(params)
    # MFU: 6 * params * tokens/s over peak flops (v5e: 197e12 bf16)
    peak = 197e12 if platform == "tpu" else 0
    mfu = (6 * n_params * tokens_per_s / peak) if peak else 0.0
    return {"platform": platform, "train_tokens_per_s": round(tokens_per_s, 1),
            "params": n_params, "mfu_pct": round(100 * mfu, 2),
            "loss": float(loss)}


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import ray_tpu

    ray_tpu.init(num_cpus=max(4, os.cpu_count() or 4),
                 object_store_memory=1024 * 1024 * 1024)
    extras = {}
    try:
        sync = bench_tasks_sync(ray_tpu)
        extras["tasks_async_per_s"] = round(bench_tasks_async(ray_tpu), 1)
        a_sync, a_async = bench_actor(ray_tpu)
        extras["actor_sync_per_s"] = round(a_sync, 1)
        extras["actor_async_per_s"] = round(a_async, 1)
        extras["put_gb_per_s"] = round(bench_put_gbps(ray_tpu), 2)
        train_actor = ray_tpu.remote(_TrainBench).remote()
        extras.update(ray_tpu.get(train_actor.run.remote(), timeout=1200))
    finally:
        ray_tpu.shutdown()
    print(json.dumps({
        "metric": "single-client sync tasks/s (ray_perf.py:174 equivalent)",
        "value": round(sync, 1),
        "unit": "tasks/s",
        "vs_baseline": round(sync / 1006.9, 3),
        "extras": extras,
    }))


class _TrainBench:
    def run(self):
        return _train_bench_loop()


if __name__ == "__main__":
    main()
