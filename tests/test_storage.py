"""StorageContext + remote checkpoint persistence tests
(reference: python/ray/train/_internal/storage.py tests)."""

import json
import os
import shutil

import numpy as np
import pytest

from ray_tpu.train.storage import StorageContext


@pytest.fixture(autouse=True)
def clean_memory_fs():
    import fsspec

    fs = fsspec.filesystem("memory")
    try:
        fs.rm("/", recursive=True)
    except Exception:
        pass
    yield


def test_local_storage_roundtrip(tmp_path):
    sc = StorageContext(str(tmp_path / "results"), "exp1")
    assert not sc.is_remote
    sc.write_text("state.json", json.dumps({"iter": 3}))
    assert json.loads(sc.read_text("state.json")) == {"iter": 3}
    src = tmp_path / "ck"
    src.mkdir()
    (src / "w.txt").write_text("weights")
    dest = sc.persist_dir(str(src), "checkpoints/ck1")
    assert open(os.path.join(dest, "w.txt")).read() == "weights"
    assert sc.list_dir("checkpoints") == ["ck1"]
    # fetch on local storage is a no-op passthrough
    assert sc.fetch_dir("checkpoints/ck1", str(tmp_path / "x")) == dest


def test_memory_storage_roundtrip(tmp_path):
    sc = StorageContext("memory://bucket/results", "exp1")
    assert sc.is_remote
    sc.write_text("meta", "hello")
    assert sc.read_text("meta") == "hello"
    assert sc.read_text("missing") is None
    src = tmp_path / "ck"
    (src / "sub").mkdir(parents=True)
    (src / "a.bin").write_bytes(b"\x01\x02")
    (src / "sub" / "b.bin").write_bytes(b"\x03")
    uri = sc.persist_dir(str(src), "checkpoints/ck1")
    assert uri.startswith("memory://")
    local = sc.fetch_dir("checkpoints/ck1", str(tmp_path / "restored"))
    assert open(os.path.join(local, "a.bin"), "rb").read() == b"\x01\x02"
    assert open(os.path.join(local, "sub", "b.bin"), "rb").read() == b"\x03"


def test_unknown_protocol_fails_at_construction():
    with pytest.raises(ValueError):
        StorageContext("warpdrive://x/y")


def test_checkpoint_manager_remote_persist(tmp_path, cpu_jax):
    """A checkpoint saved on host A restores on 'host B' (local dir
    wiped) from remote storage, index included."""
    from ray_tpu.train.checkpoint import CheckpointManager, \
        restore_checkpoint

    state = {"w": np.arange(6, dtype=np.float32), "step": np.int32(7)}
    sc = StorageContext("memory://bucket/run", "exp")
    local_a = tmp_path / "hostA"
    mgr = CheckpointManager(str(local_a), num_to_keep=2, storage=sc)
    path = mgr.save(state, metrics={"loss": 0.5})
    assert sc.list_dir("checkpoints") == ["ckpt_000001", "index.json"]

    # "host B": fresh local dir, same storage
    shutil.rmtree(local_a)
    local_b = tmp_path / "hostB"
    mgr2 = CheckpointManager(str(local_b), num_to_keep=2, storage=sc)
    assert mgr2.latest_checkpoint() == path  # index recovered remotely
    local = mgr2.fetch(mgr2.latest_checkpoint())
    restored = restore_checkpoint(local)
    assert np.array_equal(restored["w"], state["w"])
    assert int(restored["step"]) == 7


def test_checkpoint_manager_evicts_remote_copies(tmp_path, cpu_jax):
    from ray_tpu.train.checkpoint import CheckpointManager

    sc = StorageContext("memory://bucket/evict", "exp")
    mgr = CheckpointManager(str(tmp_path / "l"), num_to_keep=2, storage=sc)
    for i in range(4):
        mgr.save({"w": np.float32(i)})
    dirs = [d for d in sc.list_dir("checkpoints") if d != "index.json"]
    assert dirs == ["ckpt_000003", "ckpt_000004"]
