"""Placement group + gang scheduling tests.

Mirrors the reference's PG behavior
(reference: python/ray/tests/test_placement_group.py; bundle policies
src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h), including
the TPU-slice gang pattern: per-host bundles reserved all-or-nothing.
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import placement_group, remove_placement_group
from ray_tpu.util.placement_group import PlacementGroupError, tpu_slice_bundles


@pytest.fixture
def tpu_cluster():
    """3 nodes: 2 'TPU hosts' with 4 fake chips each + 1 CPU-only."""
    cluster = Cluster(head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, resources={"TPU": 4})
    cluster.add_node(num_cpus=2, resources={"TPU": 4})
    ray_tpu.init(address=cluster.address)
    try:
        yield cluster
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_pack_pg_basic(tpu_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK").ready(timeout=30)

    @ray_tpu.remote(placement_group=pg, placement_group_bundle_index=0)
    def a():
        return os.getpid()

    @ray_tpu.remote(placement_group=pg, placement_group_bundle_index=1)
    def b():
        return os.getpid()

    pa, pb = ray_tpu.get([a.remote(), b.remote()], timeout=60)
    assert pa and pb
    remove_placement_group(pg)


def test_strict_spread_lands_on_distinct_nodes(tpu_cluster):
    pg = placement_group([{"TPU": 4}, {"TPU": 4}],
                         strategy="STRICT_SPREAD").ready(timeout=30)
    info = pg._info()
    nodes = [p["node_id"] for p in info["placements"]]
    assert len(set(nodes)) == 2
    remove_placement_group(pg)


def test_gang_atomicity_infeasible(tpu_cluster):
    """3 TPU-hosts-worth of bundles on a 2-host cluster: nothing may be
    left partially reserved (slice all-or-nothing)."""
    pg = placement_group([{"TPU": 4}, {"TPU": 4}, {"TPU": 4}],
                         strategy="STRICT_SPREAD")
    assert not pg.wait(timeout=3)
    # all TPU resources must still be available to others
    pg2 = placement_group(tpu_slice_bundles(num_hosts=2, chips_per_host=4),
                          strategy="STRICT_SPREAD")
    assert pg2.wait(timeout=30)
    remove_placement_group(pg2)
    remove_placement_group(pg)


def test_pg_task_uses_bundle_resources(tpu_cluster):
    pg = placement_group([{"TPU": 4, "CPU": 1}]).ready(timeout=30)

    @ray_tpu.remote(num_tpus=4, placement_group=pg)
    def with_chips():
        return "got chips"

    assert ray_tpu.get(with_chips.remote(), timeout=60) == "got chips"
    remove_placement_group(pg)


def test_actor_in_pg(tpu_cluster):
    pg = placement_group([{"TPU": 4, "CPU": 1}]).ready(timeout=30)

    @ray_tpu.remote(num_tpus=2, placement_group=pg)
    class Shard:
        def where(self):
            return os.getpid()

    a, b = Shard.remote(), Shard.remote()  # both fit the 4-chip bundle
    pids = ray_tpu.get([a.where.remote(), b.where.remote()], timeout=60)
    assert len(pids) == 2
    remove_placement_group(pg)


def test_remove_pg_frees_resources(tpu_cluster):
    pg = placement_group([{"TPU": 4}, {"TPU": 4}],
                         strategy="STRICT_SPREAD").ready(timeout=30)
    remove_placement_group(pg)
    time.sleep(0.2)
    # resources back: a fresh identical PG must succeed
    pg2 = placement_group([{"TPU": 4}, {"TPU": 4}],
                          strategy="STRICT_SPREAD")
    assert pg2.wait(timeout=30)
    remove_placement_group(pg2)


def test_pg_churn_fast_right_after_task_burst(tpu_cluster):
    """PG creation must not collapse behind lingering task leases.

    Regression: task leases linger 0.2s holding CPUs after a burst; the
    head used to retry pending PGs on sleep backoff against a stale
    availability view (heartbeat period 3s), collapsing churn ~50x.  Now
    reservations queue on the agent, the agent reclaims idle leases, and
    the head replans on resource events — so churn right after a burst
    must stay within an order of magnitude of cheap (reference:
    microbenchmark.json 'placement group create/removal').
    """
    @ray_tpu.remote
    def e():
        return 1

    ray_tpu.get([e.remote() for _ in range(200)], timeout=120)
    n = 20
    cycles = []
    for _ in range(n):
        t1 = time.perf_counter()
        pg = placement_group([{"CPU": 1}]).ready(timeout=30)
        remove_placement_group(pg)
        cycles.append(time.perf_counter() - t1)
    # The collapse this guards against is the head falling back to
    # sleep-backoff retries against a stale availability view: every
    # create then stalls ~1-3s behind lingering leases.  Event-driven
    # replanning + demand-aware warm-lease reclaim resolve a create in
    # a few RPC round trips, so a LOOSE per-create latency bound (not a
    # wall-clock throughput rate — this fixture runs 3 node-agent
    # processes on however few cores CI gives it) is what's asserted
    # here; the strict ≥600/s throughput check lives in bench.py where
    # the measurement host is controlled.
    cycles.sort()
    median = cycles[n // 2]
    assert median < 0.5, \
        f"pg churn collapsed after task burst: median {median:.3f}s/create"
    assert cycles[-1] < 5.0, \
        f"pg create stalled behind a stale view: worst {cycles[-1]:.3f}s"
