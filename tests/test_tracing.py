"""Distributed tracing tests: cross-process span propagation, Serve
traceparent continuation, timeline flow/instant events, trace store
surfaces (state API / HTTP / CLI).

Mirrors the reference's tracing suite (reference:
python/ray/tests/test_tracing.py — task/actor spans parented across
process boundaries via context injected into the task spec)."""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util.state import get_trace, list_traces, timeline


@pytest.fixture(scope="module")
def cluster():
    """One cluster for the whole module: these tests only read the
    (append-only) trace store and task events, so sharing the cluster
    is safe and saves ~10 cluster boots of suite wall time."""
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def _wait_for_trace(trace_id=None, min_spans=1, predicate=None,
                    timeout=60.0):
    """Poll the head's trace store until a matching trace lands (spans
    flush on the observability cadence, not synchronously)."""
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        if trace_id is not None:
            try:
                t = get_trace(trace_id)
                if t["num_spans"] >= min_spans:
                    return t
                last = t
            except ValueError:
                pass
        else:
            for summary in list_traces():
                if summary["num_spans"] < min_spans:
                    continue
                t = get_trace(summary["trace_id"])
                if predicate is None or predicate(t):
                    return t
                last = t
        time.sleep(0.3)
    raise AssertionError(f"trace never complete; last seen: {last}")


def _assert_chained(trace):
    """One root, every other span's parent present in the trace."""
    spans = trace["spans"]
    ids = {s["span_id"] for s in spans}
    assert len({s["trace_id"] for s in spans}) == 1
    roots = [s for s in spans if not s.get("parent_id")]
    assert len(roots) <= 1, f"multiple roots: {roots}"
    for s in spans:
        if s.get("parent_id"):
            assert s["parent_id"] in ids, \
                f"dangling parent {s['parent_id'][:8]} on {s['name']}"


def test_nested_task_single_trace(cluster):
    """driver → task → nested subtask: one trace_id, ≥4 spans, correct
    submit/execute parentage chain."""
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x), timeout=60) + 1

    assert ray_tpu.get(outer.remote(1), timeout=60) == 3

    def is_nested(t):
        names = [s["name"] for s in t["spans"]]
        return any("outer" in n for n in names) \
            and any("inner" in n for n in names)

    trace = _wait_for_trace(min_spans=4, predicate=is_nested)
    _assert_chained(trace)
    spans = {s["span_id"]: s for s in trace["spans"]}
    by_name = {}
    for s in trace["spans"]:
        key = ("submit" if s["name"].startswith("submit") else "execute",
               "inner" if "inner" in s["name"] else "outer")
        by_name[key] = s
    # execute parents to its submit; the nested submit parents to the
    # outer execute span (it was made inside the task body)
    assert spans[by_name[("execute", "outer")]["parent_id"]] \
        is by_name[("submit", "outer")]
    assert spans[by_name[("submit", "inner")]["parent_id"]] \
        is by_name[("execute", "outer")]
    assert spans[by_name[("execute", "inner")]["parent_id"]] \
        is by_name[("submit", "inner")]
    # kinds: submit-side CLIENT, execute-side SERVER
    assert by_name[("submit", "outer")]["kind"] == "CLIENT"
    assert by_name[("execute", "inner")]["kind"] == "SERVER"


def test_actor_task_trace(cluster):
    """Actor creation and method calls produce chained spans too."""
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote(), timeout=60) == 1

    def is_actor(t):
        return any(s["name"] == "submit bump" for s in t["spans"])

    trace = _wait_for_trace(min_spans=2, predicate=is_actor)
    _assert_chained(trace)
    execs = [s for s in trace["spans"] if s["name"] == "execute bump"]
    subs = [s for s in trace["spans"] if s["name"] == "submit bump"]
    assert execs and subs
    assert execs[0]["parent_id"] == subs[0]["span_id"]


def _http_serve_fixture():
    from ray_tpu.serve import api as serve_api
    from ray_tpu.serve import http as serve_http

    @serve_api.deployment
    class Echo:
        def __call__(self, arg):
            return {"echo": arg}

    serve_api.run(Echo.bind(), name="traced_echo")
    return serve_http.start_http()


def test_serve_traceparent_continues_trace(cluster):
    """An inbound W3C traceparent header's trace_id is continued through
    ingress → handle → replica execution."""
    host, port = _http_serve_fixture()
    trace_id = "1f" * 16
    req = urllib.request.Request(
        f"http://{host}:{port}/traced_echo?q=1",
        headers={"traceparent": f"00-{trace_id}-{'2e' * 8}-01"})
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.status == 200
    trace = _wait_for_trace(trace_id=trace_id, min_spans=4)
    names = [s["name"] for s in trace["spans"]]
    assert any(n.startswith("http GET") for n in names), names
    assert any(n.startswith("serve.handle") for n in names), names
    assert any(n.startswith("execute") for n in names), names
    # the ingress span is NOT a root: it parents to the external
    # caller's span id from the header
    http_span = next(s for s in trace["spans"]
                     if s["name"].startswith("http GET"))
    assert http_span["parent_id"] == "2e" * 8
    spans = {s["span_id"] for s in trace["spans"]}
    for s in trace["spans"]:
        if s is not http_span and s.get("parent_id"):
            assert s["parent_id"] in spans


def test_serve_malformed_traceparent_ignored(cluster):
    """Garbage traceparent headers must not error the request — the
    request proceeds (with its own root trace)."""
    host, port = _http_serve_fixture()
    for bad in ("garbage", "00-zz-zz-zz", "00-" + "0" * 32 + "-" +
                "1" * 16 + "-01", "ff-" + "a" * 32 + "-" + "b" * 16 +
                "-01", ""):
        req = urllib.request.Request(
            f"http://{host}:{port}/traced_echo?q=2",
            headers={"traceparent": bad} if bad else {})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.status == 200, bad
            assert json.loads(r.read())["echo"] == {"q": "2"}


def test_unsampled_submission_records_no_spans(cluster):
    """With sampling off no spans accumulate — and the negative
    decision propagates: a nested subtask must not re-roll sampling
    and mint an orphan root trace mid-call-tree."""
    import os

    from ray_tpu._private import tracing

    os.environ["RT_TRACE_SAMPLING_RATIO"] = "0.0"
    try:
        time.sleep(0.25)  # let the tracing config TTL cache expire
        tracing.drain()  # clear anything buffered by the fixture
        before = {t["trace_id"] for t in list_traces(limit=500)}

        @ray_tpu.remote
        def unsampled_inner():
            return 1

        @ray_tpu.remote
        def unsampled_outer():
            return ray_tpu.get(unsampled_inner.remote(), timeout=60) + 1

        assert ray_tpu.get(unsampled_outer.remote(), timeout=60) == 2
        assert tracing.drain() == []
        # nothing from this tree reached the store (workers inherit the
        # not-sampled marker instead of re-rolling)
        time.sleep(1.5)  # one worker flush cadence
        fresh = [get_trace(t["trace_id"])
                 for t in list_traces(limit=500)
                 if t["trace_id"] not in before]
        assert not any("unsampled" in s["name"]
                       for t in fresh for s in t["spans"]), fresh
    finally:
        os.environ.pop("RT_TRACE_SAMPLING_RATIO", None)


def test_traceparent_roundtrip():
    """format_traceparent emits what parse_traceparent accepts (the
    outbound half of the W3C interop)."""
    from ray_tpu._private import tracing

    ctx = tracing.SpanContext(tracing.new_trace_id(),
                              tracing.new_span_id(), True)
    header = tracing.format_traceparent(ctx)
    back = tracing.parse_traceparent(header)
    assert back is not None
    assert (back.trace_id, back.span_id, back.sampled) == \
        (ctx.trace_id, ctx.span_id, True)
    unsampled = tracing.SpanContext(ctx.trace_id, ctx.span_id, False)
    back = tracing.parse_traceparent(tracing.format_traceparent(unsampled))
    assert back is not None and not back.sampled


def test_timeline_flow_events(cluster):
    """The exported timeline carries ph:"s"/"f" flow events pairing the
    submit point with the execution slice (Perfetto causality arrows)."""
    @ray_tpu.remote
    def traced_flow():
        time.sleep(0.01)
        return 1

    ray_tpu.get([traced_flow.remote() for _ in range(3)], timeout=60)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        events = timeline()
        slices = [e for e in events if e["ph"] == "X"
                  and e["name"].endswith("traced_flow")]
        if len(slices) >= 3:
            break
        time.sleep(0.3)
    assert len(slices) >= 3
    starts = {e["id"]: e for e in events if e["ph"] == "s"}
    ends = {e["id"]: e for e in events if e["ph"] == "f"}
    assert starts, "no flow-start events in the timeline"
    assert set(starts) == set(ends)
    for fid, s in starts.items():
        f = ends[fid]
        assert f.get("bt") == "e"
        assert s["ts"] <= f["ts"], (s, f)
        # the flow id ties back to the task the slice describes
        matching = [e for e in events if e["ph"] == "X"
                    and e["args"]["task_id"].startswith(fid)]
        assert matching, fid


def test_timeline_instant_event_for_queued_failure(cluster):
    """A task cancelled while queued (never RUNNING) must appear in the
    timeline as a ph:"i" instant event instead of being dropped."""
    @ray_tpu.remote(num_cpus=4)
    def hog():
        time.sleep(3)
        return "hog"

    @ray_tpu.remote(num_cpus=4)
    def starved():
        return 1

    h = hog.remote()  # occupies every CPU
    time.sleep(0.3)
    ref = starved.remote()  # stuck behind the hog, never RUNNING
    time.sleep(0.2)
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.RayError):
        ray_tpu.get(ref, timeout=30)
    ray_tpu.get(h, timeout=60)  # drain the hog before the next test
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        inst = [e for e in timeline()
                if e["ph"] == "i" and e["name"].endswith("starved")]
        if inst:
            break
        time.sleep(0.3)
    assert inst, "queue-time failure missing from the timeline"
    assert inst[0]["args"]["state"] == "FAILED"
    assert "cancel" in inst[0]["args"].get("error", "").lower()


def test_trace_http_endpoints_and_store_bound(cluster):
    """/api/traces + /api/traces/<id> serve the store over HTTP."""
    @ray_tpu.remote
    def probe():
        return 1

    ray_tpu.get(probe.remote(), timeout=60)
    trace = _wait_for_trace(
        min_spans=2,
        predicate=lambda t: any("probe" in s["name"] for s in t["spans"]))
    port = ray_tpu.api._worker().head.call("metrics_port")["port"]

    def fetch(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return json.loads(r.read())

    summaries = fetch("/api/traces")
    assert any(t["trace_id"] == trace["trace_id"] for t in summaries)
    one = fetch(f"/api/traces/{trace['trace_id']}")
    assert one["trace_id"] == trace["trace_id"]
    assert len(one["spans"]) == trace["num_spans"]
    missing = fetch("/api/traces/" + "0" * 32)
    assert "error" in missing


def test_rtpu_trace_cli(cluster, tmp_path, capsys):
    """`rtpu trace list` and `rtpu trace get` against the live head."""
    from ray_tpu import scripts

    @ray_tpu.remote
    def cli_probe():
        return 1

    ray_tpu.get(cli_probe.remote(), timeout=60)
    trace = _wait_for_trace(
        min_spans=2,
        predicate=lambda t: any("cli_probe" in s["name"]
                                for s in t["spans"]))
    host, port = ray_tpu.api._worker().head_addr
    addr = f"{host}:{port}"
    # big limit: serve reconcile health-checks from earlier tests in
    # this module's shared cluster keep minting traces, so the probe's
    # trace may not sit in the newest 20
    assert scripts.main(["trace", "--address", addr, "list",
                         "--limit", "500"]) == 0
    out = capsys.readouterr().out
    assert trace["trace_id"] in out
    dest = str(tmp_path / "trace.json")
    assert scripts.main(["trace", "--address", addr, "get",
                         trace["trace_id"], "-o", dest]) == 0
    dumped = json.load(open(dest))
    assert dumped["trace_id"] == trace["trace_id"]
    assert len(dumped["spans"]) >= 2
    assert scripts.main(["trace", "--address", addr, "get",
                         "f" * 32]) == 1


def test_get_log_missing_filename_raises(cluster):
    """Satellite: an explicit, nonexistent filename must raise instead
    of silently returning some other log file."""
    from ray_tpu.util.state import get_log

    with pytest.raises(FileNotFoundError):
        get_log(filename="no_such_file_xyz.log")
    # default (no filename) still returns the latest log quietly
    assert isinstance(get_log(), str)
