"""ray_tpu.serve tests (reference: python/ray/serve/tests/ unit patterns)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def test_function_deployment(cluster):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert ray_tpu.get(handle.remote("hi"), timeout=60) == {"echo": "hi"}
    serve.delete("echo")


def test_class_deployment_with_state(cluster):
    @serve.deployment(name="adder")
    class Adder:
        def __init__(self, base):
            self.base = base

        def __call__(self, x):
            return self.base + x

        def describe(self):
            return f"adder+{self.base}"

    handle = serve.run(Adder.bind(10))
    assert ray_tpu.get(handle.remote(5), timeout=60) == 15
    assert ray_tpu.get(handle.method("describe")(), timeout=30) == "adder+10"
    serve.delete("adder")


def test_multi_replica_load_balancing(cluster):
    @serve.deployment(name="pids", num_replicas=3)
    class Pids:
        def __call__(self, _):
            import os
            import time as _t

            _t.sleep(0.15)
            return os.getpid()

    handle = serve.run(Pids.bind())
    refs = [handle.remote(i) for i in range(9)]
    pids = set(ray_tpu.get(refs, timeout=120))
    assert len(pids) >= 2  # requests spread across replicas
    serve.delete("pids")


def test_dynamic_batching(cluster):
    @serve.deployment(name="batcher", max_ongoing_requests=16)
    class Model:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        def forward(self, items):
            self.batch_sizes.append(len(items))
            return [x * 2 for x in items]

        def __call__(self, x):
            return self.forward(x)

        def stats(self):
            return self.batch_sizes

    handle = serve.run(Model.bind())
    refs = [handle.remote(i) for i in range(8)]
    out = ray_tpu.get(refs, timeout=120)
    assert sorted(out) == [i * 2 for i in range(8)]
    sizes = ray_tpu.get(handle.method("stats")(), timeout=30)
    assert max(sizes) > 1, f"no batching happened: {sizes}"
    serve.delete("batcher")


def test_redeploy_scales(cluster):
    @serve.deployment(name="scaled", num_replicas=1)
    def f(x):
        return x

    serve.run(f.bind())
    handle = serve.run(f.options(num_replicas=2).bind(), name="scaled")
    assert len(handle._replicas) == 2
    serve.delete("scaled")


def test_get_handle_and_delete(cluster):
    @serve.deployment(name="tmp")
    def g(x):
        return x + 1

    serve.run(g.bind())
    h = serve.get_handle("tmp")
    assert ray_tpu.get(h.remote(1), timeout=60) == 2
    serve.delete("tmp")
    with pytest.raises(ValueError):
        serve.get_handle("tmp")


# ------------------------------------------------- async-native data plane


def test_get_async_and_await_ref(cluster):
    """Awaitable object refs: ray_tpu.get_async / `await ref` /
    ref.future() resolve on the calling event loop — errors and
    timeouts surface exactly like the blocking get."""
    import asyncio

    @ray_tpu.remote
    def f(x):
        return x + 1

    @ray_tpu.remote
    def boom():
        raise ValueError("x")

    @ray_tpu.remote
    def slow():
        time.sleep(5)

    async def drive():
        vals = await ray_tpu.get_async([f.remote(i) for i in range(20)],
                                       timeout=60)
        assert vals == list(range(1, 21))
        assert await f.remote(41) == 42
        assert await f.remote(1).future() == 2
        # plasma-stored values resolve through the same awaitable
        big = b"x" * 200_000
        assert await ray_tpu.get_async(ray_tpu.put(big), timeout=60) == big
        with pytest.raises(ray_tpu.RayTaskError):
            await ray_tpu.get_async(boom.remote(), timeout=60)
        with pytest.raises(ray_tpu.GetTimeoutError):
            await ray_tpu.get_async(slow.remote(), timeout=0.3)

    asyncio.run(drive())


def test_remote_async_and_stream_async(cluster):
    """DeploymentHandle.remote_async/stream_async: same replica choice
    and inflight accounting as the sync paths, awaitable end to end."""
    import asyncio

    @serve.deployment(name="async_dep")
    class Dep:
        def __call__(self, x):
            return x * 2

        def gen(self, n):
            for i in range(int(n)):
                yield i

    handle = serve.run(Dep.bind())

    async def drive():
        refs = [await handle.remote_async(i) for i in range(8)]
        assert await ray_tpu.get_async(refs, timeout=60) \
            == [i * 2 for i in range(8)]
        agen = await handle.stream_async(4, _method="gen")
        out = []
        async for ref in agen:
            out.append(await ref)
        assert out == [0, 1, 2, 3]

    asyncio.run(drive())
    # inflight accounting drains (remote_async charges are released by
    # the shared waiter, streams by the consumer finally)
    deadline = time.time() + 15
    while time.time() < deadline:
        with handle._lock:
            if sum(handle._inflight.values()) == 0:
                break
        time.sleep(0.05)
    with handle._lock:
        assert sum(handle._inflight.values()) == 0, handle._inflight
    serve.delete("async_dep")


# -------------------------------------------- ingress / recovery / scaling


def test_http_ingress(cluster):
    """curl-level e2e through the asyncio proxy
    (reference: serve/_private/proxy.py)."""
    import requests

    @serve.deployment(name="doubler")
    def doubler(x):
        return {"doubled": int(x["n"]) * 2} if isinstance(x, dict) else x * 2

    serve.run(doubler.bind())
    host, port = serve.start_http()
    base = f"http://{host}:{port}"
    assert requests.get(f"{base}/-/healthz", timeout=30).status_code == 200
    r = requests.post(f"{base}/doubler", json={"n": 21}, timeout=60)
    assert r.status_code == 200 and r.json() == {"doubled": 42}
    r = requests.get(f"{base}/doubler?n=5", timeout=60)
    assert r.json()["doubled"] == 10
    assert requests.post(f"{base}/nosuch", json=1, timeout=30).status_code == 404
    serve.delete("doubler")
    assert requests.post(f"{base}/doubler", json=1, timeout=30).status_code in (404, 500)
    serve.shutdown_http()


def test_replica_death_recovery(cluster):
    """The controller's reconcile loop replaces a killed replica
    (reference: deployment_state.py replica FSM recovery)."""
    @serve.deployment(name="sturdy", num_replicas=2)
    def f(x):
        return x

    handle = serve.run(f.bind())
    assert ray_tpu.get(handle.remote(1), timeout=60) == 1
    # kill one replica out from under the controller
    killed = handle._replicas[0]._actor_id
    ray_tpu.kill(handle._replicas[0])
    from ray_tpu.serve.api import CONTROLLER_NAME

    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    deadline = time.time() + 60
    ok = False
    while time.time() < deadline:
        # wait for the ACTUAL replacement: the dead replica gone from
        # the roster and the count restored — a bare count==2 check
        # passes before the controller even notices the death (the dead
        # replica is still registered), letting the test race ahead to
        # a handle refresh that re-learns the stale roster
        info = ray_tpu.get(ctrl.get_replicas.remote("sturdy"), timeout=30)
        ids = info["replica_ids"]
        if len(ids) == 2 and killed not in ids:
            ok = True
            break
        time.sleep(0.5)
    assert ok, "replica was not replaced"
    # requests still succeed after recovery (handle refreshes replicas)
    time.sleep(1.1)  # let the handle's refresh window lapse
    out = ray_tpu.get([handle.remote(i) for i in range(6)], timeout=60)
    assert out == list(range(6))
    serve.delete("sturdy")


def test_autoscaling_scales_up_under_load(cluster):
    """Replica count follows reported ongoing requests
    (reference: autoscaling_policy.py)."""
    @serve.deployment(name="slow", autoscaling_config={
        "min_replicas": 1, "max_replicas": 3, "target_ongoing_requests": 1})
    def slow(x):
        time.sleep(0.4)
        return x

    handle = serve.run(slow.bind())
    from ray_tpu.serve.api import CONTROLLER_NAME
    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    assert ray_tpu.get(ctrl.list_deployments.remote(), timeout=30)["slow"] == 1
    deadline = time.time() + 45
    scaled = False
    pending = []
    while time.time() < deadline and not scaled:
        if pending:
            _, pending = ray_tpu.wait(pending, num_returns=len(pending),
                                      timeout=0.01)
            pending = list(pending)
        while len(pending) < 6:
            pending.append(handle.remote(0))
        time.sleep(0.4)
        if ray_tpu.get(ctrl.list_deployments.remote(), timeout=30)["slow"] >= 2:
            scaled = True
    assert scaled, "deployment did not scale up under load"
    ray_tpu.get(pending, timeout=120)
    serve.delete("slow")


def test_handle_survives_redeploy(cluster):
    """An existing handle refreshes to the new replica set after a
    redeploy (version is monotonic across deploys)."""
    @serve.deployment(name="redep")
    def f(x):
        return x + 1

    handle = serve.run(f.bind())
    assert ray_tpu.get(handle.remote(1), timeout=60) == 2

    @serve.deployment(name="redep")
    def f2(x):
        return x + 100

    serve.run(f2.bind(), name="redep")
    time.sleep(1.1)  # old handle's refresh window lapses
    deadline = time.time() + 20
    while time.time() < deadline:
        try:
            if ray_tpu.get(handle.remote(1), timeout=30) == 101:
                break
        except ray_tpu.RayError:
            time.sleep(0.2)  # may race the old-replica teardown
    assert ray_tpu.get(handle.remote(1), timeout=30) == 101
    serve.delete("redep")


def test_controller_crash_recovery(cluster):
    """Kill the controller mid-traffic: detached replicas keep serving,
    a fresh controller recovers state from its KV checkpoint, and zero
    requests fail (reference: controller checkpoints to GCS KV and
    application_state recovers replicas)."""
    from ray_tpu.serve.api import CONTROLLER_NAME

    @serve.deployment(name="durable", num_replicas=2)
    def durable(x):
        return x * 2

    handle = serve.run(durable.bind())
    assert ray_tpu.get(handle.remote(21), timeout=60) == 42
    time.sleep(0.3)  # let the checkpoint land in the KV

    ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    ray_tpu.kill(ctrl)

    # existing handle still routes (replicas are detached + alive)
    out = ray_tpu.get([handle.remote(i) for i in range(5)], timeout=60)
    assert out == [0, 2, 4, 6, 8]

    # a brand-new handle goes through a fresh controller, which must
    # recover the deployment from its checkpoint
    deadline = time.time() + 60
    recovered = None
    while time.time() < deadline:
        try:
            recovered = serve.get_handle("durable")
            break
        except Exception:
            time.sleep(0.5)
    assert recovered is not None, "controller never recovered the app"
    assert ray_tpu.get(recovered.remote(5), timeout=60) == 10
    # reconcile still heals: kill a replica, count returns to 2
    ray_tpu.kill(recovered._replicas[0])
    ctrl2 = ray_tpu.get_actor(CONTROLLER_NAME)
    deadline = time.time() + 45
    while time.time() < deadline:
        if ray_tpu.get(ctrl2.list_deployments.remote(),
                       timeout=30).get("durable") == 2:
            break
        time.sleep(0.5)
    assert ray_tpu.get(ctrl2.list_deployments.remote(),
                       timeout=30).get("durable") == 2
    serve.delete("durable")


def test_rpc_ingress(cluster):
    """Binary (msgpack) ingress next to HTTP — the gRPC-ingress
    equivalent (reference: _private/proxy.py gRPCProxy)."""
    @serve.deployment(name="scorer")
    class Scorer:
        def __call__(self, xs):
            return {"sum": sum(xs)}

        def describe(self):
            return "scorer-v1"

    serve.run(Scorer.bind())
    host, port = serve.start_rpc_ingress()
    client = serve.RpcIngressClient(host, port)
    try:
        assert client.healthz()
        assert "scorer" in client.routes()
        assert client.invoke("scorer", [1, 2, 3]) == {"sum": 6}
        assert client.invoke("scorer", method="describe") == "scorer-v1"
        from ray_tpu._private.rpc import RpcError

        with pytest.raises(RpcError):
            client.invoke("nope", 1)
    finally:
        client.close()
        serve.stop_rpc_ingress()
        serve.delete("scorer")


def test_handle_streaming(cluster):
    """A generator deployment streams items through handle.stream()
    before the replica call completes (reference: serve streaming
    responses / DeploymentResponseGenerator)."""
    @serve.deployment(name="tokens")
    class Tokens:
        def __call__(self, n):
            import time as t
            for i in range(int(n)):
                yield {"tok": i, "ts": t.time()}
                t.sleep(0.1)

    handle = serve.run(Tokens.bind())
    t0 = time.time()
    items = []
    first_lag = None
    for ref in handle.stream(5):
        v = ray_tpu.get(ref, timeout=30)
        if first_lag is None:
            first_lag = time.time() - v["ts"]
        items.append(v["tok"])
    assert items == [0, 1, 2, 3, 4]
    # first token consumable well before the full 0.5s of generation
    assert first_lag < 0.3, f"first token lagged {first_lag:.2f}s"
    serve.delete("tokens")


def test_stream_abandoned_releases_inflight(cluster):
    """A stream() whose consumer never iterates (or stops early) must
    still release its inflight count once the replica-side generator
    finishes producing — the consumer-side finally alone never runs for
    an un-iterated generator, and a leaked +1 would permanently skew
    least-inflight replica selection."""
    @serve.deployment(name="drops")
    def drops(n):
        for i in range(int(n)):
            yield i

    handle = serve.run(drops.bind())

    def total_inflight():
        with handle._lock:
            return sum(handle._inflight.values())

    # consumed stream: the consumer finally releases (and the waiter's
    # release is once-only, so the count must not go negative)
    out = [ray_tpu.get(r, timeout=30) for r in handle.stream(3)]
    assert out == [0, 1, 2]
    # abandoned streams: never iterated at all
    for _ in range(3):
        handle.stream(4)
    deadline = time.time() + 15
    while time.time() < deadline and total_inflight() != 0:
        time.sleep(0.05)
    assert total_inflight() == 0, handle._inflight
    serve.delete("drops")


def test_http_streaming_chunked(cluster):
    """Accept: text/event-stream gets a chunked response fed by the
    replica's generator, tokens arriving progressively (reference:
    serve StreamingResponse over HTTP)."""
    import http.client

    @serve.deployment(name="sse")
    def sse(q):
        for i in range(4):
            yield f"tok{i}"

    serve.run(sse.bind())
    host, port = serve.start_http()
    try:
        conn = http.client.HTTPConnection(host, port, timeout=30)
        conn.request("GET", "/sse", headers={"Accept": "text/event-stream"})
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("Transfer-Encoding") == "chunked"
        body = resp.read().decode()
        lines = [l for l in body.splitlines() if l.strip()]
        import json as _json
        assert [_json.loads(l) for l in lines] == [f"tok{i}" for i in range(4)]
        conn.close()
    finally:
        serve.shutdown_http()
        serve.delete("sse")
