"""ray_tpu.serve tests (reference: python/ray/serve/tests/ unit patterns)."""

import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def test_function_deployment(cluster):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert ray_tpu.get(handle.remote("hi"), timeout=60) == {"echo": "hi"}
    serve.delete("echo")


def test_class_deployment_with_state(cluster):
    @serve.deployment(name="adder")
    class Adder:
        def __init__(self, base):
            self.base = base

        def __call__(self, x):
            return self.base + x

        def describe(self):
            return f"adder+{self.base}"

    handle = serve.run(Adder.bind(10))
    assert ray_tpu.get(handle.remote(5), timeout=60) == 15
    assert ray_tpu.get(handle.method("describe")(), timeout=30) == "adder+10"
    serve.delete("adder")


def test_multi_replica_load_balancing(cluster):
    @serve.deployment(name="pids", num_replicas=3)
    class Pids:
        def __call__(self, _):
            import os
            import time as _t

            _t.sleep(0.15)
            return os.getpid()

    handle = serve.run(Pids.bind())
    refs = [handle.remote(i) for i in range(9)]
    pids = set(ray_tpu.get(refs, timeout=120))
    assert len(pids) >= 2  # requests spread across replicas
    serve.delete("pids")


def test_dynamic_batching(cluster):
    @serve.deployment(name="batcher", max_ongoing_requests=16)
    class Model:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        def forward(self, items):
            self.batch_sizes.append(len(items))
            return [x * 2 for x in items]

        def __call__(self, x):
            return self.forward(x)

        def stats(self):
            return self.batch_sizes

    handle = serve.run(Model.bind())
    refs = [handle.remote(i) for i in range(8)]
    out = ray_tpu.get(refs, timeout=120)
    assert sorted(out) == [i * 2 for i in range(8)]
    sizes = ray_tpu.get(handle.method("stats")(), timeout=30)
    assert max(sizes) > 1, f"no batching happened: {sizes}"
    serve.delete("batcher")


def test_redeploy_scales(cluster):
    @serve.deployment(name="scaled", num_replicas=1)
    def f(x):
        return x

    serve.run(f.bind())
    handle = serve.run(f.options(num_replicas=2).bind(), name="scaled")
    assert len(handle._replicas) == 2
    serve.delete("scaled")


def test_get_handle_and_delete(cluster):
    @serve.deployment(name="tmp")
    def g(x):
        return x + 1

    serve.run(g.bind())
    h = serve.get_handle("tmp")
    assert ray_tpu.get(h.remote(1), timeout=60) == 2
    serve.delete("tmp")
    with pytest.raises(ValueError):
        serve.get_handle("tmp")
