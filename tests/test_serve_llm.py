"""LLM serving tier tests (serve/llm.py): continuous batching over a
paged KV cache, admission/shed, streaming + disconnect, resume.

Engine-level tests run without a cluster (fast, deterministic).  The
cluster tests share ONE module-scoped cluster + HTTP proxy — tier-1
budget is tight, so every deployment in this module rides the same
cluster and warms its jit cache with a 1-token request before any
timed assertion.
"""

import json
import socket
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.models.llama import LlamaConfig, LlamaModel
from ray_tpu.serve.llm import LLMEngine, LLMOverloadedError

# one tiny fp32 config for everything: fp32 keeps greedy argmax
# bit-stable across the cached and full-forward paths
MODEL = {"vocab_size": 64, "dim": 32, "n_layers": 2, "n_heads": 4,
         "n_kv_heads": 2, "hidden_dim": 64, "max_seq_len": 64}


def _cfg(**over):
    d = dict(MODEL, **over)
    return LlamaConfig(dtype=jnp.float32, **d)


# flax init is eager and costs seconds per call in this sandbox: build
# the (deterministic, seed-0) param tree once per distinct config
_params_cache = {}


def _engine(**kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("num_pages", 33)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("max_queue", 8)
    kw.setdefault("detach_grace_s", 60.0)
    cfg = kw.pop("cfg", None) or _cfg()
    if "params" not in kw:
        if cfg not in _params_cache:
            probe = LLMEngine(cfg, **kw)
            _params_cache[cfg] = probe._params
            return probe
        kw["params"] = _params_cache[cfg]
    return LLMEngine(cfg, **kw)


def _ref_greedy(engine, prompt, n):
    """Greedy decode through the NON-batched full forward — the
    correctness oracle for the continuous-batching path."""
    model, params = engine._model, engine._params
    toks = list(prompt)
    for _ in range(n):
        lg = model.apply({"params": params}, np.array([toks], np.int32))
        toks.append(int(np.argmax(np.asarray(lg[0, -1]))))
    return toks[len(prompt):]


def _assert_greedy(engine, prompt, generated, n=None):
    """Teacher-forcing oracle: ONE full non-batched forward over
    prompt+generated proves token-identity with greedy decode (each
    generated token must be the argmax at its prefix position).
    Equivalent to _ref_greedy but one eager apply instead of one per
    token — eager ops cost ~ms each in this sandbox."""
    if n is not None:
        assert len(generated) == n, (len(generated), n)
    assert generated, "nothing generated"
    full = list(prompt) + list(generated)
    lg = engine._model.apply({"params": engine._params},
                             np.array([full], np.int32))
    lg = np.asarray(lg[0])
    for j, tok in enumerate(generated):
        pos = len(prompt) + j - 1
        assert int(np.argmax(lg[pos])) == int(tok), \
            (j, tok, int(np.argmax(lg[pos])))


def _drain(engine, rounds=200):
    for _ in range(rounds):
        if not engine.step():
            break


# ----------------------------------------------------------- engine units


def test_decode_matches_full_forward():
    """The acceptance gate: greedy decode of a fixed prompt set through
    the continuous-batching path (staggered admission, chunked prefill,
    shared decode lanes, paged non-contiguous KV slots) is
    token-identical to the single-sequence full forward."""
    eng = _engine()
    prompts = [[5, 9, 3], [7, 11, 2, 4, 8, 1, 9, 10, 3, 2], [1, 2],
               [3, 3, 3, 3, 3, 3, 3, 3, 3, 3, 3, 3, 3]]
    seqs = [eng.submit({"tokens": p, "max_new_tokens": 6})
            for p in prompts[:3]]
    for _ in range(3):
        eng.step()
    # token-boundary admission: the 4th sequence joins mid-flight
    late = eng.submit({"tokens": prompts[3], "max_new_tokens": 5})
    _drain(eng)
    for p, s in zip(prompts, seqs):
        _assert_greedy(eng, p, s.generated, n=6)
    _assert_greedy(eng, prompts[3], late.generated, n=5)
    # every page recycled after EOS
    st = eng.stats()
    assert st["used_pages"] == 0 and st["free_pages"] == 32, st


def test_sampling_knobs_are_static_and_seeded():
    """temperature/top_k ride the decode as jit-STATIC knobs (ISSUE 13
    satellite): a sampled engine draws valid tokens deterministically
    per seed (same seed replays the same stream, different seeds
    diverge), while the default temperature=0 engine still compiles the
    exact greedy program the decode-identity gate above pins down."""
    prompt, n = [5, 9, 3], 6
    greedy = _engine()
    g = greedy.submit({"tokens": prompt, "max_new_tokens": n})
    _drain(greedy)
    _assert_greedy(greedy, prompt, g.generated, n=n)

    def sampled(seed):
        eng = _engine(seed=seed, temperature=0.8, top_k=5,
                      params=greedy._params)
        s = eng.submit({"tokens": prompt, "max_new_tokens": n})
        _drain(eng)
        assert eng.stats()["used_pages"] == 0
        return list(s.generated)

    a, b, c = sampled(7), sampled(7), sampled(8)
    assert a == b, "same seed must replay the same tokens"
    assert len(a) == n
    vocab = greedy.cfg.vocab_size
    assert all(0 <= t < vocab for t in a)
    # with top_k=5 every sampled token must come from the top-5 logits
    # at its position (teacher-forced oracle, like _assert_greedy)
    full = list(prompt) + a
    lg = np.asarray(greedy._model.apply(
        {"params": greedy._params}, np.array([full], np.int32))[0])
    for j, tok in enumerate(a):
        pos = len(prompt) + j - 1
        top5 = set(np.argsort(lg[pos])[-5:].tolist())
        assert int(tok) in top5, (j, tok, top5)
    if a != c:
        pass  # different seeds usually diverge; equality is not an error


def test_eos_stops_and_recycles():
    eng = _engine()
    probe = eng.submit({"tokens": [5, 9, 3], "max_new_tokens": 6})
    _drain(eng)
    ref = list(probe.generated)
    eos = ref[2]  # stop at the 3rd generated token
    s = eng.submit({"tokens": [5, 9, 3], "max_new_tokens": 6, "eos": eos})
    _drain(eng)
    assert s.generated == ref[:3]
    _assert_greedy(eng, [5, 9, 3], ref, n=6)
    assert eng.stats()["used_pages"] == 0


def test_chunked_prefill_does_not_stall_decodes():
    """A long prompt prefills one chunk per step while short sequences
    keep decoding — the Orca-style chunked-prefill property."""
    eng = _engine(max_batch=4, prefill_chunk=8)
    short = eng.submit({"tokens": [1, 2], "max_new_tokens": 3})
    eng.step()  # short enters decode
    long_prompt = [7] * 40  # 5 prefill chunks
    long = eng.submit({"tokens": long_prompt, "max_new_tokens": 3})
    _drain(eng)
    _assert_greedy(eng, [1, 2], short.generated, n=3)
    _assert_greedy(eng, long_prompt, long.generated, n=3)
    # the short sequence finished BEFORE the long prompt produced its
    # first token (it only needed 2 more steps; the prefill needed 5)
    assert short.first_token_at < long.first_token_at


def test_admission_shed_and_page_bounds():
    eng = _engine(num_pages=9, max_batch=1, max_queue=1)  # 1 seq + 1 queued
    a = eng.submit({"tokens": [1, 2, 3], "max_new_tokens": 20})
    eng.step()
    b = eng.submit({"tokens": [4, 5], "max_new_tokens": 4})
    with pytest.raises(LLMOverloadedError):
        eng.submit({"tokens": [6], "max_new_tokens": 2})
    with pytest.raises(ValueError):  # can never fit: not a shed
        eng.submit({"tokens": [1] * 40, "max_new_tokens": 40})
    _drain(eng)
    assert a.done and b.done and eng.stats()["used_pages"] == 0


def test_cancel_recycles_pages():
    eng = _engine()
    s = eng.submit({"tokens": [5, 9, 3], "max_new_tokens": 30,
                    "request_id": "c1"})
    for _ in range(4):
        eng.step()
    assert not s.done and eng.stats()["used_pages"] > 0
    assert eng.cancel("c1")
    st = eng.stats()
    assert st["used_pages"] == 0 and st["cancelled"] == 1
    # consumers see end-of-stream, not a hang
    assert [i for i in eng.iter_tokens(s, len(s.generated))] == []


def test_detach_grace_cancels_abandoned_sequence():
    eng = _engine(detach_grace_s=0.05)
    s = eng.submit({"tokens": [5, 9, 3], "max_new_tokens": 60})
    eng.step()
    eng.release(s)  # last consumer gone
    time.sleep(0.08)
    _drain(eng, rounds=5)
    assert s.done and s.cancelled
    assert eng.stats()["used_pages"] == 0


def test_save_restore_resumes_generation():
    """Fast chaos unit: a replica dies mid-decode; a new engine restores
    the __rt_save__ snapshot, re-prefills prompt + known tokens, and a
    re-attached consumer (same request_id, emit_from past what it saw)
    receives the identical remainder — at most one duplicated boundary."""
    eng = _engine()
    s = eng.submit({"tokens": [5, 9, 3], "max_new_tokens": 6,
                    "request_id": "r1"})
    for _ in range(3):
        eng.step()
    k = len(s.generated)
    assert 0 < k < 6
    snap = eng.save_state()

    eng2 = _engine(params=eng._params)
    eng2.restore_state(snap)
    s2 = eng2.submit({"tokens": [5, 9, 3], "max_new_tokens": 6,
                      "request_id": "r1", "emit_from": k})
    out = []
    t = threading.Thread(
        target=lambda: out.extend(eng2.iter_tokens(s2, max(0, k - 1))))
    t.start()
    _drain(eng2)
    t.join(10)
    assert not t.is_alive()
    _assert_greedy(eng, [5, 9, 3], s2.generated, n=6)
    # consumer resumed at k-1: exactly one duplicated token boundary,
    # delivered as coalesced multi-token items
    flat = [(o["i"] + j, t) for o in out
            for j, t in enumerate(o["tokens"])]
    assert [i for i, _ in flat] == list(range(k - 1, 6))
    assert [t for _, t in flat] == s2.generated[k - 1:]


def test_deadline_admission_refused():
    """The fourth deadline-enforcement site: an engine refuses admission
    when the remaining budget cannot cover prefill + one decode step —
    typed DeadlineExceededError(where=admission), no pages touched."""
    from ray_tpu._private import deadlines as dl
    from ray_tpu._private.errors import DeadlineExceededError

    eng = _engine()
    # cold engine: only an already-expired budget refuses
    token = dl.activate(time.time() - 0.5)
    try:
        with pytest.raises(DeadlineExceededError) as ei:
            eng.submit({"tokens": [1, 2], "max_new_tokens": 4})
    finally:
        dl.restore(token)
    assert ei.value.where == "admission"
    # warmed engine: a budget smaller than (prefill chunks + 1) x the
    # measured step EWMA refuses too — tokens that can't reach the
    # caller in time must not burn pages/lanes
    eng._step_ewma = 0.2  # 2 chunks + 1 decode = 0.6s needed
    with pytest.raises(DeadlineExceededError):
        eng.submit({"tokens": [1] * 16, "max_new_tokens": 4,
                    "deadline_ms": (time.time() + 0.2) * 1000.0})
    # a roomy budget admits normally
    s = eng.submit({"tokens": [1, 2], "max_new_tokens": 2,
                    "deadline_ms": (time.time() + 60.0) * 1000.0})
    assert s.deadline > 0
    _drain(eng)
    assert eng.stats()["used_pages"] == 0
    assert eng.stats()["deadline_expired"] >= 2


def test_deadline_expiry_mid_decode_recycles_pages():
    """An in-flight sequence past its deadline is cancelled by the
    engine sweep: its consumer gets the typed error and its KV pages
    return to the free pool (asserted via the ray_tpu_llm_kv_pages
    gauge, not just stats)."""
    from ray_tpu._private.errors import DeadlineExceededError
    from ray_tpu._private.metrics import llm_metrics

    eng = _engine()
    pages_gauge = llm_metrics()[1]

    def gauge(state):
        for k, v in pages_gauge._values.items():
            if ("state", state) in k:
                return v
        return None

    eng._set_gauges()
    free_baseline = gauge("free")
    s = eng.submit({"tokens": [5, 9, 3], "max_new_tokens": 60,
                    "deadline_ms": (time.time() + 0.15) * 1000.0})
    for _ in range(3):
        eng.step()
    assert not s.done and eng.stats()["used_pages"] > 0
    time.sleep(0.2)  # let the deadline pass
    eng.step()  # sweep runs at step start
    assert s.done and s.cancelled
    assert isinstance(s.error, DeadlineExceededError)
    assert s.error.where == "running"
    with pytest.raises(DeadlineExceededError):
        list(eng.iter_tokens(s, len(s.generated)))
    assert eng.stats()["used_pages"] == 0
    assert gauge("free") == free_baseline, "kv pages not back to baseline"


def test_loop_single_flight_and_stop():
    eng = _engine()
    t = threading.Thread(target=eng.run_loop, daemon=True)
    t.start()
    deadline = time.time() + 5
    while not eng.stats()["loop_running"] and time.time() < deadline:
        time.sleep(0.01)
    assert eng.stats()["loop_running"]
    # second install is a no-op (controller-restart re-ensure)
    assert eng.run_loop() == {"already_running": True}
    s = eng.submit({"tokens": [5, 9, 3], "max_new_tokens": 4})
    toks = [t for o in eng.iter_tokens(s) for t in o["tokens"]]
    _assert_greedy(eng, [5, 9, 3], toks, n=4)
    eng.stop()
    t.join(5)
    assert not t.is_alive()


# --------------------------------------------- prefix sharing (CoW pages)


def _gauge_value(state):
    from ray_tpu._private.metrics import llm_metrics

    pages_gauge = llm_metrics()[1]
    for k, v in pages_gauge._values.items():
        if ("state", state) in k:
            return v
    return None


def test_prefix_sharing_decode_identity():
    """The tentpole's correctness gate: a second sequence admitted onto
    SHARED physical KV pages (full-page hits) plus a copy-on-write
    split for a mid-page divergence decodes token-identically to the
    teacher-forcing full forward."""
    eng = _engine()
    base = list(range(1, 25))  # 3 full pages at page_size=8
    s1 = eng.submit({"tokens": base, "max_new_tokens": 6,
                     "request_id": "p1"})
    for _ in range(4):
        eng.step()  # s1 past prefill: its pages are registered
    assert len(eng._prefix_index) == 3
    # identical prompt: 2 full shared pages + a CoW extension of 7
    # tokens (one token always left to prefill for first-token logits)
    s2 = eng.submit({"tokens": base, "max_new_tokens": 6,
                     "request_id": "p2"})
    # mid-page divergence: shares 2 full pages, CoW-copies 4 tokens
    div = base[:20] + [60, 61, 62, 63]
    eng.step()
    s3 = eng.submit({"tokens": div, "max_new_tokens": 6,
                     "request_id": "p3"})
    _drain(eng)
    st = eng.stats()
    assert st["prefix_hits"] == 2 and st["cow_splits"] == 2, st
    assert st["prefix_tokens_shared"] == 23 + 20, st
    _assert_greedy(eng, base, s1.generated, n=6)
    _assert_greedy(eng, base, s2.generated, n=6)
    assert list(s1.generated) == list(s2.generated)
    _assert_greedy(eng, div, s3.generated, n=6)
    assert st["used_pages"] == 0 and st["free_pages"] == 32, st


def test_prefix_sharing_flag_off():
    eng = _engine(prefix_sharing=False)
    base = list(range(1, 25))
    s1 = eng.submit({"tokens": base, "max_new_tokens": 4})
    for _ in range(4):
        eng.step()
    s2 = eng.submit({"tokens": base, "max_new_tokens": 4})
    _drain(eng)
    st = eng.stats()
    assert st["prefix_hits"] == 0 and st["shared_pages"] == 0
    assert list(s1.generated) == list(s2.generated)


def test_shared_pages_recycle_only_at_refcount_zero():
    """The refcount hard paths: with two sequences sharing prefix
    pages, killing one — disconnect-cancel, mid-decode deadline
    expiry, or abandoned-consumer death (the replica-OOM analogue:
    the consumer process vanishes and the grace sweep fires) — must
    NOT recycle the shared pages while the survivor decodes on them;
    the kv-pages gauge returns to baseline only when BOTH are gone."""
    from ray_tpu._private.errors import DeadlineExceededError

    base = list(range(1, 25))

    def run_pair(eng, kill, second_req=None):
        eng._set_gauges()
        free_baseline = _gauge_value("free")
        s1 = eng.submit({"tokens": base, "max_new_tokens": 40,
                         "request_id": "k1"})
        for _ in range(4):
            eng.step()
        req2 = {"tokens": base, "max_new_tokens": 6,
                "request_id": "k2", **(second_req or {})}
        s2 = eng.submit(req2)
        eng.step()
        assert eng.stats()["prefix_hits"] == 1
        shared = [p for p in s2.block_table
                  if eng._page_refs[p] > 1]
        assert shared, "second sequence landed on no shared pages"
        assert eng.stats()["shared_pages"] == len(shared)
        kill(eng, s1)  # first holder dies mid-decode
        assert s1.done and s1.cancelled
        for p in shared:
            assert eng._page_refs[p] == 1, \
                "shared page recycled while the survivor holds it"
        _drain(eng)
        assert s2.done and not s2.cancelled
        _assert_greedy(eng, base, s2.generated, n=6)
        st = eng.stats()
        assert st["used_pages"] == 0 and st["shared_pages"] == 0, st
        eng._set_gauges()
        assert _gauge_value("free") == free_baseline, \
            "kv pages gauge not back to baseline"

    # disconnect-cancel (client dropped the stream)
    run_pair(_engine(), lambda e, s: e.cancel("k1"))

    # mid-decode deadline expiry (PR-13 sweep)
    def expire(e, s):
        s.deadline = time.time() - 0.01
        e.step()  # sweep runs at step start
        assert isinstance(s.error, DeadlineExceededError)

    run_pair(_engine(), expire,
             second_req={"deadline_ms": (time.time() + 60.0) * 1000.0})

    # abandoned consumer past the grace window (replica-OOM analogue)
    def abandon(e, s):
        e.release(s)
        time.sleep(0.08)
        e.step()

    run_pair(_engine(detach_grace_s=0.05), abandon)


# ------------------------------------------------- disaggregated prefill


def test_disagg_prefill_ship_attach_identity():
    """Engine-level disaggregation: prefill_request on engine P
    exports the KV pages, the pack/unpack wire format round-trips
    byte-checksummed, and engine D attaches the shipped pages by
    request_id, emits the shipped first token, and decodes
    token-identically to the full forward — without ever running
    prefill itself."""
    from ray_tpu._private.object_transfer import (pack_kv_pages,
                                                  unpack_kv_pages)

    P = _engine()
    D = _engine(params=P._params)
    prompt = list(range(2, 21))  # 19 tokens -> 3 pages shipped
    payload = P.prefill_request({"tokens": prompt, "max_new_tokens": 6,
                                 "request_id": "ship1"})
    assert payload["meta"]["n"] == len(prompt)
    assert payload["meta"]["pages"] == 3
    stp = P.stats()
    assert stp["kv_pages_shipped_out"] == 3 and stp["used_pages"] == 0
    # the wire format: magic + crc32 header, verified on unpack
    buf = pack_kv_pages(payload["meta"], payload["rows"])
    meta, rows = unpack_kv_pages(buf)
    assert meta["first_token"] == payload["meta"]["first_token"]

    s = D.submit({"tokens": prompt, "max_new_tokens": 6,
                  "request_id": "ship1"}, kv_pack=(meta, rows))
    _drain(D)
    assert s.done and len(s.generated) == 6
    # first generated token is the prefill replica's shipped token
    assert s.generated[0] == meta["first_token"]
    _assert_greedy(D, prompt, s.generated, n=6)
    std = D.stats()
    assert std["kv_pages_shipped_in"] == 3 and std["used_pages"] == 0


def test_disagg_kv_pack_corruption_detected():
    from ray_tpu._private.object_transfer import (TransferError,
                                                  pack_kv_pages,
                                                  unpack_kv_pages)

    P = _engine()
    payload = P.prefill_request({"tokens": [5, 9, 3, 7],
                                 "max_new_tokens": 2})
    buf = bytearray(pack_kv_pages(payload["meta"], payload["rows"]))
    buf[len(buf) // 2] ^= 0xFF
    with pytest.raises(TransferError):
        unpack_kv_pages(bytes(buf))


def test_disagg_mismatched_pack_falls_back_to_local_prefill():
    """A shipment that does not describe the request's prompt is
    discarded — the sequence prefills locally and still decodes
    correctly (disaggregation must never be a correctness risk)."""
    P = _engine()
    D = _engine(params=P._params)
    payload = P.prefill_request({"tokens": [5, 9, 3, 7],
                                 "max_new_tokens": 2})
    other = [1, 2, 3, 4, 5, 6]
    s = D.submit({"tokens": other, "max_new_tokens": 4},
                 kv_pack=(payload["meta"], payload["rows"]))
    _drain(D)
    _assert_greedy(D, other, s.generated, n=4)
    assert D.stats()["kv_pages_shipped_in"] == 0


# ------------------------------------------------- serve.batch timer fix


def test_batch_full_flushes_on_notify_not_timer():
    """A batch that fills to max_batch_size must flush immediately on
    the submitting thread's notify — with a 30s wait timer, the old
    poll-the-clock flusher passes only if the notify path works."""
    from ray_tpu.serve.api import _BatchState

    calls = []
    state = _BatchState(4, 30.0)

    def call(items):
        calls.append(list(items))
        return [x * 2 for x in items]

    results = []
    threads = [threading.Thread(
        target=lambda i=i: results.append(state.submit(i, call)))
        for i in range(4)]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert all(not t.is_alive() for t in threads), \
        "full batch waited out the 30s timer"
    assert time.monotonic() - t0 < 8.0
    assert sorted(results) == [0, 2, 4, 6]
    assert len(calls) == 1 and sorted(calls[0]) == [0, 1, 2, 3]


def test_batch_timer_deadline_uses_injected_clock():
    """Deadline math runs on the injectable clock: jumping the fake
    clock past the deadline flushes a partial batch with no real
    sleeping."""
    from ray_tpu.serve.api import _BatchState

    now = [0.0]
    state = _BatchState(8, 5.0, clock=lambda: now[0])
    calls = []

    def call(items):
        calls.append(list(items))
        return list(items)

    result = []
    t = threading.Thread(target=lambda: result.append(state.submit(1, call)))
    t.start()
    time.sleep(0.2)  # flusher parked on the condition
    assert not calls, "flushed before deadline with a frozen clock"
    now[0] = 10.0  # past the 5s deadline
    with state.lock:
        state.full.notify()
    t.join(5)
    assert not t.is_alive() and result == [1] and calls == [[1]]


# ------------------------------------------------------------ cluster e2e


@pytest.fixture(scope="module")
def llm_cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    deployed = []

    def deploy(name, **kw):
        kw.setdefault("model", dict(MODEL))
        kw.setdefault("dtype", jnp.float32)
        kw.setdefault("page_size", 8)
        kw.setdefault("num_pages", 33)
        kw.setdefault("max_batch", 4)
        kw.setdefault("prefill_chunk", 8)
        extra = {k: kw.pop(k) for k in ("num_replicas",
                                        "max_ongoing_requests",
                                        "ray_actor_options")
                 if k in kw}
        handle = serve.run(serve.llm_deployment(name, **extra, **kw))
        deployed.append(name)
        # warm every replica's jit cache (prefill + decode shapes) so
        # timed assertions never pay a compile
        for _ in range(extra.get("num_replicas", 1)):
            for ref in handle.stream({"tokens": [1], "max_new_tokens": 1}):
                ray_tpu.get(ref, timeout=120)
        return handle

    host, port = serve.start_http()
    try:
        yield {"deploy": deploy, "host": host, "port": port}
    finally:
        try:
            serve.shutdown_http()
        except Exception:
            pass
        for name in deployed:
            try:
                serve.delete(name)
            except Exception:
                pass
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def _sse_request(host, port, name, payload, timeout=60, headers=None):
    """One streaming request over a raw socket; returns (status, items,
    sock, resp).  Caller closes sock (or uses _read_sse to drain)."""
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("POST", f"/{name}", body=json.dumps(payload),
                 headers={"Content-Type": "application/json",
                          "Accept": "text/event-stream",
                          **(headers or {})})
    resp = conn.getresponse()
    return conn, resp


def _read_items(resp):
    return [json.loads(ln) for ln in resp.read().decode().splitlines()
            if ln.strip()]


def test_llm_sse_end_to_end(llm_cluster, llm_big):
    """Tokens stream over SSE through proxy -> handle.stream_async ->
    pinned decode loop, token-identical to the non-batched forward
    (same seed => same params as the local oracle)."""
    h = llm_big
    local = _engine()  # same seed: identical params for the oracle
    conn, resp = _sse_request(llm_cluster["host"], llm_cluster["port"],
                              "llm_big",
                              {"tokens": [5, 9, 3], "max_new_tokens": 6})
    assert resp.status == 200
    items = _read_items(resp)
    conn.close()
    flat = [(it["i"] + j, t) for it in items
            for j, t in enumerate(it["tokens"])]
    _assert_greedy(local, [5, 9, 3], [t for _, t in flat], n=6)
    assert [i for i, _ in flat] == list(range(6))
    assert items[-1]["done"] is True
    st = ray_tpu.get(h.method("stats")(), timeout=30)
    assert st["loop_running"] and st["used_pages"] == 0


@pytest.fixture(scope="module")
def llm_big(llm_cluster):
    """One bigger-context deployment shared by the shed and disconnect
    tests (replica processes pay ~10s of eager flax init here — one
    deployment, two tests)."""
    return llm_cluster["deploy"]("llm_big",
                                 model=dict(MODEL, max_seq_len=256),
                                 num_pages=33, max_queue=1,
                                 detach_grace_s=0.3)


def test_llm_queue_full_sheds_503(llm_cluster, llm_big):
    """Admission past the bounded queue answers 503 BEFORE any SSE
    bytes (the first-item prefetch maps LLMOverloadedError to the shed
    gate) — and below capacity a queued request gets 200, not shed."""
    h = llm_big
    host, port = llm_cluster["host"], llm_cluster["port"]
    # hold most of the page budget with a long generation (26 of 32
    # usable pages)...
    c1, r1 = _sse_request(host, port, "llm_big",
                          {"tokens": [1, 2, 3], "max_new_tokens": 200})
    assert r1.status == 200
    r1.read(1)  # first token arrived: sequence is active
    # ...then a request too big for the REMAINING pages parks in the
    # single queue slot (on a thread: its response line only arrives
    # once its first token does, i.e. after r1 finishes)
    q_result = {}

    def _queued_request():
        c2, r2 = _sse_request(host, port, "llm_big",
                              {"tokens": [4, 5], "max_new_tokens": 60},
                              timeout=120)
        q_result["status"] = r2.status
        q_result["items"] = _read_items(r2)
        c2.close()

    t = threading.Thread(target=_queued_request)
    t.start()
    deadline = time.time() + 60
    while time.time() < deadline:
        if ray_tpu.get(h.method("stats")(), timeout=30)["queued"] >= 1:
            break
        time.sleep(0.05)
    assert ray_tpu.get(h.method("stats")(), timeout=30)["queued"] >= 1
    # the third concurrent stream sheds with a real status code
    c3, r3 = _sse_request(host, port, "llm_big",
                          {"tokens": [6], "max_new_tokens": 2})
    assert r3.status == 503, r3.status
    c3.close()
    r1.read()  # drain the long stream: frees pages for r2
    c1.close()
    t.join(120)
    assert not t.is_alive()
    # below capacity = no shed: the queued request completed normally
    assert q_result["status"] == 200
    assert sum(len(it["tokens"]) for it in q_result["items"]) == 60


@pytest.fixture(scope="module")
def llm_slow_steps(llm_cluster):
    """A deliberately BIGGER model (~15-40ms/step vs ~2ms for the tiny
    config) shared by the disconnect and deadline tests: both need the
    decode to still be RUNNING when their trigger lands — the tiny
    config's 240 tokens can finish before a disconnect RST or a
    sub-second deadline is even noticed."""
    return llm_cluster["deploy"]("llm_drop",
                                 model=dict(MODEL, dim=192, n_layers=4,
                                            hidden_dim=512,
                                            max_seq_len=256),
                                 num_pages=33, detach_grace_s=0.3)


def test_llm_disconnect_frees_kv_pages(llm_cluster, llm_slow_steps):
    """Client vanishes mid-stream: the chunk writer's failure closes the
    stream chain, the handle cancels the replica-side generator, and
    the engine recycles the sequence's pages after the grace window —
    instead of decoding another ~200 tokens for nobody."""
    h = llm_slow_steps
    before = ray_tpu.get(h.method("stats")(), timeout=30)
    conn, resp = _sse_request(llm_cluster["host"], llm_cluster["port"],
                              "llm_drop",
                              {"tokens": [5, 9, 3], "max_new_tokens": 240})
    assert resp.status == 200
    resp.read(1)  # at least one token delivered
    conn.sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                         b"\x01\x00\x00\x00\x00\x00\x00\x00")  # RST
    conn.close()
    deadline = time.time() + 60
    st = {}
    while time.time() < deadline:
        st = ray_tpu.get(h.method("stats")(), timeout=30)
        if st["cancelled"] > before["cancelled"] \
                and st["used_pages"] == 0:
            break
        time.sleep(0.1)
    assert st.get("cancelled", 0) > before["cancelled"] \
        and st.get("used_pages") == 0, (before, st)


def test_llm_stream_deadline_expires_mid_decode(llm_cluster,
                                                llm_slow_steps):
    """Deadline-vs-stream interaction (ISSUE 14 satellite): an SSE
    stream whose X-Request-Deadline-Ms budget expires mid-decode closes
    with a TYPED error chunk (DeadlineExceededError, never a silent
    truncation) AND the sequence's KV pages recycle back to baseline."""
    h = llm_slow_steps
    before = ray_tpu.get(h.method("stats")(), timeout=30)
    # self-calibrating budget: decode speed varies box to box, so walk
    # the budget down until the deadline bites mid-stream (a too-roomy
    # budget lets the whole stream finish; that attempt just retries
    # tighter).  TTFT is warm (<~50ms), so even the tightest budget
    # still covers admission + first token.
    token_items = err_items = None
    for budget_s in (0.8, 0.4, 0.2, 0.1):
        deadline_ms = (time.time() + budget_s) * 1000.0
        conn, resp = _sse_request(
            llm_cluster["host"], llm_cluster["port"], "llm_drop",
            {"tokens": [5, 9, 3], "max_new_tokens": 240},
            headers={"X-Request-Deadline-Ms": str(deadline_ms)})
        assert resp.status == 200, \
            f"budget {budget_s}s did not even cover TTFT"
        items = _read_items(resp)
        conn.close()
        token_items = [it for it in items if "i" in it]
        err_items = [it for it in items if "error" in it]
        if sum(len(it["tokens"]) for it in token_items) < 240:
            break  # the deadline bit mid-decode
    assert token_items, "no tokens before the deadline"
    assert sum(len(it["tokens"]) for it in token_items) < 240, \
        "stream finished under every budget — deadline never bit"
    assert err_items and "DeadlineExceededError" in err_items[-1]["error"], \
        (items[-3:] if items else items)
    # KV pages back to baseline (the engine expired the sequence and
    # recycled; the free-page gauge is stats' source of truth)
    deadline = time.time() + 60
    st = {}
    while time.time() < deadline:
        st = ray_tpu.get(h.method("stats")(), timeout=30)
        if st["used_pages"] == 0 \
                and st["deadline_expired"] > before["deadline_expired"]:
            break
        time.sleep(0.1)
    assert st.get("used_pages") == 0, st
    assert st.get("deadline_expired", 0) > before["deadline_expired"], st


@pytest.mark.slow
def test_llm_replica_death_resumes_stream(llm_cluster):
    """Chaos ride: SIGKILL the replica worker mid-decode.  The proxy's
    resumable retry re-submits with emit_from on a survivor, which
    re-prefills (greedy decode is deterministic) — the client's SSE
    stream is the exact token sequence with at most one duplicated
    token boundary."""
    llm_cluster["deploy"]("llm_chaos", num_replicas=2,
                          model=dict(MODEL, max_seq_len=256),
                          num_pages=40, detach_grace_s=5.0)
    n = 120
    conn, resp = _sse_request(llm_cluster["host"], llm_cluster["port"],
                              "llm_chaos",
                              {"tokens": [5, 9, 3], "max_new_tokens": n,
                               "request_id": "chaos1"}, timeout=120)
    assert resp.status == 200
    # stream a few items, then SIGKILL the serving replica's worker
    buf = b""
    while buf.count(b"\n") < 8:
        buf += resp.read1(4096)
    w = ray_tpu.api._worker()
    victims = []
    for a in w.head.call("list_actors", timeout=30)["actors"]:
        if a.get("name", "").startswith("serve:llm_chaos") \
                and a.get("state") == "ALIVE":
            victims.append(a)
    # kill whichever replica holds the live sequence
    killed = False
    for a in victims:
        try:
            hdl = ray_tpu.get_actor(a["name"])
            st = ray_tpu.get(
                hdl.handle_request.remote("stats", (), {}), timeout=30)
            if st["active"] >= 1:
                ray_tpu.kill(hdl)
                killed = True
                break
        except Exception:
            continue
    assert killed, "no replica owned the live sequence"
    rest = resp.read()  # proxy resumes on a survivor
    conn.close()
    lines = [ln for ln in (buf + rest).decode().splitlines() if ln.strip()]
    items = [json.loads(ln) for ln in lines]
    errs = [it for it in items if not (isinstance(it, dict) and "i" in it)]
    assert not errs, f"stream carried errors: {errs}"
    flat = [(it["i"] + j, t) for it in items
            for j, t in enumerate(it["tokens"])]
    idx = [i for i, _ in flat]
    # at-most-one duplicated boundary, then strictly resuming
    dups = [i for i in set(idx) if idx.count(i) > 1]
    assert len(dups) <= 1, idx
    seen = dict(flat)
    assert sorted(seen) == list(range(n)), sorted(seen)[-5:]
    local = _engine()  # same seed: identical params for the oracle
    _assert_greedy(local, [5, 9, 3], [seen[i] for i in range(n)], n=n)


# ----------------------------------- disaggregated prefill: e2e + chaos


def test_disagg_kv_ship_survives_corrupt_transfer(tmp_path, monkeypatch):
    """Acceptance E2E: a prompt prefilled on one engine (the prefill
    replica) ships its packed KV pages over the bulk transfer plane;
    the transfer is chaos-corrupted ONCE, caught by the seal-time CRC,
    re-pulled from an alternate holder, unpacked (byte-checksummed wire
    format), and attached on a second engine (the decode replica) —
    whose decode is token-identical to the full forward."""
    import asyncio
    import uuid

    from ray_tpu._private import fault_injection
    from ray_tpu._private.head import HeadService
    from ray_tpu._private.node_agent import NodeAgent
    from ray_tpu._private.object_transfer import (pack_kv_pages,
                                                  unpack_kv_pages)

    P = _engine()
    D = _engine(params=P._params)
    prompt = list(range(2, 21))
    payload = P.prefill_request({"tokens": prompt, "max_new_tokens": 6,
                                 "request_id": "kvchaos"})
    buf = pack_kv_pages(payload["meta"], payload["rows"])
    MB = 1024 * 1024
    # the tiny model's KV pack is a few tens of KB — below the default
    # 1MB directory floor no holder would ever be announced, and the
    # alternate-holder retry needs the directory to know both copies
    monkeypatch.setenv("RT_LOCALITY_MIN_BYTES", "1024")

    async def ship():
        head = HeadService()
        head_port = await head.start()
        agents = []
        for i in range(3):
            ag = NodeAgent(("127.0.0.1", head_port), str(tmp_path),
                           {"CPU": 1},
                           arena_path=str(
                               tmp_path /
                               f"arena-{i}-{uuid.uuid4().hex[:6]}"),
                           capacity=32 * MB)
            await ag.start()
            agents.append(ag)
        a, b, c = agents
        try:
            loc = a.store.create("kvship", len(buf), primary=True)
            if loc["location"] == "shm":
                a.store.arena.view[loc["offset"]:loc["offset"] + len(buf)] \
                    = buf
            else:
                with open(loc["path"], "r+b") as f:
                    f.write(buf)
            a.store.seal("kvship")
            # a second holder so an alternate exists in the directory
            r = await b.rpc_ensure_local("kvship", src=[a.host, a.port])
            assert r.get("ok"), r
            deadline = time.monotonic() + 10
            while len(head.dir.locations("kvship")) < 2:
                assert time.monotonic() < deadline, "no second holder"
                await asyncio.sleep(0.05)
            fault_injection.inject("xfer.send", "corrupt", count=1,
                                   target="kvship")
            r = await c.rpc_ensure_local("kvship")
            assert r.get("ok"), r
            assert c.xfer_stats["checksum_failures"] == 1
            assert c.xfer_stats["alt_source_retries"] == 1
            entry = c.store.objects["kvship"]
            if entry.location == "shm":
                return bytes(c.store.arena.view[
                    entry.offset:entry.offset + len(buf)])
            with open(entry.path, "rb") as f:
                return f.read()
        finally:
            fault_injection.clear()
            for ag in agents:
                try:
                    await ag.stop()
                except Exception:
                    pass
            await head.stop()

    data = asyncio.run(ship())
    assert data == buf  # survived the corrupted transfer byte-exact
    meta, rows = unpack_kv_pages(data)
    s = D.submit({"tokens": prompt, "max_new_tokens": 6,
                  "request_id": "kvchaos"}, kv_pack=(meta, rows))
    _drain(D)
    assert s.done and s.generated[0] == meta["first_token"]
    _assert_greedy(D, prompt, s.generated, n=6)
    assert D.stats()["kv_pages_shipped_in"] == 3


def test_llm_disaggregated_prefill_serve_e2e(llm_cluster):
    """llm_deployment(prefill_replicas=1) deploys TWO pools; an SSE
    request's prefill phase runs on the dedicated pool (the handle's
    prefill hop), its KV pages ship by kv_ref, and the decode replica
    attaches them — token-identical to the local oracle, with the
    shipped-page counters moving on both sides."""
    h = llm_cluster["deploy"]("llm_disagg", prefill_replicas=1,
                              detach_grace_s=5.0)
    pf = serve.get_handle("llm_disagg-prefill")
    prompt = list(range(3, 22))  # 19 tokens -> 3 shipped pages
    conn, resp = _sse_request(llm_cluster["host"], llm_cluster["port"],
                              "llm_disagg",
                              {"tokens": prompt, "max_new_tokens": 6})
    assert resp.status == 200
    items = _read_items(resp)
    conn.close()
    flat = [(it["i"] + j, t) for it in items
            for j, t in enumerate(it["tokens"])]
    assert [i for i, _ in flat] == list(range(6))
    local = _engine()  # same seed: identical params for the oracle
    _assert_greedy(local, prompt, [t for _, t in flat], n=6)
    # the decode replica imported the shipped pages instead of
    # prefilling; the prefill replica exported them and recycled
    std = ray_tpu.get(h.method("stats")(), timeout=30)
    assert std["kv_pages_shipped_in"] >= 3, std
    stp = ray_tpu.get(pf.method("stats")(), timeout=30)
    assert stp["kv_pages_shipped_out"] >= 3, stp
    assert stp["used_pages"] == 0 and std["used_pages"] == 0
