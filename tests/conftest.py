"""Test fixtures.

Multi-device tests run on a virtual 8-device CPU mesh
(reference test strategy: SURVEY.md §4.3 — JAX CPU
``xla_force_host_platform_device_count`` emulates multi-device meshes
without hardware; the driver dry-runs the real multi-chip path).
"""

import os

# Must be set before jax is imported anywhere in the test process tree.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def local_cluster():
    """A started single-node ray_tpu cluster; shuts down after the test."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
