"""Test fixtures.

Multi-device tests run on a virtual 8-device CPU mesh
(reference test strategy: SURVEY.md §4.3 — JAX CPU
``xla_force_host_platform_device_count`` emulates multi-device meshes
without hardware; the driver dry-runs the real multi-chip path).

This host's sitecustomize registers the axon TPU backend at interpreter
start; `jax.config.update("jax_platforms", "cpu")` overrides it for the
test process, and the forced JAX_PLATFORMS=cpu env makes spawned workers
skip the TPU plugin entirely (see spawn.install_jax_site_hook).
"""

import os

# Env for spawned daemons/workers (inherited): pure-CPU jax with a
# virtual 8-device mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


# --------------------------------------------------------- leak tripwire
# Per-module snapshots of this process's thread, socket, and RSS
# footprint.  A cluster that truly tears down returns all three to
# baseline; a leak (an EventLoopThread or RpcClient surviving shutdown,
# a cache pinning arena views) compounds module over module.  The
# signature is a rising LOW-WATER mark: a module snapshotted
# mid-teardown spikes high but the next quiet module drops back, while
# a genuine leak lifts the floor of every later snapshot — so compare
# window minima, not per-module deltas.  Thread/socket trips FAIL;
# the RSS trip is informational under tier-1 (-m 'not slow') and fails
# full runs, like the wall-clock tripwire — the allocator's reluctance
# to return pages makes RSS the noisiest of the three.

_RESOURCE_HISTORY = []  # (module_name, threads, sockets, rss_mb)
_LEAK_WINDOW = 5        # modules per comparison window
_LEAK_FLOOR = 25        # min rise between window floors that trips
_RSS_FLOOR_MB = 300     # min RSS-floor rise (MiB) that trips


def _read_rss_mb():
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return (pages * os.sysconf("SC_PAGE_SIZE")) // (1024 * 1024)
    except (OSError, ValueError, IndexError):
        return 0


def _count_threads_sockets():
    import gc
    import threading

    # A shut-down cluster's event-loop socketpairs close on GC, not on
    # shutdown(): dead drivers pile up until a gen-2 collection, whose
    # period can exceed the comparison window — without this collect the
    # floor rises on GC lag alone.  Truly pinned components (a global
    # root holding a worker/loop) survive the collect and still trip.
    gc.collect()
    threads = threading.active_count()
    sockets = 0
    try:
        fd_dir = "/proc/self/fd"
        for fd in os.listdir(fd_dir):
            try:
                if os.readlink(os.path.join(fd_dir, fd)).startswith(
                        "socket:"):
                    sockets += 1
            except OSError:
                pass
    except OSError:
        pass
    return threads, sockets


def _monotonic_leak(history, window=_LEAK_WINDOW, floor=_LEAK_FLOOR,
                    rss_floor=_RSS_FLOOR_MB):
    """(kind, tail) when a resource's low-water mark over the last
    `window` modules sits >= its floor above its low-water mark over
    the preceding `window` modules, else None.  Minima filter transient
    spikes (a module snapshotted while its cluster is still closing);
    a real leak raises every later module's floor.  History tuples may
    omit the trailing rss_mb field (older snapshots).  Pure so the
    detector itself is unit-testable."""
    if len(history) < 2 * window:
        return None
    prev = history[-2 * window:-window]
    tail = history[-window:]
    for idx, kind, fl in ((1, "threads", floor), (2, "sockets", floor),
                          (3, "rss_mb", rss_floor)):
        if any(len(h) <= idx for h in prev + tail):
            continue
        if (min(h[idx] for h in tail)
                - min(h[idx] for h in prev)) >= fl:
            return kind, tail
    return None


@pytest.fixture(scope="module", autouse=True)
def resource_leak_tripwire(request):
    """Snapshot thread/socket/RSS after every test module and flag
    monotonic growth across cluster setup/teardown cycles.  Thread and
    socket trips fail outright; the RSS trip warns under tier-1
    (-m 'not slow') and fails full runs."""
    yield
    threads, sockets = _count_threads_sockets()
    _RESOURCE_HISTORY.append(
        (request.module.__name__, threads, sockets, _read_rss_mb()))
    hit = _monotonic_leak(_RESOURCE_HISTORY)
    if hit is None:
        return
    kind, tail = hit
    detail = ", ".join(f"{name}={t}/{s}/{r}MB" for name, t, s, r in tail)
    msg = (f"resource leak tripwire: the {kind} low-water mark rose "
           f">= {_RSS_FLOOR_MB if kind == 'rss_mb' else _LEAK_FLOOR} "
           f"across the last {_LEAK_WINDOW} test modules "
           f"(module=threads/sockets/rss: {detail}) — a cluster "
           f"component is surviving shutdown()")
    if kind == "rss_mb" and _is_tier1(request.config):
        import warnings

        warnings.warn(msg)
        return
    pytest.fail(msg)


# -------------------------------------------- module wall-clock tripwire
# Tier-1 runs against an 870s wall budget that is nearly full; this
# makes budget pressure visible per PR instead of discovered as a suite
# timeout.  Every run prints a per-module duration table in the pytest
# terminal summary; any single FAST module (its non-slow tests only)
# above _MODULE_BUDGET_S is flagged — informationally under tier-1
# (-m 'not slow'), as a session FAILURE otherwise, so full runs catch
# the regression before the tier-1 driver run hits the wall.

_MODULE_BUDGET_S = 45.0
_MODULE_DURATIONS = {}  # module path -> accumulated fast-test seconds
_SLOW_NODES = set()     # nodeids carrying @pytest.mark.slow


def _module_budget_violations(durations, budget=_MODULE_BUDGET_S):
    """[(module, seconds)] over budget, worst first.  Pure so the
    tripwire itself is unit-testable."""
    return sorted(((m, d) for m, d in durations.items() if d > budget),
                  key=lambda kv: -kv[1])


def _is_tier1(config) -> bool:
    # the tier-1 invocation deselects slow tests via -m 'not slow'
    return "not slow" in (getattr(config.option, "markexpr", "") or "")


def pytest_collection_modifyitems(config, items):
    for it in items:
        if it.get_closest_marker("slow"):
            _SLOW_NODES.add(it.nodeid)


def pytest_runtest_logreport(report):
    if report.when not in ("setup", "call", "teardown"):
        return
    if report.nodeid in _SLOW_NODES:
        return  # slow tests have their own (non-tier-1) time budget
    mod = report.nodeid.split("::", 1)[0]
    _MODULE_DURATIONS[mod] = (_MODULE_DURATIONS.get(mod, 0.0)
                              + (report.duration or 0.0))


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    if not _MODULE_DURATIONS:
        return
    tr = terminalreporter
    ranked = sorted(_MODULE_DURATIONS.items(), key=lambda kv: -kv[1])
    tr.section(f"per-module wall clock (fast tests; budget "
               f"{_MODULE_BUDGET_S:.0f}s/module)")
    for mod, d in ranked[:15]:
        flag = "  << OVER BUDGET" if d > _MODULE_BUDGET_S else ""
        tr.write_line(f"{d:8.1f}s  {mod}{flag}")
    total = sum(_MODULE_DURATIONS.values())
    tr.write_line(f"{total:8.1f}s  TOTAL (tier-1 wall budget: 870s)")
    over = _module_budget_violations(_MODULE_DURATIONS)
    if over:
        names = ", ".join(f"{m} ({d:.0f}s)" for m, d in over)
        if _is_tier1(config):
            tr.write_line(
                f"WARNING: fast module(s) over the {_MODULE_BUDGET_S:.0f}s "
                f"budget: {names} — move tests behind @pytest.mark.slow "
                f"or speed them up before the tier-1 suite hits its "
                f"870s wall")
        else:
            tr.write_line(
                f"ERROR: fast module(s) over the {_MODULE_BUDGET_S:.0f}s "
                f"budget: {names} (failing the session; informational "
                f"under -m 'not slow')")


def pytest_sessionfinish(session, exitstatus):
    # the tripwire FAILS full (non-tier-1) runs so budget regressions
    # surface locally; under tier-1 it stays informational — the tier-1
    # driver run must never be failed retroactively by a watchdog
    if exitstatus != 0 or _is_tier1(session.config):
        return
    if _module_budget_violations(_MODULE_DURATIONS):
        session.exitstatus = 1


def force_cpu_jax():
    """In-process override: this interpreter may already have the TPU
    plugin registered (sitecustomize); select CPU before first use."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax


@pytest.fixture(scope="session")
def cpu_jax():
    return force_cpu_jax()


@pytest.fixture
def local_cluster():
    """A started single-node ray_tpu cluster; shuts down after the test."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
