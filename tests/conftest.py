"""Test fixtures.

Multi-device tests run on a virtual 8-device CPU mesh
(reference test strategy: SURVEY.md §4.3 — JAX CPU
``xla_force_host_platform_device_count`` emulates multi-device meshes
without hardware; the driver dry-runs the real multi-chip path).

This host's sitecustomize registers the axon TPU backend at interpreter
start; `jax.config.update("jax_platforms", "cpu")` overrides it for the
test process, and the forced JAX_PLATFORMS=cpu env makes spawned workers
skip the TPU plugin entirely (see spawn.install_jax_site_hook).
"""

import os

# Env for spawned daemons/workers (inherited): pure-CPU jax with a
# virtual 8-device mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


def force_cpu_jax():
    """In-process override: this interpreter may already have the TPU
    plugin registered (sitecustomize); select CPU before first use."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax


@pytest.fixture(scope="session")
def cpu_jax():
    return force_cpu_jax()


@pytest.fixture
def local_cluster():
    """A started single-node ray_tpu cluster; shuts down after the test."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
