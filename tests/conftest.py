"""Test fixtures.

Multi-device tests run on a virtual 8-device CPU mesh
(reference test strategy: SURVEY.md §4.3 — JAX CPU
``xla_force_host_platform_device_count`` emulates multi-device meshes
without hardware; the driver dry-runs the real multi-chip path).

This host's sitecustomize registers the axon TPU backend at interpreter
start; `jax.config.update("jax_platforms", "cpu")` overrides it for the
test process, and the forced JAX_PLATFORMS=cpu env makes spawned workers
skip the TPU plugin entirely (see spawn.install_jax_site_hook).
"""

import os

# Env for spawned daemons/workers (inherited): pure-CPU jax with a
# virtual 8-device mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


# --------------------------------------------------------- leak tripwire
# Per-module snapshots of this process's thread and socket counts.  A
# cluster that truly tears down returns both to baseline; a leak (an
# EventLoopThread or RpcClient surviving shutdown) compounds module
# over module.  The signature is a rising LOW-WATER mark: a module
# snapshotted mid-teardown spikes high but the next quiet module drops
# back, while a genuine leak lifts the floor of every later snapshot —
# so compare window minima, not per-module deltas.

_RESOURCE_HISTORY = []  # (module_name, threads, sockets)
_LEAK_WINDOW = 5        # modules per comparison window
_LEAK_FLOOR = 25        # min rise between window floors that trips


def _count_threads_sockets():
    import gc
    import threading

    # A shut-down cluster's event-loop socketpairs close on GC, not on
    # shutdown(): dead drivers pile up until a gen-2 collection, whose
    # period can exceed the comparison window — without this collect the
    # floor rises on GC lag alone.  Truly pinned components (a global
    # root holding a worker/loop) survive the collect and still trip.
    gc.collect()
    threads = threading.active_count()
    sockets = 0
    try:
        fd_dir = "/proc/self/fd"
        for fd in os.listdir(fd_dir):
            try:
                if os.readlink(os.path.join(fd_dir, fd)).startswith(
                        "socket:"):
                    sockets += 1
            except OSError:
                pass
    except OSError:
        pass
    return threads, sockets


def _monotonic_leak(history, window=_LEAK_WINDOW, floor=_LEAK_FLOOR):
    """(kind, tail) when a resource's low-water mark over the last
    `window` modules sits >= `floor` above its low-water mark over the
    preceding `window` modules, else None.  Minima filter transient
    spikes (a module snapshotted while its cluster is still closing);
    a real leak raises every later module's floor.  Pure so the
    detector itself is unit-testable."""
    if len(history) < 2 * window:
        return None
    prev = history[-2 * window:-window]
    tail = history[-window:]
    for idx, kind in ((1, "threads"), (2, "sockets")):
        if (min(h[idx] for h in tail)
                - min(h[idx] for h in prev)) >= floor:
            return kind, tail
    return None


@pytest.fixture(scope="module", autouse=True)
def resource_leak_tripwire(request):
    """Snapshot thread/socket counts after every test module and fail
    on monotonic growth across cluster setup/teardown cycles."""
    yield
    threads, sockets = _count_threads_sockets()
    _RESOURCE_HISTORY.append(
        (request.module.__name__, threads, sockets))
    hit = _monotonic_leak(_RESOURCE_HISTORY)
    if hit is not None:
        kind, tail = hit
        detail = ", ".join(f"{name}={t}/{s}" for name, t, s in tail)
        pytest.fail(
            f"resource leak tripwire: the {kind} low-water mark rose "
            f">= {_LEAK_FLOOR} across the last {_LEAK_WINDOW} test "
            f"modules (module=threads/sockets: {detail}) — a cluster "
            f"component is surviving shutdown()")


def force_cpu_jax():
    """In-process override: this interpreter may already have the TPU
    plugin registered (sitecustomize); select CPU before first use."""
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    return jax


@pytest.fixture(scope="session")
def cpu_jax():
    return force_cpu_jax()


@pytest.fixture
def local_cluster():
    """A started single-node ray_tpu cluster; shuts down after the test."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
