"""Memory-pressure resilience tests (ISSUE 15).

Fast units for the watchdog policy (fake sampler/clock — no cluster,
no real memory pressure), typed-error pickle round-trips, quarantine
protocol units against a live head, the Serve breaker integration, the
put-backpressure store path, and checksummed-transfer recovery on the
in-process two-agent harness.  End-to-end kill/retry/quarantine flows
drive a real cluster through the ``memory_monitor_test_usage_file``
hook (deterministic pressure, nothing allocated); the chaos
``worker.oom`` e2e uses the VIRTUAL node envelope
(``memory_monitor_node_total_bytes``) so a real allocation bomb trips a
256MB-scale "node" without stressing the host.
"""

import asyncio
import os
import pickle
import threading
import time
import uuid
from collections import deque

import pytest

import ray_tpu
from ray_tpu import OutOfMemoryError, PoisonedTaskError
from ray_tpu._private import memory_monitor
from ray_tpu._private.config import config
from ray_tpu._private.errors import RayWorkerError

MB = 1024 * 1024


# ------------------------------------------------------- victim policy units


def _s(wid, rss, seq=0, retriable=True, pinned=False, saving=False):
    return memory_monitor.WorkerSample(
        worker_id=wid, rss=rss, lease_seq=seq, retriable=retriable,
        pinned=pinned, saving=saving)


def test_pick_victim_highest_rss_retriable_first():
    samples = [_s("small", 10 * MB, seq=1),
               _s("big", 500 * MB, seq=2),
               _s("bigger_actor", 900 * MB, seq=3, retriable=False)]
    # the retriable hog dies before a LARGER non-retriable actor
    assert memory_monitor.pick_victim(samples).worker_id == "big"


def test_pick_victim_last_started_tiebreak():
    samples = [_s("older", 100 * MB, seq=1), _s("newer", 100 * MB, seq=9)]
    assert memory_monitor.pick_victim(samples).worker_id == "newer"


def test_pick_victim_pinned_and_saving_are_last_resort():
    samples = [_s("pipeline", 2000 * MB, seq=5, pinned=True),
               _s("snapshotting", 1500 * MB, seq=4, saving=True),
               _s("task", 50 * MB, seq=1)]
    assert memory_monitor.pick_victim(samples).worker_id == "task"
    # with ONLY pinned/saving workers left they do get picked (the
    # alternative is the kernel OOM killer taking the whole agent)
    assert memory_monitor.pick_victim(samples[:2]).worker_id == "pipeline"
    assert memory_monitor.pick_victim([]) is None


def test_pick_victim_non_retriable_before_pinned():
    samples = [_s("actor", 10 * MB, retriable=False),
               _s("dag_loop", 900 * MB, pinned=True)]
    assert memory_monitor.pick_victim(samples).worker_id == "actor"


def test_watchdog_threshold_and_kill_gap():
    clock = [100.0]
    wd = memory_monitor.OomWatchdog(threshold=0.9, min_kill_gap_s=1.0,
                                    clock=lambda: clock[0])
    samples = [_s("w1", 100 * MB), _s("w2", 50 * MB)]
    assert wd.tick(0.5, samples) is None          # under threshold
    assert wd.tick(None, samples) is None          # unreadable usage
    v = wd.tick(0.95, samples)
    assert v is not None and v.worker_id == "w1"
    clock[0] += 0.5
    assert wd.tick(0.99, samples) is None          # inside the kill gap
    clock[0] += 0.6
    assert wd.tick(0.99, samples).worker_id == "w1"
    assert wd.kills == 2


def test_self_poisoning_discriminator():
    # limit unknown (usage-file pressure): every kill counts
    assert memory_monitor.is_self_poisoning(10 * MB, 0)
    # aggregate-pressure victim: well under the ceiling, not counted
    assert not memory_monitor.is_self_poisoning(220 * MB, 435 * MB)
    # self-poisoning: the victim alone approaches the whole ceiling
    assert memory_monitor.is_self_poisoning(520 * MB, 435 * MB)
    assert memory_monitor.is_self_poisoning(int(0.95 * 435 * MB),
                                            435 * MB)


def test_usage_fraction_sources(tmp_path):
    f = tmp_path / "usage"
    f.write_text("0.42")
    assert memory_monitor.usage_fraction(str(f)) == pytest.approx(0.42)
    # virtual envelope: sum of worker RSS over the configured total
    assert memory_monitor.usage_fraction(
        "", 1000, worker_rss_sum=750) == pytest.approx(0.75)
    # real meminfo on Linux: a sane fraction
    frac = memory_monitor.usage_fraction("")
    assert frac is None or 0.0 <= frac <= 1.0


# -------------------------------------------------------- typed error units


def test_out_of_memory_error_pickle_roundtrip():
    e = OutOfMemoryError("task killed", rss_bytes=123 * MB,
                         node_usage=0.97, node_id="n1", worker_id="w1",
                         breakdown={"workers": [["w1", 123]]})
    e2 = pickle.loads(pickle.dumps(e))
    assert isinstance(e2, OutOfMemoryError)
    assert isinstance(e2, RayWorkerError)  # serve/worker retry filters
    assert e2.rss_bytes == 123 * MB
    assert e2.node_usage == pytest.approx(0.97)
    assert e2.breakdown == {"workers": [["w1", 123]]}
    assert "task killed" in str(e2)


def test_poisoned_task_error_pickle_roundtrip():
    e = PoisonedTaskError("class quarantined", key="fid123",
                          history=["oom on node a", "crash on node b"])
    e2 = pickle.loads(pickle.dumps(e))
    assert isinstance(e2, PoisonedTaskError)
    assert e2.key == "fid123"
    assert e2.history == ["oom on node a", "crash on node b"]


# ----------------------------------------------- serve breaker integration


def test_replica_oom_feeds_circuit_breaker():
    """A replica OOM-killed by the watchdog surfaces as
    OutOfMemoryError — a RayWorkerError subclass, so the handle's
    dead-replica retry catches it AND each occurrence records a breaker
    failure; enough of them open the replica's circuit."""
    from ray_tpu.serve.api import DeploymentHandle

    assert issubclass(OutOfMemoryError, RayWorkerError)
    h = DeploymentHandle.__new__(DeploymentHandle)
    h._lock = threading.Lock()
    h._latencies = deque(maxlen=200)
    h._lat_version = 0
    h._p99_cache = None
    h._name = "d"
    h._circuits = {}
    # +1: the time decay between consecutive failures keeps the score a
    # hair under N after N of them
    for _ in range(int(config.serve_circuit_fail_threshold) + 1):
        h._record_outcome("replica-oom", error=True)
    assert h._circuits["replica-oom"].state == "open"


# ------------------------------------------------------ put backpressure


def test_put_backpressure_waits_for_pin_release(tmp_path):
    from ray_tpu._private.object_store import StoreCore

    async def main():
        store = StoreCore(str(tmp_path / f"arena-{uuid.uuid4().hex[:6]}"),
                          4 * MB, str(tmp_path / "spill"))
        # fill the arena with a PINNED sealed object: unspillable right
        # now, but its pins will release
        loc = store.create("hog", 3 * MB)
        store.seal("hog")
        await store.get(["hog"], "client-a")  # pin
        assert loc["location"] == "shm"

        async def release_later():
            await asyncio.sleep(0.3)
            store.release("hog", "client-a")

        rel = asyncio.ensure_future(release_later())
        t0 = time.monotonic()
        out = await store.create_with_backpressure("newobj", 2 * MB,
                                                   wait_s=10.0)
        waited = time.monotonic() - t0
        await rel
        # blocked until the pin released, then landed in SHM (the pinned
        # hog spilled to make room) instead of the disk fallback
        assert out["location"] == "shm", out
        assert 0.2 <= waited < 5.0, waited
        store.close()

    asyncio.run(main())


def test_put_backpressure_skips_wait_when_nothing_can_free(tmp_path):
    from ray_tpu._private.object_store import StoreCore

    async def main():
        store = StoreCore(str(tmp_path / f"arena-{uuid.uuid4().hex[:6]}"),
                          2 * MB, str(tmp_path / "spill"))
        t0 = time.monotonic()
        # larger than the whole arena: waiting can never help — straight
        # to the disk fallback, no 10s stall
        out = await store.create_with_backpressure("big", 8 * MB,
                                                   wait_s=10.0)
        assert out["location"] == "disk"
        assert time.monotonic() - t0 < 1.0
        store.close()

    asyncio.run(main())


# -------------------------------------------------- conftest RSS tripwire


def test_rss_tripwire_detector_units():
    import conftest as cft

    # rising RSS floor with flat threads/sockets trips as rss_mb
    grow = [(f"m{i}", 10, 5, 500 + i * 200) for i in range(10)]
    hit = cft._monotonic_leak(grow, window=5, floor=25, rss_floor=300)
    assert hit is not None and hit[0] == "rss_mb"
    # spikes over a flat baseline never trip
    spiky = [(f"m{i}", 10, 5, 500 + (1000 if i % 3 == 0 else 0))
             for i in range(10)]
    assert cft._monotonic_leak(spiky, window=5, floor=25,
                               rss_floor=300) is None
    # slow creep under the floor never trips
    creep = [(f"m{i}", 10, 5, 500 + i * 20) for i in range(12)]
    assert cft._monotonic_leak(creep, window=5, floor=25,
                               rss_floor=300) is None
    # old 3-tuple snapshots (no rss field) are tolerated
    legacy = [(f"m{i}", 10, 5) for i in range(10)]
    assert cft._monotonic_leak(legacy, window=5, floor=25) is None
    assert cft._read_rss_mb() > 0


# ----------------------------------------------- checksummed transfers


def _seed(agent, oid, payload, primary=True):
    loc = agent.store.create(oid, len(payload), primary=primary)
    if loc["location"] == "shm":
        agent.store.arena.view[loc["offset"]:loc["offset"] + len(payload)] \
            = payload
    else:
        with open(loc["path"], "r+b") as f:
            f.write(payload)
    agent.store.seal(oid)


def _read(agent, oid, size):
    entry = agent.store.objects[oid]
    if entry.location == "shm":
        return bytes(agent.store.arena.view[entry.offset:entry.offset + size])
    with open(entry.path, "rb") as f:
        return f.read()


async def _boot_agents(tmp_path, n=2):
    from ray_tpu._private.head import HeadService
    from ray_tpu._private.node_agent import NodeAgent

    head = HeadService()
    head_port = await head.start()
    agents = []
    for i in range(n):
        ag = NodeAgent(("127.0.0.1", head_port), str(tmp_path), {"CPU": 1},
                       arena_path=str(
                           tmp_path / f"arena-{i}-{uuid.uuid4().hex[:6]}"),
                       capacity=32 * MB)
        await ag.start()
        agents.append(ag)
    return head, agents


async def _down(head, agents):
    for ag in agents:
        try:
            await ag.stop()
        except Exception:
            pass
    await head.stop()


def test_seal_checksum_and_self_verify(tmp_path):
    from ray_tpu._private.object_store import StoreCore

    store = StoreCore(str(tmp_path / f"a-{uuid.uuid4().hex[:6]}"), 8 * MB,
                      str(tmp_path / "spill"))
    payload = os.urandom(1 * MB)
    loc = store.create("o1", len(payload))
    store.arena.view[loc["offset"]:loc["offset"] + len(payload)] = payload
    store.seal("o1")
    import zlib

    assert store.checksum("o1") == zlib.crc32(payload)
    assert store.verify_crc("o1") is True
    # post-seal bitrot in the arena is detected by re-verification
    store.arena.view[loc["offset"]] = (payload[0] ^ 0xFF)
    assert store.verify_crc("o1") is False
    store.close()


def test_corrupt_pull_detected_and_recovers_from_alternate(tmp_path):
    """`xfer.corrupt` armed: the first pull's payload fails CRC
    verification (counted, reported to the holder — whose own copy is
    intact, so it keeps it) and the pull retries from an alternate
    holder, returning byte-correct data (acceptance criterion)."""
    from ray_tpu._private import fault_injection

    async def main():
        head, agents = await _boot_agents(tmp_path, n=3)
        a, b, c = agents
        try:
            payload = os.urandom(2 * MB)
            _seed(a, "oidx", payload)
            # second holder so an alternate exists in the head directory
            r = await b.rpc_ensure_local("oidx", src=[a.host, a.port])
            assert r.get("ok"), r
            deadline = time.monotonic() + 10
            while len(head.dir.locations("oidx")) < 2:
                assert time.monotonic() < deadline, "directory never saw b"
                await asyncio.sleep(0.05)
            assert head.dir.checksum("oidx") is not None
            fault_injection.inject("xfer.send", "corrupt", count=1,
                                   target="oidx")
            r = await c.rpc_ensure_local("oidx")  # holders via directory
            assert r.get("ok"), r
            assert _read(c, "oidx", len(payload)) == payload
            assert c.xfer_stats["checksum_failures"] == 1
            assert c.xfer_stats["alt_source_retries"] == 1
            # both original holders keep their (intact) copies
            assert a.store.contains("oidx") and b.store.contains("oidx")
        finally:
            fault_injection.clear()
            await _down(head, agents)

    asyncio.run(main())


def test_corrupt_secondary_copy_is_quarantined(tmp_path):
    """A holder whose OWN stored secondary copy fails re-verification
    (real bitrot, not transit corruption) drops it on an obj_corrupt
    report — the quarantined copy leaves the directory."""
    async def main():
        head, agents = await _boot_agents(tmp_path, n=2)
        a, b = agents
        try:
            payload = os.urandom(1 * MB)
            _seed(b, "oidq", payload, primary=False)
            b.store.checksum("oidq")  # fix the seal-time crc
            assert b.store.verify_crc("oidq") is True
            entry = b.store.objects["oidq"]
            b.store.arena.view[entry.offset] = payload[0] ^ 0xFF
            r = await b.rpc_obj_corrupt("oidq")
            assert r.get("dropped") is True
            assert not b.store.contains("oidq")
            # drop_copy evicted the COPY, not owner-freed the oid: a
            # later local get must read as not-local (pullable), never
            # as "freed by its owner"
            locs = await b.store.get(["oidq"], "probe", wait_timeout=0.0)
            assert locs[0] is None, locs
            # an intact copy is NOT dropped on a (spurious) report
            _seed(a, "oidok", payload)
            a.store.checksum("oidok")
            r = await a.rpc_obj_corrupt("oidok")
            assert r.get("dropped") is False and r.get("intact") is True
            assert a.store.contains("oidok")
        finally:
            await _down(head, agents)

    asyncio.run(main())


# --------------------------------------------------------- e2e: OOM kills


@pytest.fixture
def oom_cluster(tmp_path):
    usage_file = str(tmp_path / "usage")
    with open(usage_file, "w") as f:
        f.write("0.10")
    ray_tpu.init(
        num_cpus=2, object_store_memory=64 * MB,
        _system_config={
            "memory_monitor_test_usage_file": usage_file,
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 100,
            "memory_monitor_min_kill_interval_ms": 200,
            "task_oom_retries": 3,
            "task_retry_delay_ms": 50,
            "poison_task_threshold": 2,
            "poison_task_ttl_s": 60.0,
        })
    try:
        yield usage_file
    finally:
        ray_tpu.shutdown()


def _wait_for(pred, timeout=30.0, what="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {what}")


def test_oom_retry_budget_is_separate_from_max_retries(oom_cluster,
                                                       tmp_path):
    """max_retries=0 task: a generic worker death would fail it
    outright — a watchdog OOM kill draws from task_oom_retries instead
    and the retry succeeds once pressure clears."""
    usage_file = oom_cluster
    attempts = str(tmp_path / "attempts")

    @ray_tpu.remote(max_retries=0)
    def parker():
        with open(attempts, "a") as f:
            f.write("x\n")
        if len(open(attempts).readlines()) == 1:
            time.sleep(120)  # parked until the watchdog kills us
        return len(open(attempts).readlines())

    ref = parker.remote()
    _wait_for(lambda: os.path.exists(attempts), what="first attempt")
    with open(usage_file, "w") as f:
        f.write("0.99")
    _wait_for(lambda: len(open(attempts).readlines()) >= 2,
              what="OOM retry")
    with open(usage_file, "w") as f:
        f.write("0.10")
    assert ray_tpu.get(ref, timeout=60) >= 2


def test_oom_budget_exhausted_raises_typed_error(tmp_path):
    usage_file = str(tmp_path / "usage")
    with open(usage_file, "w") as f:
        f.write("0.10")
    ray_tpu.init(
        num_cpus=2, object_store_memory=64 * MB,
        _system_config={
            "memory_monitor_test_usage_file": usage_file,
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 100,
            "memory_monitor_min_kill_interval_ms": 200,
            "task_oom_retries": 0,       # first kill is terminal
            "poison_task_threshold": 99,  # keep quarantine out of this
        })
    try:
        started = str(tmp_path / "started")

        @ray_tpu.remote(max_retries=5)
        def parker():
            open(started, "w").close()
            time.sleep(120)
            return 1

        ref = parker.remote()
        _wait_for(lambda: os.path.exists(started), what="task start")
        with open(usage_file, "w") as f:
            f.write("0.99")
        with pytest.raises(OutOfMemoryError) as ei:
            ray_tpu.get(ref, timeout=60)
        # the receipt made it end to end: RSS + node evidence attached,
        # and max_retries was NOT consumed by the kill (typed error, not
        # a generic worker-death retry loop)
        assert ei.value.rss_bytes > 0
        assert ei.value.node_usage >= 0.9
        assert ei.value.breakdown.get("workers")
        with open(usage_file, "w") as f:
            f.write("0.10")
    finally:
        ray_tpu.shutdown()


def test_quarantine_trips_fails_fast_and_clears(oom_cluster, tmp_path):
    """poison_task_threshold=2 consecutive OOM kills of one class trip
    the quarantine: the NEXT submission fails fast with
    PoisonedTaskError; `rtpu quarantine clear` lifts it and the class
    runs again."""
    usage_file = oom_cluster
    marker = str(tmp_path / "marker")

    @ray_tpu.remote(max_retries=0)
    def victim():
        with open(marker, "a") as f:
            f.write("x\n")
        if os.path.exists(usage_file + ".park"):
            time.sleep(120)
        return "ok"

    open(usage_file + ".park", "w").close()
    refs = [victim.remote()]
    _wait_for(lambda: os.path.exists(marker), what="first attempt")
    with open(usage_file, "w") as f:
        f.write("0.99")  # every parked attempt is OOM-killed
    # budget 3 + the head's threshold 2: the class accumulates kills
    # and trips; the task itself resolves with a typed error.  Under a
    # loaded box the receipt race can occasionally lose and a kill
    # reads as a generic worker death (max_retries=0 -> terminal, which
    # still counts via the crash path) — the typed-error guarantees
    # have their own dedicated tests above
    with pytest.raises((OutOfMemoryError, PoisonedTaskError,
                        RayWorkerError)):
        ray_tpu.get(refs[0], timeout=90)

    head = ray_tpu.api._worker().head

    def tripped():
        return any(e["quarantined"] for e in head.call(
            "quarantine", op="list")["entries"].values())

    # keep feeding parked victims (each kill reports) until the trip —
    # robust to a lost receipt classifying some kill as a single
    # terminal crash report
    deadline = time.time() + 60
    while not tripped():
        assert time.time() < deadline, "quarantine never tripped"
        refs.append(victim.remote())
        time.sleep(1.0)
    with open(usage_file, "w") as f:
        f.write("0.10")
    # fresh submission fails fast (no worker churn) with the history
    with pytest.raises(PoisonedTaskError) as ei:
        ray_tpu.get(victim.remote(), timeout=30)
    assert ei.value.history
    # CLI clear lifts it; with pressure gone the class runs clean once
    # the owner's short-lived local verdict cache expires and the agents
    # pick up the cleared gossip
    os.unlink(usage_file + ".park")
    from ray_tpu.scripts import main as rtpu_main

    w = ray_tpu.api._worker()
    addr = f"{w.head_addr[0]}:{w.head_addr[1]}"
    assert rtpu_main(["quarantine", "--address", addr, "clear"]) == 0
    assert not any(e["quarantined"] for e in head.call(
        "quarantine", op="list")["entries"].values())
    deadline = time.time() + 30
    while True:
        try:
            assert ray_tpu.get(victim.remote(), timeout=30) == "ok"
            break
        except PoisonedTaskError:
            assert time.time() < deadline, \
                "quarantine clear never propagated"
            time.sleep(0.5)


def test_quarantine_protocol_ttl_expiry():
    """Protocol-level: kill reports trip the quarantine at the
    threshold, ok-reports reset the consecutive count, and the TTL
    expires entries without operator action."""
    ray_tpu.init(num_cpus=1, object_store_memory=32 * MB,
                 _system_config={"poison_task_threshold": 3,
                                 "poison_task_ttl_s": 1.5})
    try:
        head = ray_tpu.api._worker().head
        r = head.call("task_kill_report", key="fidA", kind="oom",
                      name="hog", node_id="n1")
        assert not r["quarantined"]
        # a success in between resets the consecutive count
        head.call("task_ok_report", key="fidA")
        head.call("task_kill_report", key="fidA", kind="oom",
                  name="hog", node_id="n1")
        r = head.call("task_kill_report", key="fidA", kind="crash",
                      name="hog", node_id="n2")
        assert not r["quarantined"], "ok-report must reset the count"
        r = head.call("task_kill_report", key="fidA", kind="oom",
                      name="hog", node_id="n1")
        assert r["quarantined"] and r["history"]
        listing = head.call("quarantine", op="list")["entries"]
        assert listing["fidA"]["quarantined"]
        time.sleep(1.6)  # TTL
        listing = head.call("quarantine", op="list")["entries"]
        assert "fidA" not in listing
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------- chaos worker.oom e2e


def test_worker_oom_chaos_allocation_bomb_e2e(tmp_path):
    """The `worker.oom` chaos site: a real allocation bomb in the
    executing worker, caught by the watchdog under a 256MB VIRTUAL node
    envelope (per-worker RSS sampling — the bomb worker is the
    highest-RSS victim), typed receipt to the owner, OOM-budget retry
    succeeds after the rules are cleared."""
    ray_tpu.init(
        num_cpus=2, object_store_memory=64 * MB,
        _system_config={
            "memory_monitor_node_total_bytes": 256 * MB,
            "memory_usage_threshold": 0.8,
            "memory_monitor_refresh_ms": 50,
            "memory_monitor_min_kill_interval_ms": 100,
            "task_oom_retries": 8,
            "task_retry_delay_ms": 50,
            "poison_task_threshold": 99,
        })
    try:
        head = ray_tpu.api._worker().head
        head.call("chaos", op="inject",
                  rule={"site": "worker.oom", "action": "oom",
                        "target": "bomb_task", "p": 1.0, "count": -1})
        time.sleep(0.5)  # rule gossip to the agent

        @ray_tpu.remote(max_retries=0, name="bomb_task")
        def bomb_task():
            return "survived"

        ref = bomb_task.remote()
        # first kill recorded at the head via the owner's kill report;
        # then clear the rules so a retry attempt runs clean (the rule
        # is per-process, so every fresh worker would re-bomb)
        _wait_for(lambda: any(
            e["kills"] >= 1 for e in head.call(
                "quarantine", op="list")["entries"].values()),
            timeout=60, what="first OOM kill report")
        head.call("chaos", op="clear")
        assert ray_tpu.get(ref, timeout=120) == "survived"
    finally:
        ray_tpu.shutdown()


# ----------------------------------------------- pressure-aware scheduling


def test_pick_node_demotes_pressured_nodes():
    from ray_tpu._private.resources import NodeResources, ResourceSet
    from ray_tpu._private.scheduler import pick_node

    cluster = {"hot": NodeResources(ResourceSet({"CPU": 8})),
               "calm": NodeResources(ResourceSet({"CPU": 8}))}
    demand = ResourceSet({"CPU": 1})
    pressure = {"hot": 0.97, "calm": 0.30}
    for _ in range(10):
        assert pick_node(cluster, demand, local_node_id="hot",
                         pressure_by_node=pressure,
                         pressure_threshold=0.95) == "calm"
    # when ONLY the pressured node can fit, it still wins (a pressured
    # node beats no node)
    assert pick_node({"hot": cluster["hot"]}, demand, "hot",
                     pressure_by_node=pressure,
                     pressure_threshold=0.95) == "hot"
    # hard affinity overrides the demotion
    assert pick_node(cluster, demand, "calm",
                     strategy={"type": "node_affinity", "node_id": "hot"},
                     pressure_by_node=pressure,
                     pressure_threshold=0.95) == "hot"


def test_memory_resource_bin_packing():
    """Tasks declaring memory= reserve bytes against the node's memory
    total for real: two 160MB tasks cannot run concurrently on a 256MB
    node."""
    ray_tpu.init(num_cpus=4, object_store_memory=32 * MB,
                 _system_config={
                     "memory_monitor_node_total_bytes": 256 * MB})
    try:
        total = ray_tpu.cluster_resources().get("memory", 0)
        assert total == 256 * MB

        @ray_tpu.remote(memory=160 * MB, num_cpus=0)
        def span(path, hold_s):
            open(path, "a").close()
            time.sleep(hold_s)
            return time.time()

        import tempfile

        d = tempfile.mkdtemp()
        t0 = time.time()
        refs = [span.remote(os.path.join(d, f"m{i}"), 0.5)
                for i in range(2)]
        ends = ray_tpu.get(refs, timeout=60)
        # serialized by the memory reservation: the second cannot start
        # until the first's 160MB returns, so completions are >=0.4s
        # apart (two CPUs were free the whole time)
        assert abs(ends[0] - ends[1]) >= 0.4, ends
        del t0
    finally:
        ray_tpu.shutdown()
