"""Property-based invariants for the core data structures
(SURVEY §5.2 race/sanitizer strategy: the reference leans on TSan +
randomized stress; here hypothesis drives randomized operation
sequences against single-process invariants — determinism of the
scheduler policy, conservation in the resource accounting, and
no-overlap/no-loss in the arena allocator).
"""

import random

import pytest

# hypothesis is an optional dev dependency: without it these
# property tests skip instead of failing the whole tier-1 collection
hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from ray_tpu._private.object_store import FreeListAllocator
from ray_tpu._private.resources import NodeResources, ResourceSet
from ray_tpu._private.scheduler import LocalScheduler, pick_node


# ------------------------------------------------------------- scheduler


@settings(max_examples=60, deadline=None)
@given(st.integers(0, 2 ** 31 - 1),
       st.lists(st.tuples(st.integers(1, 8), st.integers(0, 8)),
                min_size=1, max_size=12),
       st.integers(1, 4))
def test_pick_node_deterministic_given_seed(seed, nodes, cpu_demand):
    def build():
        cluster = {}
        for i, (total, used) in enumerate(nodes):
            nr = NodeResources(ResourceSet({"CPU": float(total)}))
            nr.acquire(ResourceSet({"CPU": float(min(used, total))}))
            cluster[f"n{i}"] = nr
        return cluster

    demand = ResourceSet({"CPU": float(cpu_demand)})
    a = pick_node(build(), demand, "n0", rng=random.Random(seed))
    b = pick_node(build(), demand, "n0", rng=random.Random(seed))
    assert a == b  # same seed + same state -> same decision


@settings(max_examples=60, deadline=None)
@given(st.lists(st.tuples(st.integers(1, 8), st.integers(0, 8)),
                min_size=1, max_size=12),
       st.integers(1, 4),
       st.integers(0, 2 ** 31 - 1))
def test_pick_node_only_picks_feasible(nodes, cpu_demand, seed):
    cluster = {}
    for i, (total, used) in enumerate(nodes):
        nr = NodeResources(ResourceSet({"CPU": float(total)}))
        nr.acquire(ResourceSet({"CPU": float(min(used, total))}))
        cluster[f"n{i}"] = nr
    demand = ResourceSet({"CPU": float(cpu_demand)})
    pick = pick_node(cluster, demand, "n0", rng=random.Random(seed))
    if pick is None:
        assert not any(nr.is_feasible(demand) for nr in cluster.values())
    else:
        assert cluster[pick].is_feasible(demand)


# ------------------------------------------------- resource conservation


@settings(max_examples=60, deadline=None)
@given(st.lists(st.integers(1, 5), min_size=1, max_size=30),
       st.integers(2, 16))
def test_local_scheduler_conserves_resources(demands, capacity):
    """Any acquire/release interleaving ends with the full capacity
    back and never drives availability negative."""
    sched = LocalScheduler(NodeResources(ResourceSet(
        {"CPU": float(capacity)})))
    held = []
    for d in demands:
        demand = ResourceSet({"CPU": float(d)})
        avail = sched.resources.available.to_dict().get("CPU", 0.0)
        assert avail >= 0.0
        if sched.try_acquire(demand):
            assert d <= avail + 1e-9
            held.append(demand)
    for demand in held:
        sched.release(demand)
    assert sched.resources.available.to_dict()["CPU"] == float(capacity)


# --------------------------------------------------------- arena allocator


import pytest


def _make_alloc(kind, cap):
    if kind == "python":
        return FreeListAllocator(cap)
    from ray_tpu import _native

    alloc = _native.make_allocator(cap, wait_s=60)
    assert alloc is not None, "native toolchain present: must build"
    return alloc


@pytest.mark.parametrize("kind", ["python", "native"])
@settings(max_examples=40, deadline=None)
@given(ops=st.lists(st.one_of(
    st.tuples(st.just("alloc"), st.integers(1, 4096)),
    st.tuples(st.just("free"), st.integers(0, 100))),
    min_size=1, max_size=120))
def test_allocator_no_overlap_no_loss(kind, ops):
    """Random alloc/free sequences: live blocks never overlap, and after
    freeing everything the allocator is back to zero bytes allocated.
    Runs against BOTH the Python and the native C allocator."""
    cap = 64 * 1024
    alloc = _make_alloc(kind, cap)
    live = {}  # offset -> size
    counter = 0
    for op, arg in ops:
        if op == "alloc":
            off = alloc.alloc(arg)
            if off is None:
                continue
            # no overlap with any live block
            for o, s in live.items():
                assert off + arg <= o or o + s <= off, \
                    f"[{off},{off + arg}) overlaps [{o},{o + s})"
            assert 0 <= off and off + arg <= cap
            live[off] = arg
            counter += 1
        elif live:
            off = sorted(live)[arg % len(live)]
            alloc.free(off, live.pop(off))
    for off, size in list(live.items()):
        alloc.free(off, size)
    assert alloc.allocated == 0
