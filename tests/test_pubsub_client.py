"""Pub/sub channels, client mode (rt://), and TPU chip visibility tests
(reference: pubsub/publisher.h, util/client/, accelerators/tpu.py)."""

import os
import time

import pytest

import ray_tpu
from ray_tpu.experimental import pubsub


def test_pubsub_roundtrip(local_cluster):
    seq1 = pubsub.publish("app-chan", {"x": 1})
    seq2 = pubsub.publish("app-chan", {"x": 2})
    assert seq2 == seq1 + 1
    events = pubsub.poll("app-chan", after_seq=0)
    assert [e["payload"]["x"] for e in events] == [1, 2]
    assert pubsub.poll("app-chan", after_seq=seq2) == []
    # long-poll wakes on publish
    import threading

    got = []
    t = threading.Thread(target=lambda: got.extend(
        pubsub.poll("app-chan", after_seq=seq2, timeout_s=10)))
    t.start()
    time.sleep(0.3)
    pubsub.publish("app-chan", {"x": 3})
    t.join(timeout=15)
    assert [e["payload"]["x"] for e in got] == [3]


def test_pubsub_builtin_channels(local_cluster):
    # the single-node fixture registered one node at init
    events = pubsub.poll("node_events", after_seq=0)
    assert any(e["payload"]["event"] == "registered" for e in events)

    @ray_tpu.remote
    class A:
        def ping(self):
            return 1

    a = A.remote()
    ray_tpu.get(a.ping.remote(), timeout=60)
    deadline = time.time() + 15
    while time.time() < deadline:
        ev = pubsub.poll("actor_events", after_seq=0)
        if any(e["payload"]["state"] == "ALIVE" for e in ev):
            break
        time.sleep(0.2)
    assert any(e["payload"]["state"] == "ALIVE" for e in ev)


def test_client_mode_objects_tasks_actors():
    """rt:// drivers have no arena mmap: puts/gets proxy over RPC."""
    import numpy as np

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 2})
    ray_tpu.init(address=f"rt://{cluster.address}")
    try:
        from ray_tpu._private.object_store import RpcPlasmaClient
        from ray_tpu._private.worker import global_worker_or_none

        assert isinstance(global_worker_or_none().plasma, RpcPlasmaClient)
        arr = np.arange(200_000, dtype=np.float32)  # > inline threshold
        ref = ray_tpu.put(arr)
        assert np.array_equal(ray_tpu.get(ref, timeout=60), arr)

        @ray_tpu.remote
        def double(x):
            return x * 2

        out = ray_tpu.get(double.remote(ref), timeout=120)
        assert np.array_equal(out, arr * 2)

        @ray_tpu.remote
        class Acc:
            def __init__(self):
                self.v = 0

            def add(self, x):
                self.v += int(x)
                return self.v

        a = Acc.remote()
        assert ray_tpu.get(a.add.remote(5), timeout=60) == 5
        assert ray_tpu.get(a.add.remote(7), timeout=60) == 12
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_tpu_chip_visibility(tmp_path):
    """Leases holding TPU resources pin specific chips; concurrent tasks
    on one node see disjoint TPU_VISIBLE_CHIPS."""
    ray_tpu.init(num_cpus=4, resources={"TPU": 4},
                 object_store_memory=64 * 1024 * 1024)
    try:
        sync_dir = str(tmp_path)

        def make(name):
            @ray_tpu.remote(num_tpus=2, num_cpus=1, name=name)
            def chips():
                import os as _os
                import time as _t

                mine = _os.environ.get("TPU_VISIBLE_CHIPS", "")
                open(f"{sync_dir}/{_os.getpid()}.chips", "w").write(mine)
                # wait until BOTH tasks have reported (proves concurrency)
                deadline = _t.time() + 30
                while _t.time() < deadline:
                    files = [f for f in _os.listdir(sync_dir)
                             if f.endswith(".chips")]
                    if len(files) >= 2:
                        return mine
                    _t.sleep(0.1)
                return mine

            return chips

        r1, r2 = make("c1").remote(), make("c2").remote()
        a, b = ray_tpu.get([r1, r2], timeout=120)
        sa = set(a.split(",")) if a else set()
        sb = set(b.split(",")) if b else set()
        assert len(sa) == 2 and len(sb) == 2
        assert not (sa & sb), f"chips overlap: {sa} & {sb}"
    finally:
        ray_tpu.shutdown()


def test_metadata_env_first(monkeypatch):
    from ray_tpu._private import accelerators

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5e-8")
    assert accelerators.tpu_metadata("accelerator-type") == "v5e-8"
    monkeypatch.delenv("TPU_ACCELERATOR_TYPE")
    monkeypatch.setenv("RT_DISABLE_METADATA_SERVER", "1")
    assert accelerators.tpu_metadata("accelerator-type") is None


def test_actor_keeps_chips_across_method_calls(tmp_path):
    """Method pushes must not clear the constructor's chip assignment
    (jax typically initializes lazily in the first METHOD, not
    __init__)."""
    ray_tpu.init(num_cpus=2, resources={"TPU": 4},
                 object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote(num_tpus=2)
        class T:
            def chips(self):
                import os as _os

                return _os.environ.get("TPU_VISIBLE_CHIPS")

        t = T.remote()
        first = ray_tpu.get(t.chips.remote(), timeout=60)
        second = ray_tpu.get(t.chips.remote(), timeout=60)
        assert first is not None and len(first.split(",")) == 2
        assert second == first
    finally:
        ray_tpu.shutdown()
