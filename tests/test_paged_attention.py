"""Paged-attention kernel tests (ops/paged_attention.py).

The kernel runs in Pallas interpret mode on CPU — same numerics as the
TPU compilation — so these tests pin the decode kernel against the
dense gather-then-softmax reference (models/llama.py cached_attention)
across batch, context length, GQA grouping, and page size, including
ragged lengths, all-garbage lanes, and non-contiguous / shuffled
physical page assignment.  The engine-level A/B at the bottom proves
the two attention_impl settings generate token-identical streams.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from ray_tpu.models.llama import (LlamaConfig, cached_attention,
                                  copy_kv_slots, gather_kv_slots,
                                  make_kv_pools, scatter_kv_slots)
from ray_tpu.ops.paged_attention import paged_attention


def _rand_paged_case(rng, batch, ctx_lens, n_heads, n_kv_heads, head_dim,
                     page_size, num_pages):
    """Random pools + a shuffled (non-contiguous) page assignment per
    lane; returns everything both the paged kernel and the dense
    reference need.  Page 0 is the garbage page, never assigned."""
    t = num_pages * page_size
    pool_k = jnp.asarray(rng.normal(size=(t, n_kv_heads, head_dim)),
                         jnp.float32)
    pool_v = jnp.asarray(rng.normal(size=(t, n_kv_heads, head_dim)),
                         jnp.float32)
    q = jnp.asarray(rng.normal(size=(batch, 1, n_heads, head_dim)),
                    jnp.float32)
    used = [-(-c // page_size) for c in ctx_lens]
    width = max(max(used), 1)
    assert sum(used) <= num_pages - 1, "case needs more pages"
    pages = list(rng.permutation(np.arange(1, num_pages)))
    bt = np.zeros((batch, width), np.int32)
    for b in range(batch):
        for p in range(used[b]):
            bt[b, p] = pages.pop()
    return q, pool_k, pool_v, bt, np.asarray(ctx_lens, np.int32)


def _dense_reference(q, pool_k, pool_v, bt, ctx_lens, page_size):
    """cached_attention over ctx/ctx_pos/ctx_mask arrays derived from
    the same block tables — the exact arrays the dense engine path
    builds each decode step."""
    batch = q.shape[0]
    length = bt.shape[1] * page_size
    ctx = np.zeros((batch, length), np.int32)
    ctx_pos = np.zeros((batch, length), np.int32)
    ctx_mask = np.zeros((batch, length), bool)
    for b in range(batch):
        for pos in range(int(ctx_lens[b])):
            ctx[b, pos] = bt[b, pos // page_size] * page_size \
                + pos % page_size
            ctx_pos[b, pos] = pos
            ctx_mask[b, pos] = True
    q_pos = np.maximum(ctx_lens.astype(np.int32) - 1, 0)[:, None]
    return cached_attention(q, pool_k, pool_v, jnp.asarray(ctx),
                            jnp.asarray(ctx_pos), jnp.asarray(ctx_mask),
                            jnp.asarray(q_pos))


@pytest.mark.parametrize("batch,ctx_lens,heads,kv_heads,page_size", [
    (1, [1], 4, 2, 8),                 # single token, single lane
    (2, [5, 16], 4, 4, 8),             # MHA (group=1), page-exact length
    (3, [13, 1, 9], 4, 2, 4),          # GQA group=2, ragged
    (4, [31, 8, 17, 2], 8, 2, 8),      # GQA group=4, multi-page ragged
    (2, [7, 23], 4, 2, 16),            # bigger pages than one context
])
def test_kernel_matches_dense_reference(batch, ctx_lens, heads, kv_heads,
                                        page_size):
    rng = np.random.default_rng(hash((batch, heads, page_size)) % 2**32)
    q, pk, pv, bt, cl = _rand_paged_case(
        rng, batch, ctx_lens, heads, kv_heads, head_dim=16,
        page_size=page_size, num_pages=24)
    out = paged_attention(q, pk, pv, jnp.asarray(bt), jnp.asarray(cl),
                          page_size=page_size)
    ref = _dense_reference(q, pk, pv, bt, cl, page_size)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ragged_with_garbage_lanes():
    """Inactive lanes (context length 0, table pointing at the garbage
    page) must produce finite zeros — never NaNs from an all-masked
    softmax — while live lanes stay exact."""
    rng = np.random.default_rng(7)
    q, pk, pv, bt, cl = _rand_paged_case(
        rng, 4, [11, 0, 3, 0], 4, 2, head_dim=8, page_size=4,
        num_pages=16)
    out = np.asarray(paged_attention(q, pk, pv, jnp.asarray(bt),
                                     jnp.asarray(cl), page_size=4))
    assert np.all(np.isfinite(out))
    assert np.all(out[1] == 0) and np.all(out[3] == 0)
    ref = np.asarray(_dense_reference(q, pk, pv, bt, cl, 4))
    np.testing.assert_allclose(out[0], ref[0], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(out[2], ref[2], rtol=1e-5, atol=1e-5)


def test_all_garbage_batch_is_zero():
    rng = np.random.default_rng(11)
    q, pk, pv, bt, cl = _rand_paged_case(
        rng, 3, [0, 0, 0], 4, 2, head_dim=8, page_size=8, num_pages=8)
    out = np.asarray(paged_attention(q, pk, pv, jnp.asarray(bt),
                                     jnp.asarray(cl), page_size=8))
    assert np.all(out == 0) and np.all(np.isfinite(out))


def test_kernel_under_jit_and_wide_table():
    """The engine calls the kernel inside jit with a bucketed table
    width that can exceed any lane's used pages — trailing table
    entries must not perturb the result."""
    rng = np.random.default_rng(3)
    q, pk, pv, bt, cl = _rand_paged_case(
        rng, 2, [9, 4], 4, 2, head_dim=16, page_size=4, num_pages=16)
    ref = paged_attention(q, pk, pv, jnp.asarray(bt), jnp.asarray(cl),
                          page_size=4)
    wide = np.zeros((2, 8), np.int32)           # width 3 -> 8
    wide[:, :bt.shape[1]] = bt
    fn = jax.jit(lambda *a: paged_attention(*a, page_size=4))
    out = fn(q, pk, pv, jnp.asarray(wide), jnp.asarray(cl))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_shared_pages_between_lanes():
    """Prefix sharing: two lanes whose tables alias the SAME physical
    pages must each read the shared KV — the kernel only ever addresses
    pages through the table, so aliasing is invisible to it."""
    rng = np.random.default_rng(5)
    q, pk, pv, bt, cl = _rand_paged_case(
        rng, 2, [12, 12], 4, 2, head_dim=8, page_size=4, num_pages=16)
    bt[1] = bt[0]                                # full alias
    out = paged_attention(q, pk, pv, jnp.asarray(bt), jnp.asarray(cl),
                          page_size=4)
    ref = _dense_reference(q, pk, pv, bt, cl, 4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


# ------------------------------------------------ slot-pool round trips


def test_gather_scatter_copy_round_trip():
    """Property test over the KV slot-pool plumbing the paged cache
    rides on: scatter(gather(x)) is identity on the touched slots, a
    gather after shipping through numpy equals the original rows, and
    copy_kv_slots makes dst rows literally equal src rows (the CoW
    split primitive)."""
    cfg = LlamaConfig(vocab_size=16, dim=16, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=16, max_seq_len=32,
                      dtype=jnp.float32)
    rng = np.random.default_rng(13)
    for trial in range(5):
        num_slots = 40
        pools = make_kv_pools(cfg, num_slots)
        pools = {"k": [jnp.asarray(rng.normal(size=p.shape), p.dtype)
                       for p in pools["k"]],
                 "v": [jnp.asarray(rng.normal(size=p.shape), p.dtype)
                       for p in pools["v"]]}
        n = int(rng.integers(1, 12))
        slots = rng.choice(np.arange(1, num_slots), size=n, replace=False)
        rows = gather_kv_slots(pools, slots)
        # round trip into a fresh zeroed pool set
        fresh = make_kv_pools(cfg, num_slots)
        fresh = scatter_kv_slots(fresh, slots, rows)
        back = gather_kv_slots(fresh, slots)
        for side in ("k", "v"):
            for a, b in zip(rows[side], back[side]):
                np.testing.assert_array_equal(a, b)
        # copy: dst slots must equal src slots afterwards
        free = [s for s in range(1, num_slots) if s not in set(slots)]
        dst = np.asarray(free[:n], np.int32)
        copied = copy_kv_slots(pools, slots, dst)
        after_src = gather_kv_slots(copied, slots)
        after_dst = gather_kv_slots(copied, dst)
        for side in ("k", "v"):
            for a, b in zip(after_src[side], after_dst[side]):
                np.testing.assert_array_equal(a, b)


# ------------------------------------------------ engine-level A/B


def _make_engine(impl, params=None):
    from ray_tpu.serve.llm import LLMEngine

    cfg = LlamaConfig(vocab_size=64, dim=32, n_layers=2, n_heads=4,
                      n_kv_heads=2, hidden_dim=64, max_seq_len=64,
                      dtype=jnp.float32)
    return LLMEngine(cfg, page_size=8, num_pages=33, max_batch=4,
                     prefill_chunk=8, max_queue=8,
                     attention_impl=impl, params=params)


def test_engine_paged_vs_dense_identical_tokens():
    """The serving A/B: the same prompts decoded greedily through the
    paged kernel and through the dense reference produce identical
    token streams (fp32 keeps argmax bit-stable)."""
    paged = _make_engine("paged")
    dense = _make_engine("dense", params=paged._params)
    assert paged.stats()["attention_impl"] == "paged"
    assert dense.stats()["attention_impl"] == "dense"
    reqs = [{"tokens": [5, 9, 3], "max_new_tokens": 6},
            {"tokens": [7, 11, 2, 4, 8, 1, 9, 10, 3, 2],
             "max_new_tokens": 6},
            {"tokens": [3] * 13, "max_new_tokens": 6}]
    out_p = paged.generate_batch([dict(r) for r in reqs])
    out_d = dense.generate_batch([dict(r) for r in reqs])
    assert out_p == out_d, (out_p, out_d)


def test_attention_impl_validation():
    with pytest.raises(ValueError, match="auto\\|paged\\|dense"):
        _make_engine("flashier")
