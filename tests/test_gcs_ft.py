"""Head (GCS) fault-tolerance tests.

Mirrors the reference's GCS restart suite
(reference: python/ray/tests/test_gcs_fault_tolerance.py; persistence via
gcs/store_client/redis_store_client.h, raylet resync via
node_manager.proto:352 NotifyGCSRestart): the head persists its tables
to disk, is SIGKILLed mid-workload, restarts on the same port, and the
cluster — agents, drivers, named actors, placement groups, KV — carries
on.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def restartable_cluster():
    cluster = Cluster(head_node_args={"num_cpus": 4})
    ray_tpu.init(address=cluster.address)
    try:
        yield cluster
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def _wait_persist():
    """Outwait the head's snapshot debounce before killing it."""
    time.sleep(0.6)


def test_kv_and_named_actor_survive_head_restart(restartable_cluster):
    from ray_tpu.experimental import internal_kv

    internal_kv.kv_put(b"ft-key", b"ft-value")

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    counter = Counter.options(name="survivor").remote()
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 1
    _wait_persist()

    restartable_cluster.restart_head()

    # named actor resolves again (retry window covers the restart)
    handle = ray_tpu.get_actor("survivor")
    # the actor process itself never died: state is intact
    assert ray_tpu.get(handle.incr.remote(), timeout=60) == 2
    assert internal_kv.kv_get(b"ft-key") == b"ft-value"


def test_tasks_run_through_head_restart(restartable_cluster):
    @ray_tpu.remote
    def sq(x):
        time.sleep(0.05)
        return x * x

    # warm a lease so in-flight work exists across the restart
    assert ray_tpu.get(sq.remote(3), timeout=60) == 9
    refs = [sq.remote(i) for i in range(20)]
    restartable_cluster.restart_head(kill=True)
    assert ray_tpu.get(refs, timeout=120) == [i * i for i in range(20)]
    # NEW work (fresh leases, function table reads) also succeeds
    refs2 = [sq.remote(i) for i in range(10)]
    assert ray_tpu.get(refs2, timeout=120) == [i * i for i in range(10)]


def test_placement_group_survives_head_restart(restartable_cluster):
    from ray_tpu.util.placement_group import placement_group

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout=30)
    _wait_persist()

    restartable_cluster.restart_head()

    @ray_tpu.remote
    def inside():
        return "ok"

    # the restored PG placement is still honored for new work
    ref = inside.options(placement_group=pg,
                         placement_group_bundle_index=0).remote()
    assert ray_tpu.get(ref, timeout=60) == "ok"
    from ray_tpu.util.placement_group import placement_group_table

    states = {e["pg_id"]: e["state"] for e in placement_group_table()}
    assert states.get(pg.id) == "CREATED"


def test_heartbeats_keep_nodes_alive(restartable_cluster):
    """Regression: the head once rejected every heartbeat (signature
    mismatch on the piggybacked demand report), so idle nodes were
    silently reaped after the health threshold (~15 s) and the node
    table emptied under a live cluster."""
    time.sleep(17)
    assert len(ray_tpu.nodes()) == 1, "idle node was reaped (dead heartbeats)"


def test_agents_reregister_after_head_restart(restartable_cluster):
    restartable_cluster.add_node(num_cpus=2, resources={"extra": 1})
    restartable_cluster.wait_for_nodes(2)
    _wait_persist()
    restartable_cluster.restart_head()
    # both agents re-register on their next heartbeat; resources are back
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        try:
            res = ray_tpu.cluster_resources()
            if res.get("CPU") == 6.0 and res.get("extra") == 1.0:
                return
        except Exception:
            pass
        time.sleep(0.25)
    raise AssertionError(
        f"cluster view did not recover: {ray_tpu.cluster_resources()}")


@pytest.mark.slow
def test_chaos_head_kill_agents_reregister(restartable_cluster):
    """The ``head.kill`` chaos site (ISSUE 14 satellite): the PR-7
    chaos engine can now exercise THIS module's recovery paths on
    demand — the head SIGKILLs itself via `rtpu chaos`-style injection,
    the supervisor restarts it on the same port, and agents re-register
    with resources intact."""
    restartable_cluster.add_node(num_cpus=2, resources={"extra": 1})
    restartable_cluster.wait_for_nodes(2)
    _wait_persist()
    w = ray_tpu.api._worker()
    st = w.head.call("chaos", op="inject",
                     rule={"site": "head.kill", "action": "kill",
                           "count": 1, "delay_s": 0.3}, timeout=30)
    assert any(r["site"] == "head.kill" for r in st["rules"])
    # the head self-SIGKILLs shortly after the reply flushed
    assert restartable_cluster._head_proc.proc.wait(timeout=15) is not None
    # same restart path the harness uses (kill on a dead pid is a no-op)
    restartable_cluster.restart_head(kill=True)
    deadline = time.monotonic() + 45
    while time.monotonic() < deadline:
        try:
            res = ray_tpu.cluster_resources()
            if res.get("CPU") == 6.0 and res.get("extra") == 1.0:
                break
        except Exception:
            pass
        time.sleep(0.25)
    else:
        raise AssertionError(
            f"agents did not re-register after chaos head kill: "
            f"{ray_tpu.cluster_resources()}")
    # and the restarted head serves chaos status with a clean plane
    w.head.call("chaos", op="clear", timeout=30)

    @ray_tpu.remote
    def probe():
        return "ok"

    assert ray_tpu.get(probe.remote(), timeout=60) == "ok"
