"""JaxTrainer + model + mesh tests on the virtual 8-device CPU mesh.

Mirrors the reference's Train test strategy
(reference: python/ray/train/tests/ — tiny ScalingConfig on one machine,
SURVEY §4.2).
"""

import numpy as np
import pytest

import ray_tpu
from tests.conftest import force_cpu_jax


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------------- model


@pytest.mark.slow
def test_llama_forward_shapes():
    # behind `slow` since the LLM serving tests joined tier-1: the
    # decode-identity gate (test_serve_llm.py) runs the full LlamaModel
    # forward on every tier-1 pass, so this eager shape/dtype check
    # (~20s of op dispatch on the CI box) is redundant cover there
    jax = force_cpu_jax()
    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaConfig, LlamaModel, causal_lm_loss

    cfg = LlamaConfig.tiny()
    model = LlamaModel(cfg)
    tokens = jnp.zeros((2, 16), dtype=jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    # lm_head stays bf16 (MXU fast path); the loss upcasts to fp32
    assert logits.dtype == cfg.dtype
    loss = causal_lm_loss(logits, tokens)
    assert np.isfinite(float(loss))


def test_llama_param_count():
    from ray_tpu.models.llama import LlamaConfig

    # 8B config should land in the 7.5-9B range
    n = LlamaConfig.llama3_8b().num_params()
    assert 7.5e9 < n < 9e9, n


def test_mesh_spec_resolution():
    from ray_tpu.parallel.mesh import MeshSpec

    s = MeshSpec(dp=-1, fsdp=2, tp=2).resolve(8)
    assert (s.dp, s.fsdp, s.tp) == (2, 2, 2)
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=-1).resolve(8)


def test_sharded_train_step_runs_on_mesh():
    jax = force_cpu_jax()
    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh
    from ray_tpu.train.gspmd import build_llama_train_state

    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2), devices=jax.devices()[:8])
    cfg = LlamaConfig.tiny()
    params, opt, step, _ = build_llama_train_state(cfg, mesh, batch_size=4,
                                                   seq_len=32)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (4, 32), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    losses = []
    for _ in range(3):
        params, opt, loss = step(params, opt, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # memorizing one batch must reduce loss


# ----------------------------------------------------------------- trainer


def test_jax_trainer_data_parallel(cluster):
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def mnist_style_loop(config):
        """DataParallel MLP on synthetic data over all local devices
        (BASELINE.json config #1 shape). Defined inside the test so
        cloudpickle serializes it by value."""
        import jax
        import optax

        from ray_tpu import train as rt_train
        from ray_tpu.parallel.mesh import MeshSpec, make_mesh, shard_batch

        ctx = rt_train.get_context()
        mesh = make_mesh(MeshSpec(dp=-1), devices=jax.devices())

        key = jax.random.PRNGKey(0)
        params = {"w1": jax.random.normal(key, (64, 32)) * 0.1,
                  "w2": jax.random.normal(key, (32, 10)) * 0.1}
        tx = optax.sgd(0.1)
        opt = tx.init(params)

        x = jax.random.normal(jax.random.PRNGKey(1), (config["batch"], 64))
        y = jax.random.randint(jax.random.PRNGKey(2), (config["batch"],), 0, 10)

        def loss_fn(p, x, y):
            h = jax.nn.relu(x @ p["w1"])
            logits = h @ p["w2"]
            return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

        @jax.jit
        def step(p, o, x, y):
            loss, g = jax.value_and_grad(loss_fn)(p, x, y)
            up, o = tx.update(g, o, p)
            return optax.apply_updates(p, up), o, loss

        with mesh:
            xs, ys = shard_batch(mesh, x), shard_batch(mesh, y)
            for epoch in range(config["epochs"]):
                params, opt, loss = step(params, opt, xs, ys)
                rt_train.report({"loss": float(loss), "epoch": epoch,
                                 "rank": ctx.get_world_rank()})
        return {"final_loss": float(loss)}

    trainer = JaxTrainer(
        mnist_style_loop,
        scaling_config=ScalingConfig(num_workers=1),
        train_loop_config={"batch": 64, "epochs": 8},
    )
    result = trainer.fit()
    hist = result.metrics_history
    assert len(hist) == 8
    assert hist[-1]["loss"] < hist[0]["loss"]
    assert result.per_worker_final[0]["final_loss"] == hist[-1]["loss"]


def test_jax_trainer_error_surfaces(cluster):
    from ray_tpu.train import JaxTrainer, ScalingConfig, TrainingFailedError

    def bad_loop(config):
        raise RuntimeError("train exploded")

    trainer = JaxTrainer(bad_loop, scaling_config=ScalingConfig(num_workers=1),
                         train_loop_config={})
    with pytest.raises(TrainingFailedError, match="train exploded"):
        trainer.fit()


def test_worker_group_execute(cluster):
    from ray_tpu.train import WorkerGroup

    g = WorkerGroup(3)
    infos = g.execute("node_info")
    assert len(infos) == 3
    g.shutdown()


@pytest.mark.slow
def test_trainer_dataset_ingest(cluster):
    """Datasets flow to workers as block shards (reference:
    streaming_split ingest; object-plane boundary SURVEY §3.4 step 6).
    Behind `slow` for tier-1 budget: dataset iteration is covered by
    test_data.py and the trainer fit/report path by the dp trainer
    e2e above."""
    from ray_tpu import data as rtd
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        from ray_tpu import train as rt_train

        shard = rt_train.get_dataset_shard("train")
        seen = 0
        for batch in shard.iter_batches(batch_size=10):
            seen += len(batch["id"])
            rt_train.report({"seen": seen})
        return seen

    ds = rtd.range(40, num_blocks=4)
    result = JaxTrainer(loop, scaling_config=ScalingConfig(num_workers=2),
                        train_loop_config={}, datasets={"train": ds}).fit()
    assert sum(result.per_worker_final) == 40


def test_report_outside_session_raises():
    from ray_tpu.train import report

    with pytest.raises(RuntimeError):
        report({"x": 1})


# ------------------------------------------------------- fault tolerance


def test_fit_retries_worker_death_and_resumes(cluster):
    """Worker death mid-fit rebuilds the gang and resumes from the last
    reported checkpoint (reference: backend_executor.py:629 +
    tune_controller.py:1792 gang-restart semantics)."""
    import json
    import os
    import tempfile

    from ray_tpu import train

    marker = os.path.join(tempfile.mkdtemp(), "died_once")

    def loop(config):
        start = 0
        ckpt = train.get_checkpoint()
        if ckpt:
            with open(os.path.join(ckpt, "state.json")) as f:
                start = json.load(f)["step"] + 1
        for step in range(start, 6):
            if step == 3 and not os.path.exists(config["marker"]):
                open(config["marker"], "w").close()
                os._exit(1)  # hard worker death, not a python error
            d = os.path.join(train.get_context().trial_dir,
                             f"ckpt_{step}")
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "state.json"), "w") as f:
                json.dump({"step": step}, f)
            train.report({"step": step}, checkpoint=d)
        return "done"

    trainer = train.JaxTrainer(
        loop,
        scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(name="ft_run", failure_max_retries=1),
        train_loop_config={"marker": marker})
    result = trainer.fit()
    steps = [m["step"] for m in result.metrics_history]
    assert result.per_worker_final == ["done"]
    # ran 0,1,2 then died at 3; resumed at 3 (from ckpt_2) through 5
    assert steps == [0, 1, 2, 3, 4, 5], steps


def test_fit_exhausted_retries_raises(cluster):
    import os

    from ray_tpu import train

    def loop():
        os._exit(1)

    trainer = train.JaxTrainer(
        loop, scaling_config=train.ScalingConfig(num_workers=1),
        run_config=train.RunConfig(failure_max_retries=1))
    with pytest.raises(train.TrainingFailedError):
        trainer.fit()


def test_orbax_checkpoint_roundtrip(tmp_path):
    jax = force_cpu_jax()
    import jax.numpy as jnp

    from ray_tpu.train import restore_checkpoint, save_checkpoint

    state = {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
             "step": jnp.int32(7)}
    path = save_checkpoint(str(tmp_path / "ck"), state)
    restored = restore_checkpoint(path)
    assert float(restored["params"]["w"][1][2]) == 5.0
    assert int(restored["step"]) == 7
    # restore with a target tree (dtype/sharding-aware path)
    target = {"params": {"w": jnp.zeros((2, 3))}, "step": jnp.int32(0)}
    restored2 = restore_checkpoint(path, target=target)
    assert float(restored2["params"]["w"][0][1]) == 1.0


def test_checkpoint_manager_topk(tmp_path):
    force_cpu_jax()
    import jax.numpy as jnp

    from ray_tpu.train import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "mgr"), num_to_keep=2,
                            metric="loss", mode="min")
    p1 = mgr.save({"x": jnp.float32(1)}, {"loss": 3.0})
    p2 = mgr.save({"x": jnp.float32(2)}, {"loss": 1.0})
    p3 = mgr.save({"x": jnp.float32(3)}, {"loss": 2.0})
    import os
    assert not os.path.exists(p1)  # worst evicted
    assert mgr.best_checkpoint() == p2
    assert mgr.latest_checkpoint() == p3
