"""End-to-end deadline propagation + enforcement (ISSUE 14 tentpole).

Unit coverage for every enforcement site raising the typed
DeadlineExceededError — queued (owner pump, agent lease queue), running
(owner deadline sweep + cooperative cancel), get (ambient budget) —
plus nested ``.remote()`` propagation, the ingress-header parser, and
the jittered rpc reconnect backoff satellite.  The fourth site
(LLM admission) lives with the engine tests in test_serve_llm.py.
"""

import os
import random
import time

import pytest

import ray_tpu
from ray_tpu._private import deadlines


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


@ray_tpu.remote
def _sleep(s):
    time.sleep(s)
    return "done"


def test_running_task_fails_at_deadline(cluster):
    """A task mid-execution past its budget resolves with the typed
    error AT the deadline (the sweep resolves it owner-side, then
    cancels the worker) — the caller's get() does not wait out the
    task's natural 5s runtime."""
    t0 = time.monotonic()
    with pytest.raises(ray_tpu.DeadlineExceededError) as ei:
        ray_tpu.get(_sleep.options(timeout_s=0.5).remote(5), timeout=30)
    assert time.monotonic() - t0 < 3.0
    assert ei.value.where == "running"


def test_queued_task_fails_fast_without_running(cluster, tmp_path):
    """A task expiring while queued behind busy workers fails with
    where=queued and is NEVER dispatched (no side effects)."""
    marker = str(tmp_path / "ran")

    @ray_tpu.remote
    def doomed(path):
        open(path, "w").write("ran")
        return "ran"

    blockers = [_sleep.remote(1.5) for _ in range(2)]  # both CPUs busy
    time.sleep(0.3)  # blockers actually running
    t0 = time.monotonic()
    with pytest.raises(ray_tpu.DeadlineExceededError) as ei:
        ray_tpu.get(doomed.options(timeout_s=0.4).remote(marker),
                    timeout=30)
    assert time.monotonic() - t0 < 2.0  # failed FAST, not at blocker end
    assert ei.value.where == "queued"
    assert ray_tpu.get(blockers, timeout=60) == ["done", "done"]
    time.sleep(0.2)
    assert not os.path.exists(marker), "expired task was dispatched"


def test_nested_remote_inherits_deadline(cluster):
    """spec.deadline propagates through nested .remote() via the
    contextvar, the way trace context does: the inner task sees the
    OUTER caller's absolute deadline."""
    @ray_tpu.remote
    def inner_probe():
        return deadlines.current_deadline()

    @ray_tpu.remote
    def outer():
        return ray_tpu.get(inner_probe.remote(), timeout=30)

    expect = time.time() + 5.0
    got = ray_tpu.get(outer.options(timeout_s=5.0).remote(), timeout=30)
    assert got is not None and abs(got - expect) < 1.5, (got, expect)


def test_nested_get_spends_remaining_budget(cluster):
    """A get() inside a deadlined task is bounded by the ambient
    budget: the whole tree resolves at the outer deadline with the
    typed error (surfaced either by the inner get or the owner
    sweep, whichever wins the race)."""
    @ray_tpu.remote
    def hang_forever():
        time.sleep(30)

    @ray_tpu.remote
    def outer_waits():
        return ray_tpu.get(hang_forever.remote())

    t0 = time.monotonic()
    with pytest.raises((ray_tpu.DeadlineExceededError,
                        ray_tpu.RayTaskError)) as ei:
        ray_tpu.get(outer_waits.options(timeout_s=0.7).remote(),
                    timeout=30)
    assert time.monotonic() - t0 < 5.0
    e = ei.value
    cause = getattr(e, "cause", None)
    assert isinstance(e, ray_tpu.DeadlineExceededError) \
        or isinstance(cause, ray_tpu.DeadlineExceededError), (e, cause)


def test_driver_side_ambient_deadline_bounds_get(cluster):
    """get() with an active ambient deadline spends only the remaining
    budget — the 'get' enforcement site."""
    ref = _sleep.remote(10)  # will not finish inside the window
    token = deadlines.activate(time.time() + 0.4)
    t0 = time.monotonic()
    try:
        with pytest.raises(ray_tpu.DeadlineExceededError) as ei:
            ray_tpu.get(ref, timeout=30)
    finally:
        deadlines.restore(token)
    assert time.monotonic() - t0 < 2.0
    assert ei.value.where == "get"
    ray_tpu.cancel(ref, force=True)


def test_agent_drops_expired_lease_queue_entry(cluster):
    """Agent-side enforcement: a queued lease request whose spec
    deadline passed is dropped from the FIFO and the owner notified
    with the typed error reply — it never camps on the agent queue
    until the generic lease timeout."""
    from ray_tpu._private.ids import JobID, TaskID
    from ray_tpu._private.task_spec import TaskSpec

    w = ray_tpu.api._worker()
    blockers = [_sleep.remote(1.2) for _ in range(2)]  # exhaust CPUs
    time.sleep(0.3)
    spec = TaskSpec(
        task_id=TaskID.for_normal_task(JobID.from_hex(w.job_id)).hex(),
        job_id=w.job_id, function_id="f" * 8,
        resources={"CPU": 1}, owner_addr=w.address,
        caller_id=w.worker_id, deadline=time.time() - 1.0)
    t0 = time.monotonic()
    reply = w.agent.call("request_lease", spec=spec.to_wire(), timeout=30)
    assert reply.get("error") == "deadline exceeded", reply
    assert time.monotonic() - t0 < 2.0  # dropped, not lease-timeout'd
    assert ray_tpu.get(blockers, timeout=60) == ["done", "done"]


def test_actor_method_timeout(cluster):
    """.options(timeout_s=...) on actor method calls: an expired call
    resolves with the typed error while the actor survives."""
    @ray_tpu.remote
    class Slowpoke:
        def work(self, s):
            time.sleep(s)
            return "ok"

    a = Slowpoke.remote()
    assert ray_tpu.get(a.work.remote(0.01), timeout=30) == "ok"
    with pytest.raises(ray_tpu.DeadlineExceededError):
        ray_tpu.get(a.work.options(timeout_s=0.3).remote(5), timeout=30)
    # note: the force-cancel path may restart the worker; the actor
    # handle must still answer afterwards (max_restarts=0 actors die
    # with their worker — so assert only that undeadlined calls on a
    # FRESH actor are unaffected by the machinery)
    b = Slowpoke.remote()
    assert ray_tpu.get(b.work.remote(0.01), timeout=60) == "ok"


def test_deadline_metric_counts_sites(cluster):
    from ray_tpu._private.metrics import deadline_metrics

    c = deadline_metrics()
    before = dict(c._values)
    with pytest.raises(ray_tpu.DeadlineExceededError):
        ray_tpu.get(_sleep.options(timeout_s=0.2).remote(5), timeout=30)
    assert sum(c._values.values()) > sum(before.values())


# ----------------------------------------------------- header + helpers


def test_deadline_header_parse():
    now_ms = time.time() * 1000.0
    got = deadlines.from_header(str(now_ms + 5000))
    assert got is not None and abs(got - (now_ms / 1000.0 + 5.0)) < 0.01
    # malformed / absent / non-positive values are ignored, never errors
    for bad in (None, "", "abc", "-5", "0", object()):
        assert deadlines.from_header(bad) is None


def test_effective_deadline_tighter_wins():
    token = deadlines.activate(time.time() + 10.0)
    try:
        tight = deadlines.effective_deadline(1.0)
        assert tight is not None and tight - time.time() < 1.5
        loose = deadlines.effective_deadline(60.0)
        assert loose is not None and loose - time.time() < 11.0
    finally:
        deadlines.restore(token)
    assert deadlines.effective_deadline(None) is None


# ------------------------------------------ rpc reconnect backoff (jitter)


def test_backoff_schedule_exponential_jittered_capped():
    from ray_tpu._private.rpc import backoff_delays

    rng = random.Random(42)
    delays = [next(d) for d in [backoff_delays(0.05, 1.0, rng)]
              for _ in range(12)]
    # each draw sits in [ceiling/2, ceiling] with the ceiling doubling
    # 0.05 -> 0.1 -> ... -> capped at 1.0
    ceiling = 0.05
    for d in delays:
        assert ceiling / 2 - 1e-9 <= d <= ceiling + 1e-9, (d, ceiling)
        ceiling = min(ceiling * 2, 1.0)
    # capped: the tail never exceeds the cap but keeps jittering
    tail = delays[-4:]
    assert all(0.5 <= d <= 1.0 for d in tail), tail
    assert len(set(tail)) > 1, "no jitter at the cap"
    # deterministic per seed, different across seeds (the de-sync)
    a = [next(g) for g in [backoff_delays(rng=random.Random(7))]
         for _ in range(6)]
    b = [next(g) for g in [backoff_delays(rng=random.Random(7))]
         for _ in range(6)]
    c = [next(g) for g in [backoff_delays(rng=random.Random(8))]
         for _ in range(6)]
    assert a == b and a != c


# --------------------------------------- conftest module-budget tripwire


def test_module_budget_violation_detector():
    from conftest import _module_budget_violations

    durations = {"tests/test_a.py": 10.0, "tests/test_b.py": 50.0,
                 "tests/test_c.py": 45.0}
    over = _module_budget_violations(durations, budget=45.0)
    assert over == [("tests/test_b.py", 50.0)]
    assert _module_budget_violations({"m": 1.0}) == []
