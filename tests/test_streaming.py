"""Streaming generator tests (num_returns="streaming").

Mirrors the reference's streaming generator behavior
(reference: python/ray/tests/test_streaming_generator.py;
machinery at python/ray/_raylet.pyx:272,1104): yields are consumable
BEFORE the task finishes, large items ride plasma, mid-stream errors
surface at the break position, and actor methods (sync + async) stream.
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def test_items_stream_before_completion(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        import time as t
        for i in range(5):
            yield (i, t.time())
            t.sleep(0.15)

    g = gen.remote()
    assert isinstance(g, ray_tpu.ObjectRefGenerator)
    # the first item must arrive while the task is still sleeping
    # through later yields — i.e. before ~0.6s of remaining run time
    i0, produced = ray_tpu.get(g.next_ref(timeout=30))
    lag = time.time() - produced
    assert i0 == 0
    assert lag < 0.5, f"first yield arrived {lag:.2f}s after production"
    assert not g.completed()
    rest = [ray_tpu.get(r, timeout=30)[0] for r in g]
    assert rest == [1, 2, 3, 4]
    assert g.completed()


def test_large_items_via_plasma(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def big():
        for i in range(3):
            yield np.full(200_000, i, dtype=np.float64)  # 1.6 MB each

    vals = [ray_tpu.get(r, timeout=60) for r in big.remote()]
    assert [int(v[0]) for v in vals] == [0, 1, 2]
    assert all(len(v) == 200_000 for v in vals)


def test_midstream_error_preserves_prefix(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def bad():
        yield "a"
        yield "b"
        raise ValueError("boom")

    g = bad.remote()
    assert ray_tpu.get(next(g), timeout=30) == "a"
    assert ray_tpu.get(next(g), timeout=30) == "b"
    with pytest.raises(ray_tpu.RayTaskError):
        next(g)


def test_non_generator_body_errors(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def notgen():
        return 42

    with pytest.raises(ray_tpu.RayTaskError):
        next(notgen.remote())


def test_actor_method_streaming(cluster):
    @ray_tpu.remote
    class Gen:
        @ray_tpu.method(num_returns="streaming")
        def tokens(self, n):
            for i in range(n):
                yield f"tok{i}"

        def plain(self):
            return "ok"

    a = Gen.remote()
    toks = [ray_tpu.get(r, timeout=30) for r in a.tokens.remote(5)]
    assert toks == [f"tok{i}" for i in range(5)]
    # non-annotated methods unaffected
    assert ray_tpu.get(a.plain.remote(), timeout=30) == "ok"
    # .options() override works too
    toks = [ray_tpu.get(r, timeout=30)
            for r in a.tokens.options(num_returns="streaming").remote(2)]
    assert toks == ["tok0", "tok1"]


def test_async_generator_streaming(cluster):
    @ray_tpu.remote
    class AGen:
        @ray_tpu.method(num_returns="streaming")
        async def aiter(self, n):
            import asyncio
            for i in range(n):
                await asyncio.sleep(0.01)
                yield i * 2

    a = AGen.remote()  # keep the owning handle alive while streaming
    vals = [ray_tpu.get(r, timeout=30) for r in a.aiter.remote(4)]
    assert vals == [0, 2, 4, 6]


def test_generator_not_picklable(cluster):
    import cloudpickle

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield 1

    g = gen.remote()
    with pytest.raises(TypeError):
        cloudpickle.dumps(g)
    list(g)  # drain


def test_nested_consumption_donates_cpu(cluster):
    """A task consuming a stream must not deadlock the node: the
    consumer donates its CPU while blocked in __next__ (same rule as
    get; reference: HandleWorkerBlocked)."""
    @ray_tpu.remote(num_returns="streaming")
    def producer():
        for i in range(3):
            yield i

    @ray_tpu.remote(num_cpus=4)  # hog every CPU, then consume
    def consumer():
        g = producer.remote()
        return [ray_tpu.get(r) for r in g]

    assert ray_tpu.get(consumer.remote(), timeout=60) == [0, 1, 2]


def test_get_actor_carries_streaming_annotation(cluster):
    """A handle fetched by name must stream like the creating handle —
    @method annotations travel through the head's actor table."""
    import ray_tpu.api as rapi

    class Named:
        @ray_tpu.method(num_returns="streaming")
        def gen(self, n):
            for i in range(n):
                yield i

    a = rapi.ActorClass(Named, name="named-streamer").remote()
    assert ray_tpu.get(next(a.gen.remote(1)), timeout=30) == 0
    h = ray_tpu.get_actor("named-streamer")
    vals = [ray_tpu.get(r, timeout=30) for r in h.gen.remote(3)]
    assert vals == [0, 1, 2]
    ray_tpu.kill(a)


def test_put_inside_streaming_task_no_collision(cluster):
    """put() ObjectIDs and streamed-item ObjectIDs share a task_id but
    partitioned index spaces — no silent collision (regression)."""
    @ray_tpu.remote(num_returns="streaming")
    def gen_with_puts():
        refs = []
        for i in range(5):
            refs.append(ray_tpu.put(i * 100))
            yield i
        # resolve the puts at the end: values must be intact
        assert [ray_tpu.get(r) for r in refs] == [0, 100, 200, 300, 400]
        yield "done"

    vals = [ray_tpu.get(r, timeout=30) for r in gen_with_puts.remote()]
    assert vals == [0, 1, 2, 3, 4, "done"]


def test_yielding_refs_fails_loudly(cluster):
    @ray_tpu.remote(num_returns="streaming")
    def yields_ref():
        yield {"ref": ray_tpu.put([1, 2, 3])}

    with pytest.raises(ray_tpu.RayTaskError):
        next(yields_ref.remote())
