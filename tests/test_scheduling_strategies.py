"""SPREAD / node-affinity / node-label scheduling tests
(reference: python/ray/tests/test_scheduling_2.py strategy coverage,
raylet/scheduling/policy tests)."""

import collections

import pytest

import ray_tpu
from ray_tpu._private.resources import NodeResources, ResourceSet
from ray_tpu._private.scheduler import pick_node
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util import (NodeAffinitySchedulingStrategy,
                          NodeLabelSchedulingStrategy)


def _nr(cpu_total, cpu_used=0.0):
    nr = NodeResources(ResourceSet({"CPU": cpu_total}))
    if cpu_used:
        nr.acquire(ResourceSet({"CPU": cpu_used}))
    return nr


# ---------------------------------------------------------------- unit


def test_pick_node_spread_prefers_least_utilized():
    cluster = {"a": _nr(4, 3), "b": _nr(4, 0), "c": _nr(4, 2)}
    demand = ResourceSet({"CPU": 1})
    picks = {pick_node(cluster, demand, "a", strategy={"type": "spread"})
             for _ in range(10)}
    assert picks == {"b"}


def test_pick_node_affinity_hard_and_soft():
    cluster = {"a": _nr(4), "b": _nr(4)}
    demand = ResourceSet({"CPU": 1})
    strat = {"type": "node_affinity", "node_id": "b", "soft": False}
    assert pick_node(cluster, demand, "a", strategy=strat) == "b"
    # hard affinity to an unknown node: never falls back
    strat = {"type": "node_affinity", "node_id": "zz", "soft": False}
    assert pick_node(cluster, demand, "a", strategy=strat) is None
    # soft affinity falls back to the default policy
    strat = {"type": "node_affinity", "node_id": "zz", "soft": True}
    assert pick_node(cluster, demand, "a", strategy=strat) in ("a", "b")


def test_pick_node_labels():
    cluster = {"a": _nr(4), "b": _nr(4)}
    labels = {"a": {"zone": "us-1"}, "b": {"zone": "eu-2"}}
    demand = ResourceSet({"CPU": 1})
    strat = {"type": "node_label", "hard": {"zone": "eu-2"}}
    assert pick_node(cluster, demand, "a", strategy=strat,
                     labels_by_node=labels) == "b"
    strat = {"type": "node_label", "hard": {"zone": "mars"}}
    assert pick_node(cluster, demand, "a", strategy=strat,
                     labels_by_node=labels) is None


# ------------------------------------------------------------ end-to-end


@pytest.fixture(scope="module")
def two_node():
    cluster = Cluster(head_node_args={"num_cpus": 4})
    cluster.add_node(num_cpus=4, labels={"tier": "accel"})
    ray_tpu.init(address=cluster.address)
    cluster.wait_for_nodes(2)
    try:
        yield cluster
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_spread_uses_both_nodes(two_node):
    @ray_tpu.remote(scheduling_strategy="SPREAD", num_cpus=1)
    def where():
        import os as _os
        import time as _time

        # long enough that the execution-time depth curve keeps the
        # pipeline at depth 1: a batch then NEEDS several leases, so
        # spread exercises both nodes every round instead of the whole
        # batch riding whichever single lease granted first (the first
        # batch's node set used to freeze for the rest of the test)
        _time.sleep(0.2)
        return _os.environ["RT_NODE_ID"]

    import time as _t

    nodes = set()
    deadline = _t.time() + 60
    while len(nodes) < 2 and _t.time() < deadline:
        nodes |= set(ray_tpu.get([where.remote() for _ in range(8)],
                                 timeout=60))
    assert len(nodes) == 2


def test_node_affinity_task_and_actor(two_node):
    target = two_node.nodes[1].node_id

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        target), num_cpus=1)
    def where():
        import os as _os

        return _os.environ["RT_NODE_ID"]

    got = ray_tpu.get([where.remote() for _ in range(4)], timeout=60)
    assert set(got) == {target}

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        target))
    class Pinned:
        def where(self):
            import os as _os

            return _os.environ["RT_NODE_ID"]

    a = Pinned.remote()
    assert ray_tpu.get(a.where.remote(), timeout=60) == target


def test_node_label_strategy(two_node):
    labeled = two_node.nodes[1].node_id

    @ray_tpu.remote(scheduling_strategy=NodeLabelSchedulingStrategy(
        hard={"tier": "accel"}), num_cpus=1)
    def where():
        import os as _os

        return _os.environ["RT_NODE_ID"]

    assert ray_tpu.get(where.remote(), timeout=60) == labeled


def test_hard_affinity_to_dead_node_fails(two_node):
    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        "0" * 56), num_cpus=1, max_retries=0)
    def f():
        return 1

    with pytest.raises(ray_tpu.RayError):
        ray_tpu.get(f.remote(), timeout=60)
