"""Object store tests: allocator, arena, server/client over real RPC."""

import gc
import os
import uuid

import numpy as np
import pytest

from ray_tpu._private.object_store import (
    Buffer,
    FreeListAllocator,
    ObjectAlreadyExists,
    PlasmaClient,
    ShmArena,
    StoreCore,
)
from ray_tpu._private.rpc import EventLoopThread, RpcHost, RpcServer, SyncRpcClient
from ray_tpu._private import serialization


class TestAllocator:
    def test_alloc_free_coalesce(self):
        a = FreeListAllocator(1024)
        o1 = a.alloc(100)   # rounds to 128
        o2 = a.alloc(100)
        o3 = a.alloc(100)
        assert {o1, o2, o3} == {0, 128, 256}
        a.free(o2, 100)
        a.free(o1, 100)
        # coalesced: can allocate 256 contiguous at 0
        assert a.alloc(256) == 0
        a.free(o3, 100)

    def test_alignment(self):
        a = FreeListAllocator(1 << 20)
        offs = [a.alloc(n) for n in (1, 63, 65, 1000)]
        assert all(o % 64 == 0 for o in offs)

    def test_exhaustion(self):
        a = FreeListAllocator(256)
        assert a.alloc(256) == 0
        assert a.alloc(1) is None


class TestArena:
    def test_create_attach_shared(self, tmp_path):
        path = str(tmp_path / "arena")
        a = ShmArena.create(path, 4096)
        b = ShmArena.attach(path)
        a.view[100:104] = b"abcd"
        assert bytes(b.view[100:104]) == b"abcd"
        a.close(unlink=True)
        b.close()


class _StoreHost(RpcHost):
    """Minimal RPC facade over StoreCore (the node agent embeds the same)."""

    def __init__(self, core: StoreCore):
        self.core = core

    async def rpc_store_create(self, oid=None, size=None, primary=True):
        return self.core.create(oid, size, primary=primary)

    async def rpc_store_seal(self, oid=None):
        self.core.seal(oid)
        return {}

    async def rpc_store_get(self, oids=None, client_id=None, wait_timeout=None):
        return await self.core.get(oids, client_id, wait_timeout=wait_timeout)

    async def rpc_store_release(self, oid=None, client_id=None):
        self.core.release(oid, client_id)

    async def rpc_store_abort(self, oid=None):
        self.core.abort(oid)
        return {}

    async def rpc_store_free(self, oids=None):
        self.core.free(oids)
        return {}

    async def rpc_store_contains(self, oid=None):
        return self.core.contains(oid)


@pytest.fixture
def store(tmp_path):
    """A StoreCore served over RPC + an attached PlasmaClient."""
    arena_path = str(tmp_path / "arena")
    core = StoreCore(arena_path, 1 << 20, str(tmp_path / "spill"))
    host = _StoreHost(core)
    io = EventLoopThread()
    server = RpcServer(host)
    port = io.run(server.start())
    rpc = SyncRpcClient("127.0.0.1", port, io)
    client = PlasmaClient(arena_path, rpc, client_id="test-client")
    yield core, client
    client.close()
    rpc.close()
    io.run(server.stop())
    io.stop()
    core.close()


def _oid():
    return uuid.uuid4().hex


class TestStore:
    def test_put_get_roundtrip(self, store):
        core, client = store
        oid = _oid()
        value = {"x": [1, 2, 3], "arr": np.arange(100, dtype=np.int64)}
        frames, size = serialization.serialize(value)
        client.put_serialized(oid, frames, size)
        (out,) = client.get_values([oid])
        assert out["x"] == [1, 2, 3]
        np.testing.assert_array_equal(out["arr"], np.arange(100, dtype=np.int64))

    def test_zero_copy_and_pin_release(self, store):
        from ray_tpu._private.object_store import _PEP688

        core, client = store
        oid = _oid()
        arr = np.arange(10000, dtype=np.float64)
        frames, size = serialization.serialize(arr)
        client.put_serialized(oid, frames, size)
        (out,) = client.get_values([oid])
        np.testing.assert_array_equal(out, arr)
        entry = core.objects[oid]
        if not _PEP688:
            # pre-3.12 interpreters can't export the buffer protocol from
            # a Python class: loads copy the frames and unpin immediately
            import time
            for _ in range(100):
                if not entry.pinned:
                    break
                time.sleep(0.02)
            assert not entry.pinned
            return
        # zero copy: the array's memory lives inside the arena mapping
        base = np.frombuffer(client.arena.view, dtype=np.uint8).ctypes.data
        assert base <= out.ctypes.data < base + client.arena.size
        assert out.ctypes.data % 64 == 0
        assert entry.pinned
        del out
        gc.collect()
        import time
        for _ in range(100):
            if not entry.pinned:
                break
            time.sleep(0.02)
        assert not entry.pinned

    def test_duplicate_create_rejected(self, store):
        core, client = store
        oid = _oid()
        client.put_raw(oid, b"hello")
        from ray_tpu._private.rpc import RpcError
        with pytest.raises(RpcError):
            client.rpc.call("store_create", oid=oid, size=5, primary=True)

    def test_free(self, store):
        core, client = store
        oid = _oid()
        client.put_raw(oid, b"data")
        assert client.contains(oid)
        client.free([oid])
        assert not client.contains(oid)
        with pytest.raises(KeyError, match="freed"):
            client.get_values([oid], timeout=0.5)

    def test_free_of_pinned_object_hides_it(self, store):
        core, client = store
        oid = _oid()
        arr = np.arange(4096, dtype=np.float64)
        frames, size = serialization.serialize(arr)
        client.put_serialized(oid, frames, size)
        (out,) = client.get_values([oid])  # holds a pin via the live array
        client.free([oid])
        # freed-but-pinned: invisible to contains/get, dropped once unpinned
        assert not client.contains(oid)
        with pytest.raises(KeyError, match="freed"):
            client.get_values([oid], timeout=0.2)
        np.testing.assert_array_equal(out, arr)  # existing reader unaffected

    def test_partial_get_releases_pins(self, store):
        core, client = store
        oid = _oid()
        frames, size = serialization.serialize(np.zeros(64))
        client.put_serialized(oid, frames, size)
        with pytest.raises(KeyError):
            client.get_values([oid, _oid()], timeout=0.2)
        import time
        entry = core.objects[oid]
        for _ in range(100):
            if not entry.pinned:
                break
            time.sleep(0.02)
        assert not entry.pinned

    def test_eviction_of_secondary_copies(self, store):
        core, client = store
        # fill with secondary (non-primary) copies, then overflow: LRU evicted
        oids = []
        for i in range(8):
            oid = _oid()
            frames, size = serialization.serialize(np.zeros(1 << 14, dtype=np.float64))
            client.put_serialized(oid, frames, size, primary=False)  # 128KB each
            oids.append(oid)
        big = _oid()
        frames, size = serialization.serialize(np.zeros(1 << 15, dtype=np.float64))
        client.put_serialized(big, frames, size)  # 256KB forces eviction
        assert core.num_evicted > 0
        assert client.contains(big)

    def test_spill_and_disk_fallback(self, store):
        core, client = store
        # primary objects overflowing the 1MB arena spill to disk
        oids = []
        for i in range(10):
            oid = _oid()
            frames, size = serialization.serialize(np.full(1 << 14, i, dtype=np.float64))
            client.put_serialized(oid, frames, size)  # 128KB each, 1.28MB total
            oids.append(oid)
        assert core.num_spilled > 0 or any(
            core.objects[o].location == "disk" for o in oids)
        # all values still readable (spilled ones restored from disk)
        for i, oid in enumerate(oids):
            (out,) = client.get_values([oid])
            assert out[0] == i

    def test_oversized_object_goes_to_disk(self, store):
        core, client = store
        oid = _oid()
        arr = np.arange(1 << 18, dtype=np.float64)  # 2MB > 1MB arena
        frames, size = serialization.serialize(arr)
        client.put_serialized(oid, frames, size)
        assert core.objects[oid].location == "disk"
        (out,) = client.get_values([oid])
        np.testing.assert_array_equal(out, arr)

    def test_get_blocks_until_seal(self, store):
        core, client = store
        oid = _oid()
        data = serialization.serialize_to_bytes("late")
        loc = client.rpc.call("store_create", oid=oid, size=len(data), primary=True)
        import threading, time
        result = {}

        def getter():
            result["v"] = client.get_values([oid], timeout=10)

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(0.2)
        assert "v" not in result
        client.arena.view[loc["offset"]:loc["offset"] + len(data)] = data
        client.rpc.call("store_seal", oid=oid)
        t.join(timeout=5)
        assert result["v"] == ["late"]


class TestBuffer:
    @pytest.mark.skipif(
        __import__("sys").version_info < (3, 12),
        reason="Buffer exports the C buffer protocol via PEP 688 (3.12+)")
    def test_buffer_protocol_roots_exporter(self):
        released = []
        raw = bytearray(b"x" * 128)
        buf = Buffer(memoryview(raw), on_release=lambda: released.append(1))
        arr = np.frombuffer(buf, dtype=np.uint8)
        del buf
        gc.collect()
        assert not released  # array keeps the Buffer alive
        del arr
        gc.collect()
        assert released == [1]
