"""CLI + job submission tests.

Mirrors the reference's CLI and job-manager suites
(reference: python/ray/tests/test_cli.py,
dashboard/modules/job/tests/test_job_manager.py): a cluster stood up
entirely from the shell runs a submitted job to completion, with
status and logs retrievable from any client.
"""

import json
import os
import subprocess
import sys
import time

import pytest

import ray_tpu
from ray_tpu._private.spawn import fast_python_cmd


@pytest.fixture
def isolated_tmpdir(tmp_path, monkeypatch):
    monkeypatch.setenv("RT_TMPDIR", str(tmp_path))
    return str(tmp_path)


def _cli(args, tmpdir, timeout=120):
    cmd, env_up = fast_python_cmd("ray_tpu.scripts", list(args))
    env = dict(os.environ)
    env.update(env_up)
    env["RT_TMPDIR"] = tmpdir
    return subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=timeout)


def test_cli_start_status_job_stop(isolated_tmpdir):
    tmp = isolated_tmpdir
    r = _cli(["start", "--head", "--num-cpus", "4"], tmp)
    assert r.returncode == 0, r.stderr
    assert "cluster started at" in r.stdout
    try:
        r = _cli(["status"], tmp)
        assert r.returncode == 0, r.stderr
        assert "1 node(s)" in r.stdout

        script = os.path.join(tmp, "jobscript.py")
        with open(script, "w") as f:
            f.write(
                "import ray_tpu\n"
                "ray_tpu.init()\n"  # RT_ADDRESS from the supervisor
                "@ray_tpu.remote\n"
                "def sq(x):\n"
                "    return x * x\n"
                "print('job result:', ray_tpu.get("
                "[sq.remote(i) for i in range(4)], timeout=60))\n"
                "ray_tpu.shutdown()\n")
        r = _cli(["job", "submit", "--wait", "--",
                  sys.executable, "-S", script], tmp, timeout=240)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "SUCCEEDED" in r.stdout
        assert "job result: [0, 1, 4, 9]" in r.stdout

        r = _cli(["job", "list"], tmp)
        assert r.returncode == 0
        assert "SUCCEEDED" in r.stdout
    finally:
        r = _cli(["stop"], tmp)
    assert r.returncode == 0, r.stderr


def test_cli_worker_join(isolated_tmpdir):
    tmp = isolated_tmpdir
    r = _cli(["start", "--head", "--num-cpus", "2"], tmp)
    assert r.returncode == 0, r.stderr
    address = [ln for ln in r.stdout.splitlines()
               if "cluster started at" in ln][0].split()[-1]
    try:
        r = _cli(["start", "--address", address, "--num-cpus", "2",
                  "--resources", json.dumps({"extra": 1})], tmp)
        assert r.returncode == 0, r.stderr
        deadline = time.monotonic() + 30
        ok = False
        while time.monotonic() < deadline:
            r = _cli(["status"], tmp)
            if "2 node(s)" in r.stdout:
                ok = True
                break
            time.sleep(0.5)
        assert ok, r.stdout
    finally:
        _cli(["stop"], tmp)


def test_job_api_stop_and_logs(isolated_tmpdir):
    tmp = isolated_tmpdir
    r = _cli(["start", "--head", "--num-cpus", "4"], tmp)
    assert r.returncode == 0, r.stderr
    address = [ln for ln in r.stdout.splitlines()
               if "cluster started at" in ln][0].split()[-1]
    try:
        from ray_tpu.job_submission import JobSubmissionClient

        client = JobSubmissionClient(address)
        try:
            job_id = client.submit_job(
                f"{sys.executable} -S -c \"import time\n"
                "print('spinning', flush=True)\n"
                "time.sleep(600)\"")
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if client.get_job_status(job_id) == "RUNNING" \
                        and "spinning" in client.get_job_logs(job_id):
                    break
                time.sleep(0.3)
            assert client.get_job_status(job_id) == "RUNNING"
            client.stop_job(job_id)
            status = client.wait_until_finish(job_id, timeout=60)
            assert status == "STOPPED"
            assert "spinning" in client.get_job_logs(job_id)
        finally:
            client.close()
    finally:
        _cli(["stop"], tmp)
