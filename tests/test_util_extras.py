"""ActorPool / Queue / multiprocessing.Pool tests
(reference: python/ray/tests/test_actor_pool.py, test_queue.py,
python/ray/util/multiprocessing tests)."""

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.multiprocessing import Pool
from ray_tpu.util.queue import Empty, Full, Queue


@ray_tpu.remote
class _Doubler:
    def double(self, v):
        return 2 * v


def test_actor_pool_ordered(local_cluster):
    pool = ActorPool([_Doubler.remote() for _ in range(2)])
    out = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert out == [2 * v for v in range(8)]


def test_actor_pool_unordered_and_queueing(local_cluster):
    # 2 actors, 6 items: work must queue behind busy actors
    pool = ActorPool([_Doubler.remote() for _ in range(2)])
    out = sorted(pool.map_unordered(lambda a, v: a.double.remote(v), range(6)))
    assert out == [2 * v for v in range(6)]


def test_actor_pool_submit_get_next(local_cluster):
    pool = ActorPool([_Doubler.remote()])
    assert not pool.has_next()
    pool.submit(lambda a, v: a.double.remote(v), 10)
    pool.submit(lambda a, v: a.double.remote(v), 11)  # queues: 1 actor
    assert pool.has_next()
    assert pool.get_next(timeout=30) == 20
    assert pool.get_next(timeout=30) == 22
    assert not pool.has_next()
    with pytest.raises(StopIteration):
        pool.get_next()


def test_actor_pool_push_pop(local_cluster):
    a, b = _Doubler.remote(), _Doubler.remote()
    pool = ActorPool([a])
    popped = pool.pop_idle()
    assert popped is a
    assert pool.pop_idle() is None
    pool.push(b)
    assert pool.has_free()
    with pytest.raises(ValueError):
        pool.push(b)


def test_queue_fifo_and_batches(local_cluster):
    q = Queue(maxsize=5)
    for i in range(3):
        q.put(i, timeout=10)
    assert q.qsize() == 3 and not q.empty() and not q.full()
    assert [q.get(timeout=10) for _ in range(3)] == [0, 1, 2]
    q.put_nowait_batch([7, 8, 9])
    assert q.get_nowait_batch(3) == [7, 8, 9]
    q.shutdown()


def test_queue_empty_full(local_cluster):
    q = Queue(maxsize=1)
    q.put_nowait("x")
    assert q.full()
    with pytest.raises(Full):
        q.put_nowait("y")
    with pytest.raises(Full):
        q.put("y", timeout=0.2)
    assert q.get_nowait() == "x"
    with pytest.raises(Empty):
        q.get_nowait()
    with pytest.raises(Empty):
        q.get(timeout=0.2)
    q.shutdown()


def test_queue_cross_actor(local_cluster):
    """A queue handle works from inside another actor."""
    q = Queue()

    @ray_tpu.remote
    class Producer:
        def produce(self, q, n):
            for i in range(n):
                q.put(i)
            return True

    p = Producer.remote()
    assert ray_tpu.get(p.produce.remote(q, 4), timeout=60)
    assert sorted(q.get(timeout=10) for _ in range(4)) == [0, 1, 2, 3]
    q.shutdown()


def test_mp_pool_map(local_cluster):
    _square = lambda x: x * x  # noqa: E731 — by-value pickling for workers
    with Pool(processes=2) as pool:
        assert pool.map(_square, range(10)) == [x * x for x in range(10)]


def test_mp_pool_apply_starmap_imap(local_cluster):
    _square = lambda x: x * x  # noqa: E731
    pool = Pool(processes=2)
    try:
        assert pool.apply(divmod, (7, 3)) == (2, 1)
        res = pool.apply_async(_square, (6,))
        assert res.get(timeout=60) == 36
        assert res.successful()
        assert pool.starmap(pow, [(2, 3), (3, 2)]) == [8, 9]
        assert list(pool.imap(_square, range(5), chunksize=2)) == \
            [0, 1, 4, 9, 16]
        assert sorted(pool.imap_unordered(_square, range(5))) == \
            [0, 1, 4, 9, 16]
    finally:
        pool.terminate()


def test_mp_pool_closed_raises(local_cluster):
    pool = Pool(processes=1)
    pool.close()
    with pytest.raises(ValueError):
        pool.map(len, [[1]])
    pool.join()


def test_queue_batch_failure_drains_nothing(local_cluster):
    q = Queue(maxsize=5)
    q.put_nowait_batch([1, 2])
    with pytest.raises(Empty):
        q.get_nowait_batch(3)  # atomic: must not drain the 2 items
    assert q.qsize() == 2
    with pytest.raises(Full):
        q.put_nowait_batch([3, 4, 5, 6])  # atomic: nothing inserted
    assert q.qsize() == 2
    q.shutdown()


def test_mp_pool_timed_out_get_recovers(local_cluster):
    import time as _t

    pool = Pool(processes=1)
    try:
        res = pool.apply_async(_t.sleep, (1.5,))
        with pytest.raises(ray_tpu.GetTimeoutError):
            res.get(timeout=0.2)
        assert res.get(timeout=30) is None  # still succeeds afterwards
        assert res.successful()
    finally:
        pool.terminate()


def test_mp_pool_callback_fires_without_get(local_cluster):
    import time as _t

    hits = []
    pool = Pool(processes=1)
    try:
        pool.apply_async(int, ("42",), callback=hits.append)
        deadline = _t.time() + 30
        while not hits and _t.time() < deadline:
            _t.sleep(0.1)
        assert hits == [42]
    finally:
        pool.terminate()
