"""Resource sets, task spec wire format, local/cluster scheduling."""

import random

import pytest

from ray_tpu._private.resources import NodeResources, ResourceSet
from ray_tpu._private.scheduler import LocalScheduler, pick_node
from ray_tpu._private.task_spec import TaskSpec, WireArg


class TestResourceSet:
    def test_fixed_point_exact(self):
        r = ResourceSet({"CPU": 0.1})
        total = ResourceSet({})
        for _ in range(10):
            total = total.add(r)
        assert total == ResourceSet({"CPU": 1.0})
        for _ in range(10):
            total = total.subtract(r)
        assert total.is_empty()

    def test_fits_and_underflow(self):
        avail = ResourceSet({"CPU": 4, "TPU": 8})
        assert avail.fits(ResourceSet({"CPU": 2, "TPU": 8}))
        assert not avail.fits(ResourceSet({"CPU": 5}))
        assert not avail.fits(ResourceSet({"custom": 1}))
        with pytest.raises(ValueError):
            avail.subtract(ResourceSet({"GPU": 1}))

    def test_node_acquire_release(self):
        nr = NodeResources(ResourceSet({"CPU": 2, "TPU": 4}))
        d = ResourceSet({"CPU": 1, "TPU": 4})
        assert nr.acquire(d)
        assert not nr.acquire(d)  # TPUs exhausted
        assert nr.utilization() == 1.0
        nr.release(d)
        assert nr.available == nr.total
        # double release clamps at total
        nr.release(d)
        assert nr.available == nr.total

    def test_feasible_vs_available(self):
        nr = NodeResources(ResourceSet({"TPU": 4}))
        nr.acquire(ResourceSet({"TPU": 4}))
        assert nr.is_feasible(ResourceSet({"TPU": 4}))
        assert not nr.can_fit(ResourceSet({"TPU": 4}))
        assert not nr.is_feasible(ResourceSet({"TPU": 8}))


class TestTaskSpec:
    def test_wire_roundtrip(self):
        spec = TaskSpec(
            task_id="ab" * 12, job_id="01020304", function_id="ff" * 8,
            args=[WireArg(value=b"inline"),
                  WireArg(object_id="cd" * 14, owner_addr=("127.0.0.1", 9000)),
                  WireArg(value=b"kwv", kw="key")],
            num_returns=2, resources={"CPU": 1, "TPU": 0.5},
            actor_id="ee" * 8, method_name="step", seqno=7,
            owner_addr=("10.0.0.1", 1234),
        )
        import msgpack
        wire = msgpack.unpackb(msgpack.packb(spec.to_wire(), use_bin_type=True),
                               raw=False)
        back = TaskSpec.from_wire(wire)
        assert back.task_id == spec.task_id
        assert back.args[0].value == b"inline"
        assert back.args[1].object_id == "cd" * 14
        assert back.args[1].owner_addr == ("127.0.0.1", 9000)
        assert back.args[2].kw == "key"
        assert back.resources == {"CPU": 1, "TPU": 0.5}
        assert back.owner_addr == ("10.0.0.1", 1234)
        assert back.seqno == 7

    def test_scheduling_class_groups_same_shape(self):
        a = TaskSpec(task_id="a", job_id="j", resources={"CPU": 1})
        b = TaskSpec(task_id="b", job_id="j", resources={"CPU": 1.0})
        c = TaskSpec(task_id="c", job_id="j", resources={"CPU": 2})
        assert a.scheduling_class() == b.scheduling_class()
        assert a.scheduling_class() != c.scheduling_class()


class TestLocalScheduler:
    def test_fifo_with_resources(self):
        s = LocalScheduler(NodeResources(ResourceSet({"CPU": 2})))
        one = ResourceSet({"CPU": 1})
        assert s.try_acquire(one)
        assert s.try_acquire(one)
        assert not s.try_acquire(one)
        s.enqueue("t3", one)
        s.enqueue("t4", one)
        assert s.release(one) == ["t3"]
        assert s.release(one) == ["t4"]

    def test_fifo_order_preserved_under_mixed_sizes(self):
        s = LocalScheduler(NodeResources(ResourceSet({"CPU": 4})))
        big, small = ResourceSet({"CPU": 4}), ResourceSet({"CPU": 1})
        assert s.try_acquire(big)
        s.enqueue("big2", big)
        s.enqueue("small", small)
        # small fits now but must wait behind big2 (FIFO head-of-line)
        assert s.try_acquire(small) is False
        granted = s.release(big)
        assert granted == ["big2"]

    def test_cancel(self):
        s = LocalScheduler(NodeResources(ResourceSet({"CPU": 1})))
        assert s.try_acquire(ResourceSet({"CPU": 1}))
        s.enqueue("x" * 9, ResourceSet({"CPU": 1}))
        found, granted = s.cancel("xxxxxxxx" + "x")  # equal, not identical
        assert found and granted == []
        assert s.release(ResourceSet({"CPU": 1})) == []

    def test_cancel_head_of_line_unblocks(self):
        s = LocalScheduler(NodeResources(ResourceSet({"CPU": 2})))
        assert s.try_acquire(ResourceSet({"CPU": 1}))
        s.enqueue("big", ResourceSet({"CPU": 2}))
        s.enqueue("small", ResourceSet({"CPU": 1}))
        found, granted = s.cancel("big")
        assert found and granted == ["small"]


class TestHybridPolicy:
    def _cluster(self):
        c = {}
        for nid, cpus in [("n1", 4), ("n2", 4), ("n3", 4)]:
            c[nid] = NodeResources(ResourceSet({"CPU": cpus}))
        return c

    def test_prefers_local_when_underloaded(self):
        c = self._cluster()
        assert pick_node(c, ResourceSet({"CPU": 1}), "n2") == "n2"

    def test_spreads_when_local_hot(self):
        c = self._cluster()
        c["n1"].acquire(ResourceSet({"CPU": 3}))  # 75% util > 0.5 threshold
        rng = random.Random(0)
        picks = {pick_node(c, ResourceSet({"CPU": 1}), "n1", rng=rng)
                 for _ in range(20)}
        assert "n1" not in picks
        assert picks <= {"n2", "n3"}

    def test_queues_on_feasible_when_all_busy(self):
        c = self._cluster()
        for nr in c.values():
            nr.acquire(ResourceSet({"CPU": 4}))
        pick = pick_node(c, ResourceSet({"CPU": 2}), "n1")
        assert pick in c

    def test_infeasible_returns_none(self):
        c = self._cluster()
        assert pick_node(c, ResourceSet({"TPU": 8}), "n1") is None

    def test_tpu_demand_targets_tpu_node(self):
        c = self._cluster()
        c["tpu-node"] = NodeResources(ResourceSet({"CPU": 1, "TPU": 8}))
        assert pick_node(c, ResourceSet({"TPU": 4}), "n1") == "tpu-node"
