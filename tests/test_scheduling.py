"""Resource sets, task spec wire format, local/cluster scheduling."""

import random

import pytest

from ray_tpu._private.resources import NodeResources, ResourceSet
from ray_tpu._private.scheduler import LocalScheduler, pick_node
from ray_tpu._private.task_spec import TaskSpec, WireArg


class TestResourceSet:
    def test_fixed_point_exact(self):
        r = ResourceSet({"CPU": 0.1})
        total = ResourceSet({})
        for _ in range(10):
            total = total.add(r)
        assert total == ResourceSet({"CPU": 1.0})
        for _ in range(10):
            total = total.subtract(r)
        assert total.is_empty()

    def test_fits_and_underflow(self):
        avail = ResourceSet({"CPU": 4, "TPU": 8})
        assert avail.fits(ResourceSet({"CPU": 2, "TPU": 8}))
        assert not avail.fits(ResourceSet({"CPU": 5}))
        assert not avail.fits(ResourceSet({"custom": 1}))
        with pytest.raises(ValueError):
            avail.subtract(ResourceSet({"GPU": 1}))

    def test_node_acquire_release(self):
        nr = NodeResources(ResourceSet({"CPU": 2, "TPU": 4}))
        d = ResourceSet({"CPU": 1, "TPU": 4})
        assert nr.acquire(d)
        assert not nr.acquire(d)  # TPUs exhausted
        assert nr.utilization() == 1.0
        nr.release(d)
        assert nr.available == nr.total
        # double release clamps at total
        nr.release(d)
        assert nr.available == nr.total

    def test_feasible_vs_available(self):
        nr = NodeResources(ResourceSet({"TPU": 4}))
        nr.acquire(ResourceSet({"TPU": 4}))
        assert nr.is_feasible(ResourceSet({"TPU": 4}))
        assert not nr.can_fit(ResourceSet({"TPU": 4}))
        assert not nr.is_feasible(ResourceSet({"TPU": 8}))


class TestTaskSpec:
    def test_wire_roundtrip(self):
        spec = TaskSpec(
            task_id="ab" * 12, job_id="01020304", function_id="ff" * 8,
            args=[WireArg(value=b"inline"),
                  WireArg(object_id="cd" * 14, owner_addr=("127.0.0.1", 9000),
                          size=4 * 1024 * 1024, loc=("10.0.0.2", 7001)),
                  WireArg(value=b"kwv", kw="key")],
            num_returns=2, resources={"CPU": 1, "TPU": 0.5},
            actor_id="ee" * 8, method_name="step", seqno=7,
            owner_addr=("10.0.0.1", 1234),
        )
        import msgpack
        wire = msgpack.unpackb(msgpack.packb(spec.to_wire(), use_bin_type=True),
                               raw=False)
        back = TaskSpec.from_wire(wire)
        assert back.task_id == spec.task_id
        assert back.args[0].value == b"inline"
        assert back.args[1].object_id == "cd" * 14
        assert back.args[1].owner_addr == ("127.0.0.1", 9000)
        assert back.args[1].size == 4 * 1024 * 1024
        assert back.args[1].loc == ("10.0.0.2", 7001)
        assert back.args[0].loc is None and back.args[0].size == 0
        assert back.args[2].kw == "key"
        assert back.resources == {"CPU": 1, "TPU": 0.5}
        assert back.owner_addr == ("10.0.0.1", 1234)
        assert back.seqno == 7

    def test_scheduling_class_groups_same_shape(self):
        a = TaskSpec(task_id="a", job_id="j", resources={"CPU": 1})
        b = TaskSpec(task_id="b", job_id="j", resources={"CPU": 1.0})
        c = TaskSpec(task_id="c", job_id="j", resources={"CPU": 2})
        assert a.scheduling_class() == b.scheduling_class()
        assert a.scheduling_class() != c.scheduling_class()


class TestLocalScheduler:
    def test_fifo_with_resources(self):
        s = LocalScheduler(NodeResources(ResourceSet({"CPU": 2})))
        one = ResourceSet({"CPU": 1})
        assert s.try_acquire(one)
        assert s.try_acquire(one)
        assert not s.try_acquire(one)
        s.enqueue("t3", one)
        s.enqueue("t4", one)
        assert s.release(one) == ["t3"]
        assert s.release(one) == ["t4"]

    def test_fifo_order_preserved_under_mixed_sizes(self):
        s = LocalScheduler(NodeResources(ResourceSet({"CPU": 4})))
        big, small = ResourceSet({"CPU": 4}), ResourceSet({"CPU": 1})
        assert s.try_acquire(big)
        s.enqueue("big2", big)
        s.enqueue("small", small)
        # small fits now but must wait behind big2 (FIFO head-of-line)
        assert s.try_acquire(small) is False
        granted = s.release(big)
        assert granted == ["big2"]

    def test_cancel(self):
        s = LocalScheduler(NodeResources(ResourceSet({"CPU": 1})))
        assert s.try_acquire(ResourceSet({"CPU": 1}))
        s.enqueue("x" * 9, ResourceSet({"CPU": 1}))
        found, granted = s.cancel("xxxxxxxx" + "x")  # equal, not identical
        assert found and granted == []
        assert s.release(ResourceSet({"CPU": 1})) == []

    def test_cancel_head_of_line_unblocks(self):
        s = LocalScheduler(NodeResources(ResourceSet({"CPU": 2})))
        assert s.try_acquire(ResourceSet({"CPU": 1}))
        s.enqueue("big", ResourceSet({"CPU": 2}))
        s.enqueue("small", ResourceSet({"CPU": 1}))
        found, granted = s.cancel("big")
        assert found and granted == ["small"]


class TestHybridPolicy:
    def _cluster(self):
        c = {}
        for nid, cpus in [("n1", 4), ("n2", 4), ("n3", 4)]:
            c[nid] = NodeResources(ResourceSet({"CPU": cpus}))
        return c

    def test_prefers_local_when_underloaded(self):
        c = self._cluster()
        assert pick_node(c, ResourceSet({"CPU": 1}), "n2") == "n2"

    def test_spreads_when_local_hot(self):
        c = self._cluster()
        c["n1"].acquire(ResourceSet({"CPU": 3}))  # 75% util > 0.5 threshold
        rng = random.Random(0)
        picks = {pick_node(c, ResourceSet({"CPU": 1}), "n1", rng=rng)
                 for _ in range(20)}
        assert "n1" not in picks
        assert picks <= {"n2", "n3"}

    def test_queues_on_feasible_when_all_busy(self):
        c = self._cluster()
        for nr in c.values():
            nr.acquire(ResourceSet({"CPU": 4}))
        pick = pick_node(c, ResourceSet({"CPU": 2}), "n1")
        assert pick in c

    def test_infeasible_returns_none(self):
        c = self._cluster()
        assert pick_node(c, ResourceSet({"TPU": 8}), "n1") is None

    def test_tpu_demand_targets_tpu_node(self):
        c = self._cluster()
        c["tpu-node"] = NodeResources(ResourceSet({"CPU": 1, "TPU": 8}))
        assert pick_node(c, ResourceSet({"TPU": 4}), "n1") == "tpu-node"


class TestLocalityScoring:
    MB = 1024 * 1024

    def _cluster(self):
        return {nid: NodeResources(ResourceSet({"CPU": 4}))
                for nid in ("n1", "n2", "n3")}

    def test_holder_beats_local_preference(self):
        c = self._cluster()
        # n1 is local, idle and under the spread threshold — without
        # locality it would win; the argument bytes on n3 override that
        pick = pick_node(c, ResourceSet({"CPU": 1}), "n1",
                         arg_bytes_by_node={"n3": 8 * self.MB},
                         locality_min_bytes=self.MB)
        assert pick == "n3"

    def test_below_threshold_falls_back_to_hybrid(self):
        c = self._cluster()
        pick = pick_node(c, ResourceSet({"CPU": 1}), "n1",
                         arg_bytes_by_node={"n3": self.MB // 2},
                         locality_min_bytes=self.MB)
        assert pick == "n1"  # hybrid local preference

    def test_most_bytes_wins(self):
        c = self._cluster()
        pick = pick_node(c, ResourceSet({"CPU": 1}), "n1",
                         arg_bytes_by_node={"n2": 2 * self.MB,
                                            "n3": 16 * self.MB},
                         locality_min_bytes=self.MB)
        assert pick == "n3"

    def test_tie_breaks_toward_colder_node(self):
        c = self._cluster()
        c["n2"].acquire(ResourceSet({"CPU": 2}))
        pick = pick_node(c, ResourceSet({"CPU": 1}), "n1",
                         arg_bytes_by_node={"n2": 4 * self.MB,
                                            "n3": 4 * self.MB},
                         locality_min_bytes=self.MB)
        assert pick == "n3"

    def test_full_but_feasible_holder_still_wins(self):
        # skipping the transfer beats a short queue wait: a busy holder
        # still receives the lease (queued demand triggers warm-lease
        # reclaim there); only an INFEASIBLE holder falls back
        c = self._cluster()
        c["n3"].acquire(ResourceSet({"CPU": 4}))
        pick = pick_node(c, ResourceSet({"CPU": 1}), "n1",
                         arg_bytes_by_node={"n3": 8 * self.MB},
                         locality_min_bytes=self.MB)
        assert pick == "n3"

    def test_available_holder_beats_fuller_holder(self):
        c = self._cluster()
        c["n3"].acquire(ResourceSet({"CPU": 4}))
        pick = pick_node(c, ResourceSet({"CPU": 1}), "n1",
                         arg_bytes_by_node={"n3": 8 * self.MB,
                                            "n2": 4 * self.MB},
                         locality_min_bytes=self.MB)
        assert pick == "n2"  # fewer bytes but can run it NOW

    def test_infeasible_holder_falls_back_to_hybrid(self):
        c = self._cluster()
        c["tpu"] = NodeResources(ResourceSet({"CPU": 4, "TPU": 4}))
        # the holder can never run a TPU demand: hybrid policy decides
        pick = pick_node(c, ResourceSet({"CPU": 1, "TPU": 1}), "n1",
                         arg_bytes_by_node={"n3": 8 * self.MB},
                         locality_min_bytes=self.MB)
        assert pick == "tpu"

    def test_strategy_overrides_unaffected(self):
        c = self._cluster()
        hints = {"n3": 8 * self.MB}
        assert pick_node(c, ResourceSet({"CPU": 1}), "n1",
                         strategy={"type": "node_affinity", "node_id": "n2"},
                         arg_bytes_by_node=hints,
                         locality_min_bytes=self.MB) == "n2"
        rng = random.Random(0)
        spread = {pick_node(c, ResourceSet({"CPU": 1}), "n1", rng=rng,
                            strategy={"type": "spread"},
                            arg_bytes_by_node=hints,
                            locality_min_bytes=self.MB)
                  for _ in range(20)}
        assert spread == {"n1", "n2", "n3"}  # least-utilized, ignores bytes

    def test_unknown_holder_node_ignored(self):
        c = self._cluster()
        pick = pick_node(c, ResourceSet({"CPU": 1}), "n1",
                         arg_bytes_by_node={"dead-node": 64 * self.MB},
                         locality_min_bytes=self.MB)
        assert pick == "n1"
