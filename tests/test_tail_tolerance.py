"""Serve tail tolerance: hedged requests, per-replica circuit breakers,
gray-failure chaos (ISSUE 14).

Unit coverage for the ReplicaCircuit state machine (injectable clock,
sleep-free) and the hedge-delay policy; e2e coverage for a 2-replica
deployment with one GRAY (slow, not dead) replica — hedging absorbs it
and the circuit breaker evicts it from routing — plus the chaos
``worker.stall`` site that manufactures such replicas on demand.
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.api import DeploymentHandle, ReplicaCircuit


# ------------------------------------------------- circuit breaker units


def _circuit(**kw):
    now = [0.0]
    kw.setdefault("fail_threshold", 3.0)
    kw.setdefault("decay_s", 5.0)
    kw.setdefault("cooldown_s", 1.0)
    return ReplicaCircuit(clock=lambda: now[0], **kw), now


def test_circuit_opens_after_threshold_and_probes_halfopen():
    c, now = _circuit()
    assert c.routable()
    assert not c.record_failure()
    assert not c.record_failure()
    assert c.state == "closed" and c.routable()
    assert c.record_failure() is True  # the opening transition
    assert c.state == "open" and not c.routable()
    now[0] = 0.5
    assert not c.routable()  # still cooling down
    now[0] = 1.5
    assert c.routable() and c.state == "half_open"
    c.note_picked()  # THE probe
    assert not c.routable()  # only one probe in flight
    c.record_success()
    assert c.state == "closed" and c.routable() and c.score == 0.0


def test_circuit_probe_failure_reopens():
    c, now = _circuit()
    for _ in range(3):
        c.record_failure()
    now[0] = 2.0
    assert c.routable()
    c.note_picked()
    c.record_failure()  # probe failed
    assert c.state == "open" and not c.routable()
    now[0] = 2.5
    assert not c.routable()  # fresh cooldown from the re-open
    now[0] = 3.5
    assert c.routable()


def test_circuit_score_decays():
    c, now = _circuit()
    c.record_failure()
    c.record_failure()
    now[0] = 20.0  # 4 half-lives: the old burst is worth ~0.125
    assert not c.record_failure()  # 1.125 < 3: stays closed
    assert c.state == "closed"


def test_allow_is_routable_plus_picked():
    c, now = _circuit(fail_threshold=1.0)
    c.record_failure()
    now[0] = 1.5
    assert c.allow() is True   # half-open probe consumed
    assert c.allow() is False  # second caller refused


# ------------------------------------------------------ hedge-delay unit


def test_hedge_delay_policy(monkeypatch):
    h = DeploymentHandle.__new__(DeploymentHandle)
    import threading
    from collections import deque

    h._lock = threading.Lock()
    h._latencies = deque(maxlen=200)
    h._lat_version = 0
    h._p99_cache = None
    # no policy / not idempotent: hedging off
    h._policy = {}
    assert h._hedge_delay() is None
    h._policy = {"hedge_after_s": 0.2}
    assert h._hedge_delay() is None, "hedging requires idempotent=True"
    h._policy = {"hedge_after_s": 0.2, "idempotent": True}
    assert h._hedge_delay() == 0.2
    # "p99": configured floor until enough samples, then the observed p99
    h._policy = {"hedge_after_s": "p99", "idempotent": True}
    from ray_tpu._private.config import config

    assert h._hedge_delay() == float(config.serve_hedge_min_delay_s)
    h._latencies.extend([0.01] * 99 + [0.5])
    h._lat_version = 100
    assert h._hedge_delay() == 0.5
    # cached between samples: a heavier tail only shows up after the
    # refresh window's worth of appends invalidates the cache
    h._latencies.extend([0.9] * 5)
    h._lat_version += 1
    assert h._hedge_delay() == 0.5
    h._lat_version += 20
    assert h._hedge_delay() == 0.9


# ------------------------------------------------------------------- e2e


def _flaky_cls():
    """Deployment target whose per-replica delay is settable directly
    on the replica actor — the deterministic gray-replica knob.
    Built in local scope so cloudpickle ships it by value (a module-
    level test class would need this test module importable on the
    replica workers)."""

    class Flaky:
        def __init__(self):
            self.delay = 0.0

        def __call__(self, x):
            if self.delay:
                time.sleep(self.delay)
            return {"ok": 1}

        def set_delay(self, d):
            self.delay = float(d)
            return True

    return Flaky


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def _replica_actors(prefix):
    w = ray_tpu.api._worker()
    return [a for a in w.head.call("list_actors", timeout=30)["actors"]
            if a.get("name", "").startswith(f"serve:{prefix}")
            and a.get("state") == "ALIVE"]


def test_hedging_and_circuit_evict_gray_replica(cluster, monkeypatch):
    """One of two replicas goes gray (1.2s service time vs ~0).  A
    request routed to it hedges a duplicate to the healthy replica
    after hedge_after_s and the hedge WINS — p-high latency stays at
    the hedge delay, zero failures — and the hedge-slow event opens the
    gray replica's circuit so it leaves routing immediately."""
    monkeypatch.setenv("RT_SERVE_CIRCUIT_FAIL_THRESHOLD", "1")
    handle = serve.run(serve.deployment(
        _flaky_cls(), name="hedged", num_replicas=2,
        request_timeout_s=10.0, hedge_after_s=0.15,
        idempotent=True).bind())
    assert handle._policy["idempotent"] is True
    # gray one replica
    replicas = _replica_actors("hedged")
    assert len(replicas) == 2
    slow_name = replicas[0]["name"]
    slow_rid = replicas[0]["actor_id"]
    fast_rid = replicas[1]["actor_id"]
    slow = ray_tpu.get_actor(slow_name)
    assert ray_tpu.get(
        slow.handle_request.remote("set_delay", (1.2,), {}), timeout=30)

    from ray_tpu._private.metrics import serve_tail_metrics

    hedges, circuit_opens = serve_tail_metrics()
    won_before = sum(v for k, v in hedges._values.items()
                     if ("outcome", "won") in k)
    opens_before = sum(circuit_opens._values.values())

    # force the first pick onto the gray replica (deterministically):
    # pile phantom inflight on the healthy one
    with handle._lock:
        handle._inflight[fast_rid] = 50
    t0 = time.monotonic()
    out = asyncio.run(handle.call_async({"x": 1}))
    dt = time.monotonic() - t0
    with handle._lock:
        handle._inflight[fast_rid] = 0
    assert out == {"ok": 1}
    assert dt < 1.0, f"hedge did not absorb the gray replica ({dt:.2f}s)"
    won_after = sum(v for k, v in hedges._values.items()
                    if ("outcome", "won") in k)
    assert won_after > won_before, "hedge never fired/won"
    # the hedge-slow event opened the gray replica's breaker
    assert sum(circuit_opens._values.values()) > opens_before
    c = handle._circuits.get(slow_rid)
    assert c is not None and c.state in ("open", "half_open")

    # with the circuit open the gray replica is out of routing: every
    # subsequent request is fast WITHOUT needing a hedge
    for _ in range(3):
        t0 = time.monotonic()
        assert asyncio.run(handle.call_async({"x": 2})) == {"ok": 1}
        assert time.monotonic() - t0 < 1.0
    serve.delete("hedged")


def test_request_timeout_policy_bounds_unary_call(cluster):
    """A deployment-level request_timeout_s bounds call_async: a wedged
    replica surfaces the typed DeadlineExceededError at the budget, not
    at the transport's 120s default."""
    handle = serve.run(serve.deployment(
        _flaky_cls(), name="bounded", num_replicas=1,
        request_timeout_s=0.5).bind())
    slow = ray_tpu.get_actor(_replica_actors("bounded")[0]["name"])
    assert ray_tpu.get(
        slow.handle_request.remote("set_delay", (10.0,), {}), timeout=30)
    t0 = time.monotonic()
    with pytest.raises(ray_tpu.DeadlineExceededError):
        asyncio.run(handle.call_async({"x": 1}))
    assert time.monotonic() - t0 < 3.0
    serve.delete("bounded")


def test_worker_stall_chaos_site(cluster):
    """``worker.stall``: the target worker busy-hangs (gray) but never
    dies — calls issued during the stall window complete late, the
    process survives, and no restart happens.  Also proves head→agent
    rule gossip end-to-end (the agent executes the gossiped rule)."""
    from ray_tpu._private import fault_injection as fi

    @ray_tpu.remote
    class Probe:
        def wid(self):
            from ray_tpu._private.worker import global_worker_or_none

            return global_worker_or_none().worker_id

        def ping(self):
            return "pong"

    a = Probe.remote()
    wid = ray_tpu.get(a.wid.remote(), timeout=60)
    w = ray_tpu.api._worker()
    w.head.call("chaos", op="inject",
                rule={"site": "worker.stall", "action": "stall",
                      "target": wid, "count": 1, "delay_s": 3.0},
                timeout=30)
    try:
        # the rule reaches the agent by push (ms) or heartbeat catch-up
        # (seconds, on a loaded box): keep pinging until one ping lands
        # inside the stall window and visibly hangs
        stalled = False
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
            if time.monotonic() - t0 > 0.3:
                stalled = True
                break
            time.sleep(0.05)
        assert stalled, "worker never stalled (rule not applied?)"
        # gray, not dead: same worker id (no restart), fast pings again
        assert ray_tpu.get(a.wid.remote(), timeout=60) == wid
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            t0 = time.monotonic()
            assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
            if time.monotonic() - t0 < 0.2:
                return  # recovered
        raise AssertionError("worker never recovered from the stall")
    finally:
        w.head.call("chaos", op="clear", timeout=30)
        fi.clear()
