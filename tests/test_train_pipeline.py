"""MPMD pipeline-parallel training tests (ISSUE 10).

Tier-1 core: a 2-stage CPU pipeline through REAL channels + pinned
actor loops matches the single-program loss trajectory within
tolerance; 1F1B schedule properties; partition balance; bubble
accounting; poison-on-stage-death.  The chaos-restart resume ride is
multi-second and runs under the ``slow`` marker.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.train.pipeline import (PipelineError, TrainPipeline,
                                    bubble_pct, in_flight_bound,
                                    one_f_one_b, partition_layers,
                                    slice_params_for_stage)


# ---------------------------------------------------------- schedule units


def test_one_f_one_b_ordering_and_counts():
    for n_stages in (2, 3, 4):
        for m in (1, 2, 4, 8):
            for stage in range(n_stages):
                ops = one_f_one_b(stage, n_stages, m)
                fs = [k for op, k in ops if op == "F"]
                bs = [k for op, k in ops if op == "B"]
                # every microbatch goes forward once and backward once,
                # each stream in order
                assert fs == list(range(m))
                assert bs == list(range(m))
                # B(k) strictly after F(k)
                pos = {("F", k): i for i, (op, k) in enumerate(ops)
                       if op == "F"}
                for i, (op, k) in enumerate(ops):
                    if op == "B":
                        assert i > pos[("F", k)]
                # the last stage alternates strictly (zero warm-up)
                if stage == n_stages - 1:
                    assert ops[:2 * m:2] == [("F", k) for k in range(m)]


def test_one_f_one_b_in_flight_bound():
    """The schedule's in-flight microbatch count is what sizes the
    activation channel rings: min(n_stages - stage, m)."""
    for n_stages in (2, 3, 4):
        for m in (1, 2, 4, 16):
            for stage in range(n_stages):
                lead = peak = 0
                for op, _k in one_f_one_b(stage, n_stages, m):
                    lead += 1 if op == "F" else -1
                    peak = max(peak, lead)
                assert peak == in_flight_bound(stage, n_stages, m)
                assert peak <= n_stages  # default act ring depth covers it


def test_bubble_accounting():
    assert bubble_pct([1.0, 1.0], 1.0) == 0.0
    assert bubble_pct([0.5, 0.5], 1.0) == 50.0
    # busy can never drive the bubble negative (clock jitter)
    assert bubble_pct([1.2, 1.1], 1.0) == 0.0
    assert bubble_pct([], 1.0) == 0.0


def test_partition_layers_balance():
    cfg = LlamaConfig.llama3_8b()
    ranges = partition_layers(cfg, 4)
    assert ranges[0][0] == 0 and ranges[-1][1] == cfg.n_layers
    for (a, b), (c, _d) in zip(ranges, ranges[1:]):
        assert b == c and b > a  # contiguous, non-empty
    # the embedding-weighted first stage and lm_head-weighted last stage
    # get fewer blocks than the pure-transformer middles
    counts = [b - a for a, b in ranges]
    assert counts[-1] < max(counts[1:-1])
    with pytest.raises(ValueError):
        partition_layers(LlamaConfig.tiny(), 3)  # 2 layers, 3 stages


def test_slice_params_for_stage_covers_tree():
    full = {"embed": 1, "layer_0": 2, "layer_1": 3, "final_norm": 4,
            "lm_head": 5}
    ranges = [(0, 1), (1, 2)]
    s0 = slice_params_for_stage(full, ranges, 0)
    s1 = slice_params_for_stage(full, ranges, 1)
    assert set(s0) == {"embed", "layer_0"}
    assert set(s1) == {"layer_1", "final_norm", "lm_head"}


# ------------------------------------------------------- channel overrides


def test_per_channel_ring_overrides(cluster):
    """with_channel_options sizes ONE edge's ring without touching the
    compile-wide defaults (deep activation edges vs shallow grad edges)."""
    from ray_tpu.dag.nodes import InputNode

    @ray_tpu.remote
    class Echo:
        def step(self, x):
            return x

    with InputNode() as inp:
        inp.with_channel_options(max_in_flight=3)
        mid = Echo.bind().step.bind(inp)
        mid.with_channel_options(max_in_flight=16,
                                 buffer_size_bytes=4096)
        out = Echo.bind().step.bind(mid)
    g = out.experimental_compile(use_channels=True, max_in_flight=4)
    try:
        assert g._input_spec.max_in_flight == 3
        mid_spec = g._out_specs[id(mid)]
        out_spec = g._out_specs[id(out)]
        assert mid_spec.max_in_flight == 16
        assert mid_spec.slot_size == 4096
        assert out_spec.max_in_flight == 4  # inherits the compile-wide
        assert g.execute(7).get(timeout=30) == 7
    finally:
        g.teardown()
    with pytest.raises(ValueError):
        mid.with_channel_options(max_in_flight=0)


# ------------------------------------------------------------ e2e pipeline


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def _token_batch(cfg, batch, seq, seed=1):
    rng = np.random.default_rng(seed)
    return rng.integers(0, cfg.vocab_size, size=(batch, seq),
                        dtype=np.int32)


@pytest.fixture(scope="module")
def pipeline(cluster):
    """One shared 2-stage CPU pipeline: building it (actor spawn + per-
    stage jit) dominates module wall time, so the trajectory test and
    the death test (which consumes the pipeline LAST — it poisons it for
    good) ride the same instance.  Tier-1 runs this module in definition
    order, which the death test relies on."""
    cfg = LlamaConfig.tiny()
    B, S, m = 4, 32, 2
    pipe = TrainPipeline(cfg, pp=2, microbatch_size=B // m,
                         num_microbatches=m, seq_len=S, rng_seed=0,
                         devices_per_stage=1, step_timeout=60.0)
    try:
        yield pipe
    finally:
        pipe.teardown()


def test_pipeline_matches_single_program_loss(pipeline):
    """Numerical-correctness gate: a 2-stage pp pipeline over real
    channels tracks the single-program loss trajectory over 5 steps."""
    from tests.conftest import force_cpu_jax

    jax = force_cpu_jax()
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh
    from ray_tpu.train.gspmd import build_llama_train_state

    cfg = pipeline.cfg
    B, S = pipeline.global_batch_size, pipeline.seq_len
    tokens = _token_batch(cfg, B, S)

    mesh = make_mesh(MeshSpec(), devices=jax.devices()[:1])
    params, opt, step_fn, _ = build_llama_train_state(
        cfg, mesh, batch_size=B, seq_len=S, rng_seed=0)
    sp_losses = []
    p, o = params, opt
    for _ in range(5):
        p, o, loss = step_fn(p, o, tokens)
        sp_losses.append(float(loss))

    pp_losses = []
    reports = []
    for _ in range(5):
        out = pipeline.step(tokens)
        pp_losses.append(out["loss"])
        reports.append(out)
    assert np.allclose(sp_losses, pp_losses, rtol=2e-2, atol=1e-3), (
        sp_losses, pp_losses)
    assert pp_losses[-1] < pp_losses[0]  # it actually trains
    # honest per-stage accounting came back with every step
    last = reports[-1]
    assert last["step"] == 5
    assert 0.0 <= last["bubble_pct"] <= 100.0
    assert len(last["per_stage"]) == 2
    for rep in last["per_stage"]:
        assert rep["busy_s"] > 0
    assert last["tokens_per_s"] > 0


def test_pipeline_poisoned_on_stage_death(pipeline):
    """A chaos-killed stage worker fails in-flight and future step()
    calls within the monitor interval instead of hanging the pipeline
    (driver monitor sees the loop-task death and poisons every ring).
    Runs LAST in the module: it destroys the shared pipeline."""
    import os
    import signal

    cfg = pipeline.cfg
    tokens = _token_batch(cfg, pipeline.global_batch_size,
                          pipeline.seq_len)
    assert pipeline.step(tokens)["loss"] is not None
    info = pipeline._ctl(pipeline._handles[1], {"op": "info"})
    os.kill(info["pid"], signal.SIGKILL)
    deadline = time.monotonic() + 30
    with pytest.raises(Exception) as exc_info:
        while time.monotonic() < deadline:
            pipeline.step(tokens)
    assert not isinstance(exc_info.value, AssertionError)
    # and it STAYS failed (fail-fast, not wedged)
    with pytest.raises(Exception):
        pipeline.step(tokens)
    # without checkpointing there is nothing to resume from
    with pytest.raises(PipelineError):
        pipeline.resume(timeout=5.0)


@pytest.mark.slow
def test_pipeline_stage_restart_resume(cluster):
    """Chaos ride: SIGKILL one stage's worker mid-run; the actor
    restarts with __rt_restore__ state, resume() rolls every stage to
    the newest common snapshot step, and training continues with the
    step counter intact."""
    import os
    import signal

    cfg = LlamaConfig.tiny()
    B, S, m = 4, 32, 2
    pipe = TrainPipeline(cfg, pp=2, microbatch_size=B // m,
                         num_microbatches=m, seq_len=S,
                         devices_per_stage=1, max_restarts=2,
                         step_timeout=60.0)
    try:
        tokens = _token_batch(cfg, B, S)
        for _ in range(3):
            out = pipe.step(tokens)
        assert out["step"] == 3
        info = pipe._ctl(pipe._handles[1], {"op": "info"})
        os.kill(info["pid"], signal.SIGKILL)
        deadline = time.monotonic() + 30
        with pytest.raises(Exception):
            while time.monotonic() < deadline:
                pipe.step(tokens)
        resumed = pipe.resume(timeout=180.0)
        assert resumed == 3
        out = pipe.step(tokens)
        assert out["step"] == 4
        assert np.isfinite(out["loss"])
    finally:
        pipe.teardown()
