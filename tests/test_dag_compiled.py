"""Compiled-graph subsystem tests: mutable channels (ring semantics,
backpressure, fan-out, remote push + compat fallback) and channel-
compiled DAG execution with pinned actor loops
(reference: python/ray/dag/tests/experimental/test_accelerated_dag.py)."""

import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag import channel as chmod
from ray_tpu._private.object_store import StoreCore


# ----------------------------------------------------------- channel units


@pytest.fixture
def store(tmp_path):
    s = StoreCore(str(tmp_path / "arena"), 8 * 1024 * 1024,
                  str(tmp_path / "spill"))
    yield s
    s.close(unlink=True)


def _local_channel(store, oid="ch-test", mif=2, n_readers=1,
                   slot=64 * 1024):
    spec = chmod.ChannelSpec(oid=oid, max_in_flight=mif, slot_size=slot,
                             n_readers=n_readers, writer_node="n0",
                             reader_nodes=["n0"] * n_readers, nodes={})
    loc = store.create_channel(oid, spec.total_size())
    view = store.arena.view[loc["offset"]:loc["offset"] + spec.total_size()]
    chmod.init_view(view, spec.header_wire())
    return spec, view


def test_channel_ring_wraparound(store):
    """Versions stay intact across several wraps of a 2-deep ring."""
    spec, view = _local_channel(store, mif=2)
    w = chmod.ChannelWriter(spec, view=view)
    r = chmod.ChannelReader(spec, 0, view=view)
    for seq in range(1, 8):
        w.write({"seq": seq, "data": b"x" * seq})
        value, is_err = r.read(seq, timeout=5)
        assert not is_err and value == {"seq": seq, "data": b"x" * seq}
        r.advance(seq)


def test_channel_backpressure_blocks_writer(store):
    """A slow reader BLOCKS the writer at max_in_flight versions —
    versions are never dropped."""
    spec, view = _local_channel(store, mif=2)
    w = chmod.ChannelWriter(spec, view=view)
    r = chmod.ChannelReader(spec, 0, view=view)
    w.write(1)
    w.write(2)
    with pytest.raises(chmod.ChannelTimeoutError):
        w.write(3, timeout=0.2)
    value, _ = r.read(1, timeout=5)
    assert value == 1
    r.advance(1)
    w.write(3, timeout=5)  # slot freed: write proceeds
    assert r.read(2, timeout=5)[0] == 2
    r.advance(2)
    assert r.read(3, timeout=5)[0] == 3


def test_channel_multi_reader_fanout(store):
    """Every reader sees every version; the writer only advances once
    ALL readers have consumed the slot it needs."""
    spec, view = _local_channel(store, mif=2, n_readers=2)
    w = chmod.ChannelWriter(spec, view=view)
    r0 = chmod.ChannelReader(spec, 0, view=view)
    r1 = chmod.ChannelReader(spec, 1, view=view)
    w.write("a")
    w.write("b")
    for seq, expect in ((1, "a"), (2, "b")):
        assert r0.read(seq, timeout=5)[0] == expect
        r0.advance(seq)
    # r1 has consumed nothing: the ring is still full for the writer
    with pytest.raises(chmod.ChannelTimeoutError):
        w.write("c", timeout=0.2)
    assert r1.read(1, timeout=5)[0] == "a"
    r1.advance(1)
    w.write("c", timeout=5)
    assert r1.read(2, timeout=5)[0] == "b"
    r1.advance(2)
    assert r1.read(3, timeout=5)[0] == "c"
    assert r0.read(3, timeout=5)[0] == "c"


def test_channel_error_version_and_poison(store):
    spec, view = _local_channel(store)
    w = chmod.ChannelWriter(spec, view=view)
    r = chmod.ChannelReader(spec, 0, view=view)
    w.write(ValueError("boom"), error=True)
    value, is_err = r.read(1, timeout=5)
    assert is_err and isinstance(value, ValueError)
    r.advance(1)
    chmod.poison_view(view, chmod.pickle_error(
        ray_tpu.ActorDiedError("actor gone")))
    with pytest.raises(ray_tpu.ActorDiedError):
        r.read(2, timeout=5)
    with pytest.raises(ray_tpu.ActorDiedError):
        w.write("after poison")


def test_channel_close_wakes_reader(store):
    spec, view = _local_channel(store)
    w = chmod.ChannelWriter(spec, view=view)
    r = chmod.ChannelReader(spec, 0, view=view)
    w.write(1)
    assert r.read(1, timeout=5)[0] == 1
    r.advance(1)
    w.close()
    with pytest.raises(chmod.ChannelClosedError):
        r.read(2, timeout=5)


from ray_tpu._private.rpc import RpcHost


class _MiniAgent(RpcHost):
    """Just enough of a node agent for the compat channel RPC path."""

    def __init__(self, store):
        self.store = store

    def _entry(self, oid):
        e = self.store.objects.get(oid)
        return e if e is not None and e.channel else None

    async def rpc_channel_write(self, oid, offset, data):
        e = self._entry(oid)
        if e is None or offset < 0 or offset + len(data) > e.size:
            return {"ok": False, "error": "bad channel write"}
        base = e.offset
        self.store.arena.view[base + offset:base + offset + len(data)] = data
        return {"ok": True}

    async def rpc_channel_read(self, oid, offset, length):
        e = self._entry(oid)
        if e is None:
            return {"ok": False, "error": "no channel"}
        base = e.offset
        return {"ok": True, "data": bytes(
            self.store.arena.view[base + offset:base + offset + length])}


def _remote_pair(tmp_path, xfer_port_of):
    """Writer store + reader store with a transfer server and a compat
    RPC agent on the reader side; returns (spec, wview, rview, cleanup)."""
    import asyncio

    from ray_tpu._private.object_transfer import ObjectTransferServer
    from ray_tpu._private.rpc import EventLoopThread, RpcServer

    store_w = StoreCore(str(tmp_path / "arena-w"), 8 << 20,
                        str(tmp_path / "spill-w"))
    store_r = StoreCore(str(tmp_path / "arena-r"), 8 << 20,
                        str(tmp_path / "spill-r"))
    xfer = ObjectTransferServer(store_r)
    io = EventLoopThread(name="rt-test-agent")
    xfer_port = io.run(xfer.start())
    server = RpcServer(_MiniAgent(store_r), "127.0.0.1", 0)
    rpc_port = io.run(server.start())

    spec = chmod.ChannelSpec(
        oid="ch-remote", max_in_flight=2, slot_size=64 * 1024, n_readers=1,
        writer_node="nw", reader_nodes=["nr"],
        nodes={"nw": {"agent": ["127.0.0.1", 1], "xfer_port": 0},
               "nr": {"agent": ["127.0.0.1", rpc_port],
                      "xfer_port": xfer_port_of(xfer_port)}})
    for st in (store_w, store_r):
        loc = st.create_channel(spec.oid, spec.total_size())
        view = st.arena.view[loc["offset"]:loc["offset"] + spec.total_size()]
        chmod.init_view(view, spec.header_wire())
    wview = store_w.arena.view[
        store_w.objects[spec.oid].offset:][:spec.total_size()]
    rview = store_r.arena.view[
        store_r.objects[spec.oid].offset:][:spec.total_size()]

    def cleanup():
        io.run(xfer.stop())
        io.run(server.stop())
        io.stop()
        store_w.close(unlink=True)
        store_r.close(unlink=True)

    return spec, wview, rview, cleanup


@pytest.mark.parametrize("plane", ["bulk", "rpc_fallback"])
def test_channel_remote_push(tmp_path, plane):
    """Remote-reader delivery: versions are PUSHED into the reader
    node's mirror over the bulk plane; with the bulk listener
    unreachable the writer falls back to the compat RPC path, and
    backpressure still flows back through the mirror's cursors."""
    spec, wview, rview, cleanup = _remote_pair(
        tmp_path,
        (lambda p: p) if plane == "bulk" else (lambda p: 1))  # port 1: dead
    try:
        w = chmod.ChannelWriter(spec, view=wview)
        r = chmod.ChannelReader(spec, 0, view=rview)
        for seq in range(1, 6):
            w.write({"v": seq}, timeout=10)
            assert r.read(seq, timeout=10)[0] == {"v": seq}
            r.advance(seq)
        if plane == "rpc_fallback":
            assert not w._targets[0].bulk_ok
        else:
            assert w._targets[0].bulk_ok
        # slow remote reader: ring full blocks the writer
        w.write("x", timeout=10)
        w.write("y", timeout=10)
        with pytest.raises(chmod.ChannelTimeoutError):
            w.write("z", timeout=0.3)
        assert r.read(6, timeout=10)[0] == "x"
        r.advance(6)
        w.write("z", timeout=10)
        assert r.read(7, timeout=10)[0] == "y"
        r.advance(7)
        assert r.read(8, timeout=10)[0] == "z"
        r.advance(8)
        w.detach()
    finally:
        cleanup()


# ------------------------------------------------------ compiled graph e2e


def test_compiled_graph_chain(local_cluster):
    @ray_tpu.remote
    class Stage:
        def __init__(self):
            self.calls = 0

        def step(self, x):
            self.calls += 1
            return x + self.calls

    with InputNode() as inp:
        dag = Stage.bind().step.bind(Stage.bind().step.bind(inp))
    g = dag.experimental_compile(use_channels=True, max_in_flight=4)
    try:
        # state persists across executes: calls accumulate per stage
        assert g.execute(10).get(timeout=60) == 12   # 10+1 then +1
        assert g.execute(10).get(timeout=60) == 14   # 10+2 then +2
        refs = [g.execute(0) for _ in range(3)]
        assert [r.get(timeout=60) for r in refs] == [6, 8, 10]
    finally:
        g.teardown()


def test_compiled_graph_multi_output_and_fanout(local_cluster):
    @ray_tpu.remote
    class A:
        def tag(self, x):
            return ("a", x)

    @ray_tpu.remote
    class B:
        def tag(self, pair):
            return ("b",) + pair

    with InputNode() as inp:
        shared = A.bind().tag.bind(inp)
        dag = MultiOutputNode([B.bind().tag.bind(shared),
                               B.bind().tag.bind(shared)])
    g = dag.experimental_compile(use_channels=True)
    try:
        out = g.execute(7).get(timeout=60)
        assert out == [("b", "a", 7), ("b", "a", 7)]
    finally:
        g.teardown()


def test_compiled_graph_error_propagates(local_cluster):
    @ray_tpu.remote
    class S:
        def step(self, x):
            if x < 0:
                raise ValueError("negative input")
            return x * 2

    with InputNode() as inp:
        dag = S.bind().step.bind(S.bind().step.bind(inp))
    g = dag.experimental_compile(use_channels=True)
    try:
        assert g.execute(3).get(timeout=60) == 12
        with pytest.raises(ValueError, match="negative input"):
            g.execute(-1).get(timeout=60)
        # the pipeline survives a value-level error
        assert g.execute(5).get(timeout=60) == 20
    finally:
        g.teardown()


def test_compiled_graph_value_level_write_failures_survive(local_cluster):
    """An oversized or unserializable RESULT degrades to a per-execution
    error (re-raised by that ref's get, re-raisable on a retried get)
    without killing the actor loop or poisoning the pipeline."""
    @ray_tpu.remote
    class S:
        def step(self, x):
            if x == "big":
                return b"x" * (256 * 1024)  # exceeds the 64KB slot below
            return x

    @ray_tpu.remote
    class T:
        def step(self, x):
            return x

    with InputNode() as inp:
        dag = T.bind().step.bind(S.bind().step.bind(inp))
    g = dag.experimental_compile(use_channels=True,
                                 buffer_size_bytes=64 * 1024)
    try:
        assert g.execute("ok").get(timeout=60) == "ok"
        ref = g.execute("big")
        with pytest.raises(ray_tpu.RayError, match="exceeds the channel"):
            ref.get(timeout=60)
        # a retried get re-raises the ORIGINAL error, not an
        # eviction/bookkeeping artifact
        with pytest.raises(ray_tpu.RayError, match="exceeds the channel"):
            ref.get(timeout=60)
        # the pipeline survives the value-level failure
        assert g.execute("after").get(timeout=60) == "after"
    finally:
        g.teardown()


def test_compiled_graph_teardown_idempotent_and_rejects_execute(
        local_cluster):
    @ray_tpu.remote
    class S:
        def step(self, x):
            return x

    with InputNode() as inp:
        dag = S.bind().step.bind(inp)
    g = dag.experimental_compile(use_channels=True)
    # while the graph is live, its pinned slots are visible in the
    # store breakdown AND claimed by this driver (not leak candidates)
    from ray_tpu import api as _api

    agent = _api._worker().agent
    live = agent.call("node_memory", include_workers=False)["breakdown"]
    assert live["channel_slots"] > 0 and live["channel_bytes"] > 0
    assert g.execute(1).get(timeout=60) == 1
    g.teardown()
    # leak tripwire self-test (ISSUE 9 satellite): teardown must free
    # every pinned channel slot — the accounting API is the assert
    after = agent.call("node_memory", include_workers=False)["breakdown"]
    assert after["channel_slots"] == 0, after
    assert after["channel_bytes"] == 0, after
    from ray_tpu.dag import execution as _exec

    assert _exec.live_channel_oids() == []
    g.teardown()  # idempotent
    with pytest.raises(ray_tpu.RayError):
        g.execute(2)


def test_compiled_graph_actor_death_fails_inflight_gets(local_cluster):
    """An actor killed mid-pipeline must fail in-flight get()s within
    the monitor interval instead of hanging them."""
    @ray_tpu.remote(max_restarts=0)
    class Flaky:
        def step(self, x):
            if x == "die":
                os._exit(1)
            return x

    @ray_tpu.remote
    class Tail:
        def step(self, x):
            return x

    with InputNode() as inp:
        dag = Tail.bind().step.bind(Flaky.bind().step.bind(inp))
    g = dag.experimental_compile(use_channels=True, max_in_flight=4)
    try:
        assert g.execute("ok").get(timeout=60) == "ok"
        ref = g.execute("die")
        with pytest.raises(ray_tpu.RayError):
            ref.get(timeout=30)
        with pytest.raises(ray_tpu.RayError):
            g.execute("after")  # pipeline is poisoned
    finally:
        g.teardown()


# ----------------------------------------------- dynamic-path satellites


def test_dynamic_compiled_backpressure_surfaces_actor_death(local_cluster):
    """dag/compiled.py::_apply_backpressure used to silently re-block up
    to 300s per round when a DAG actor died mid-pipeline; it must now
    surface ActorDiedError from the oldest in-flight group."""
    @ray_tpu.remote(max_restarts=0)
    class S:
        def step(self, x):
            if x >= 2:
                os._exit(1)
            time.sleep(0.05)
            return x

    with InputNode() as inp:
        dag = S.bind().step.bind(inp)
    c = dag.experimental_compile(max_in_flight=2)
    t0 = time.monotonic()
    with pytest.raises(ray_tpu.ActorDiedError):
        for i in range(10):
            c.execute(i)
    assert time.monotonic() - t0 < 60  # not a 300s wait round
    c.teardown()


def test_dynamic_compiled_teardown_waits_and_is_idempotent(local_cluster):
    @ray_tpu.remote
    class S:
        def step(self, x):
            return x

    with InputNode() as inp:
        dag = S.bind().step.bind(inp)
    c = dag.experimental_compile()
    assert ray_tpu.get(c.execute(1), timeout=60) == 1
    c.teardown()
    c.teardown()  # double-teardown: no-op
    with pytest.raises(ray_tpu.RayError):
        c.execute(2)


def test_dynamic_compiled_teardown_after_actor_crash(local_cluster):
    @ray_tpu.remote(max_restarts=0)
    class S:
        def boom(self):
            os._exit(1)

    dag = S.bind().boom.bind()
    c = dag.experimental_compile()
    with pytest.raises(ray_tpu.RayError):
        ray_tpu.get(c.execute(), timeout=60)
    c.teardown()  # actors already dead: still synchronous, no raise
    c.teardown()


def test_dynamic_diamond_shared_stage_runs_once(local_cluster):
    """Regression (MultiOutputNode memo): a diamond DAG's shared
    upstream stage must execute exactly once per execute()."""
    @ray_tpu.remote
    class Counting:
        def __init__(self):
            self.calls = 0

        def produce(self, x):
            self.calls += 1
            return (x, self.calls)

        def count(self):
            return self.calls

    @ray_tpu.remote
    def branch(tagged, label):
        return (label,) + tagged

    node = Counting.options(name="diamond_shared").bind()
    with InputNode() as inp:
        shared = node.produce.bind(inp)
        dag = MultiOutputNode([branch.bind(shared, "l"),
                               branch.bind(shared, "r")])
    c = dag.experimental_compile()
    try:
        for i in range(1, 4):
            left, right = ray_tpu.get(c.execute(i), timeout=60)
            # both branches saw the SAME single execution of the stage
            assert left == ("l", i, i) and right == ("r", i, i)
        counter = ray_tpu.get_actor("diamond_shared")
        assert ray_tpu.get(counter.count.remote(), timeout=60) == 3
    finally:
        c.teardown()
