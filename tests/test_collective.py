"""Host-collective API tests (ray_tpu.util.collective).

Mirrors the reference's collective tests
(reference: python/ray/util/collective/tests/) with actor gangs on one
machine; payloads are control-plane-sized numpy arrays.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def _make_members(n, group="g"):
    @ray_tpu.remote
    class Member:
        def setup(self, world, rank, group):
            from ray_tpu.util import collective

            collective.init_collective_group(world, rank, group)
            return rank

        def do_allreduce(self, group):
            from ray_tpu.util import collective

            rank = collective._group(group).rank
            return collective.allreduce(np.array([float(rank + 1)]), "sum",
                                        group)

        def do_allgather(self, group):
            from ray_tpu.util import collective

            rank = collective._group(group).rank
            return collective.allgather(np.array([rank]), group)

        def do_broadcast(self, group, value):
            from ray_tpu.util import collective

            rank = collective._group(group).rank
            data = np.array([value]) if rank == 0 else None
            return collective.broadcast(data, 0, group)

        def do_barrier(self, group):
            from ray_tpu.util import collective

            collective.barrier(group)
            return True

    members = [Member.remote() for _ in range(n)]
    ray_tpu.get([m.setup.remote(n, i, group) for i, m in enumerate(members)],
                timeout=60)
    return members


def test_allreduce(cluster):
    members = _make_members(3, "ar")
    out = ray_tpu.get([m.do_allreduce.remote("ar") for m in members], timeout=60)
    for o in out:
        assert float(np.asarray(o)[0]) == 6.0  # 1+2+3


def test_allgather(cluster):
    members = _make_members(3, "ag")
    out = ray_tpu.get([m.do_allgather.remote("ag") for m in members], timeout=60)
    for o in out:
        assert [int(np.asarray(p)[0]) for p in o] == [0, 1, 2]


def test_broadcast(cluster):
    members = _make_members(3, "bc")
    out = ray_tpu.get([m.do_broadcast.remote("bc", 42.0) for m in members],
                      timeout=60)
    for o in out:
        assert float(np.asarray(o)[0]) == 42.0


def test_barrier(cluster):
    members = _make_members(4, "bar")
    out = ray_tpu.get([m.do_barrier.remote("bar") for m in members], timeout=60)
    assert out == [True] * 4
