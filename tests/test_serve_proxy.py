"""Serve HTTP ingress tests: the async event-loop data plane
(pipelining, keep-alive-after-SSE, overload shedding, defensive
parsing) plus the per-node proxy test — in its own module because the
per-node test stands up its own multi-node cluster and must not tear
down test_serve.py's module-scoped runtime (reference: per-node proxy
actors + long-poll route table)."""

import json
import socket
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def proxy():
    """Single-node cluster + default async proxy with a few fixture
    deployments (echo, SSE generator, slow endpoint)."""
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)

    @serve.deployment(name="pecho")
    def pecho(x):
        return {"v": x}

    @serve.deployment(name="psse")
    def psse(x):
        for i in range(3):
            yield f"tok{i}"

    @serve.deployment(name="pslow", max_ongoing_requests=16)
    def pslow(x):
        import time as _t

        _t.sleep(0.4)
        return {"ok": 1}

    serve.run(pecho.bind())
    serve.run(psse.bind())
    serve.run(pslow.bind())
    host, port = serve.start_http()
    try:
        yield host, port
    finally:
        for fn in (serve.shutdown_http, serve.shutdown, ray_tpu.shutdown):
            try:
                fn()
            except Exception:
                pass


def _read_response(f):
    """Read one HTTP response off a socket file; returns
    (status_line, headers, body) with chunked bodies de-framed."""
    status = f.readline().decode("latin1")
    headers = {}
    while True:
        line = f.readline()
        if line in (b"\r\n", b"\n", b""):
            break
        k, _, v = line.decode("latin1").partition(":")
        headers[k.strip().lower()] = v.strip()
    body = b""
    if headers.get("transfer-encoding") == "chunked":
        while True:
            size = int(f.readline().strip() or b"0", 16)
            if size == 0:
                f.readline()
                break
            body += f.read(size)
            f.readline()
    else:
        clen = int(headers.get("content-length", 0) or 0)
        if clen:
            body = f.read(clen)
    return status, headers, body


def _connect(host, port):
    s = socket.create_connection((host, port), timeout=30)
    return s, s.makefile("rb")


def test_pipelined_keepalive_requests(proxy):
    """HTTP/1.1 pipelining: several requests written back-to-back on one
    connection get their responses in request order, connection open
    throughout."""
    host, port = proxy
    s, f = _connect(host, port)
    try:
        s.sendall(b"".join(
            f"GET /pecho?x={i} HTTP/1.1\r\nHost: t\r\n\r\n".encode()
            for i in range(3)))
        for i in range(3):
            status, headers, body = _read_response(f)
            assert " 200 " in status, status
            assert json.loads(body) == {"v": {"x": str(i)}}
        # connection still usable after the pipelined burst
        s.sendall(b"GET /pecho?x=9 HTTP/1.1\r\nHost: t\r\n\r\n")
        status, _, body = _read_response(f)
        assert json.loads(body) == {"v": {"x": "9"}}
    finally:
        s.close()


def test_keepalive_after_sse(proxy):
    """A chunked/SSE response leaves the connection alive (chunked
    framing is self-terminating) — a follow-up request on the SAME
    connection succeeds.  Also sends a traceparent header through the
    async stream path (contextvar propagation must not break it)."""
    host, port = proxy
    s, f = _connect(host, port)
    try:
        s.sendall(b"GET /psse HTTP/1.1\r\nHost: t\r\n"
                  b"Accept: text/event-stream\r\n"
                  b"traceparent: 00-" + b"ab" * 16 + b"-" + b"cd" * 8 +
                  b"-01\r\n\r\n")
        status, headers, body = _read_response(f)
        assert " 200 " in status, status
        assert headers.get("transfer-encoding") == "chunked"
        toks = [json.loads(l) for l in body.splitlines() if l.strip()]
        assert toks == ["tok0", "tok1", "tok2"]
        # the same connection serves a plain request afterwards
        s.sendall(b"GET /pecho?x=after HTTP/1.1\r\nHost: t\r\n\r\n")
        status, _, body = _read_response(f)
        assert " 200 " in status and json.loads(body) == {"v": {"x": "after"}}
    finally:
        s.close()


def test_http10_close_by_default(proxy):
    """HTTP/1.0 semantics: close unless the client explicitly opts into
    keep-alive."""
    host, port = proxy
    s, f = _connect(host, port)
    try:
        s.sendall(b"GET /pecho?x=1 HTTP/1.0\r\nHost: t\r\n\r\n")
        status, headers, _ = _read_response(f)
        assert " 200 " in status
        assert headers.get("connection") == "close"
        assert f.readline() == b""  # server closed the connection
    finally:
        s.close()
    s, f = _connect(host, port)
    try:
        s.sendall(b"GET /pecho?x=1 HTTP/1.0\r\nHost: t\r\n"
                  b"Connection: keep-alive\r\n\r\n")
        status, headers, _ = _read_response(f)
        assert headers.get("connection") == "keep-alive"
        s.sendall(b"GET /pecho?x=2 HTTP/1.0\r\nHost: t\r\n\r\n")
        status, _, body = _read_response(f)
        assert json.loads(body) == {"v": {"x": "2"}}
    finally:
        s.close()


def test_malformed_content_length_400(proxy):
    """`Content-Length: abc` gets a defensive 400, not a torn-down
    connection via the generic handler."""
    host, port = proxy
    s, f = _connect(host, port)
    try:
        s.sendall(b"POST /pecho HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: abc\r\n\r\n")
        status, _, body = _read_response(f)
        assert status.startswith("HTTP/1.1 400"), status
        assert b"content-length" in body
    finally:
        s.close()


def test_transfer_encoding_rejected(proxy):
    """A chunked request body we don't de-frame would desync pipelined
    request framing (smuggling vector) — refused with 501, connection
    closed."""
    host, port = proxy
    s, f = _connect(host, port)
    try:
        s.sendall(b"POST /pecho HTTP/1.1\r\nHost: t\r\n"
                  b"Transfer-Encoding: chunked\r\n\r\n"
                  b"5\r\nhello\r\n0\r\n\r\n")
        status, _, _ = _read_response(f)
        assert status.startswith("HTTP/1.1 501"), status
        assert f.readline() == b""  # framing untrusted: closed
    finally:
        s.close()


def test_header_and_body_limits(proxy):
    """Oversized headers shed with 431, oversized declared bodies with
    413 — one misbehaving client cannot make the proxy buffer unbounded
    memory."""
    host, port = proxy
    s, f = _connect(host, port)
    try:
        s.sendall(b"GET /pecho HTTP/1.1\r\nHost: t\r\n"
                  b"X-Big: " + b"a" * 70_000 + b"\r\n\r\n")
        status, _, _ = _read_response(f)
        assert status.startswith("HTTP/1.1 431"), status
    finally:
        s.close()
    s, f = _connect(host, port)
    try:
        s.sendall(b"POST /pecho HTTP/1.1\r\nHost: t\r\n"
                  b"Content-Length: 999999999\r\n\r\n")
        status, _, _ = _read_response(f)
        assert status.startswith("HTTP/1.1 413"), status
    finally:
        s.close()


def test_overload_shedding_503(proxy):
    """Beyond the in-flight cap the proxy sheds with 503 instead of
    queueing; capacity recovers once load drains."""
    host, _ = proxy
    serve.shutdown_http()
    host, port = serve.start_http(max_inflight=2)
    try:
        conns = [_connect(host, port) for _ in range(6)]
        for s, _ in conns:
            s.sendall(b"GET /pslow HTTP/1.1\r\nHost: t\r\n\r\n")
        statuses = []
        for s, f in conns:
            status, _, _ = _read_response(f)
            statuses.append(status.split(" ", 2)[1])
            s.close()
        assert "200" in statuses, statuses
        assert "503" in statuses, statuses
        # after the burst drains, requests succeed again
        s, f = _connect(host, port)
        s.sendall(b"GET /pslow HTTP/1.1\r\nHost: t\r\n\r\n")
        status, _, _ = _read_response(f)
        assert " 200 " in status, status
        s.close()
    finally:
        serve.shutdown_http()


def test_per_node_proxies():
    """Every node runs its own ingress; requests entering any node's
    proxy reach replicas anywhere (reference: per-node proxy actors +
    long-poll route table)."""
    import json
    import urllib.request

    from ray_tpu.cluster_utils import Cluster

    # needs its own 2-node cluster; the module-scoped fixture's runtime
    # may still be up from earlier tests (this test runs last)
    try:
        serve.shutdown()
    except Exception:
        pass
    try:
        ray_tpu.shutdown()
    except Exception:
        pass

    cluster = Cluster(head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    try:
        @serve.deployment(name="spread", num_replicas=2)
        def spread(x):
            return {"v": x}

        serve.run(spread.bind())
        addrs = serve.start_per_node_http()
        assert len(addrs) == 2, addrs
        for host, port in addrs:
            with urllib.request.urlopen(
                    f"http://{host}:{port}/spread?x=7", timeout=30) as r:
                assert json.loads(r.read()) == {"v": {"x": "7"}}
        serve.shutdown_http()
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        cluster.shutdown()
