"""Per-node Serve ingress test — in its own module because it stands up
its own multi-node cluster and must not tear down test_serve.py's
module-scoped runtime (reference: per-node proxy actors + long-poll
route table)."""

import time  # noqa: F401 — kept for parity with test_serve helpers

import ray_tpu
from ray_tpu import serve


def test_per_node_proxies():
    """Every node runs its own ingress; requests entering any node's
    proxy reach replicas anywhere (reference: per-node proxy actors +
    long-poll route table)."""
    import json
    import urllib.request

    from ray_tpu.cluster_utils import Cluster

    # needs its own 2-node cluster; the module-scoped fixture's runtime
    # may still be up from earlier tests (this test runs last)
    try:
        serve.shutdown()
    except Exception:
        pass
    try:
        ray_tpu.shutdown()
    except Exception:
        pass

    cluster = Cluster(head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2)
    ray_tpu.init(address=cluster.address)
    try:
        @serve.deployment(name="spread", num_replicas=2)
        def spread(x):
            return {"v": x}

        serve.run(spread.bind())
        addrs = serve.start_per_node_http()
        assert len(addrs) == 2, addrs
        for host, port in addrs:
            with urllib.request.urlopen(
                    f"http://{host}:{port}/spread?x=7", timeout=30) as r:
                assert json.loads(r.read()) == {"v": {"x": "7"}}
        serve.shutdown_http()
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()
        cluster.shutdown()
