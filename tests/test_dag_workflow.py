"""DAG API, compiled DAG, and durable workflow tests
(reference: python/ray/dag/tests/, python/ray/workflow/tests/)."""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode, MultiOutputNode


# ------------------------------------------------------------------ dag


def test_dag_dynamic_execute(local_cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def mul(a, b):
        return a * b

    dag = mul.bind(add.bind(1, 2), add.bind(3, 4))  # (1+2) * (3+4)
    assert ray_tpu.get(dag.execute(), timeout=60) == 21


def test_dag_input_node(local_cluster):
    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        dag = add.bind(double.bind(inp), inp)
    assert ray_tpu.get(dag.execute(10), timeout=60) == 30


def test_dag_input_projection(local_cluster):
    @ray_tpu.remote
    def combine(a, b):
        return a - b

    with InputNode() as inp:
        dag = combine.bind(inp["hi"], inp["lo"])
    assert ray_tpu.get(dag.execute({"hi": 9, "lo": 4}), timeout=60) == 5


def test_dag_actor_nodes(local_cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    node = Counter.bind(100)
    dag = node.add.bind(5)
    assert ray_tpu.get(dag.execute(), timeout=60) == 105
    # dynamic execute creates a FRESH actor per call
    assert ray_tpu.get(dag.execute(), timeout=60) == 105


def test_compiled_dag_reuses_actors(local_cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.v = 0

        def add(self, x):
            self.v += x
            return self.v

    with InputNode() as inp:
        dag = Counter.bind().add.bind(inp)
    compiled = dag.experimental_compile(max_in_flight=2)
    try:
        # same actor across executes: state accumulates
        assert ray_tpu.get(compiled.execute(1), timeout=60) == 1
        assert ray_tpu.get(compiled.execute(2), timeout=60) == 3
        refs = [compiled.execute(1) for _ in range(6)]  # exceeds in-flight cap
        assert ray_tpu.get(refs[-1], timeout=60) == 9
    finally:
        compiled.teardown()


def test_compiled_dag_multi_output(local_cluster):
    @ray_tpu.remote
    def inc(x):
        return x + 1

    @ray_tpu.remote
    def dec(x):
        return x - 1

    with InputNode() as inp:
        dag = MultiOutputNode([inc.bind(inp), dec.bind(inp)])
    compiled = dag.experimental_compile()
    out = compiled.execute(10)
    assert ray_tpu.get(out, timeout=60) == [11, 9]


# ------------------------------------------------------------- workflow


@pytest.fixture
def wf_storage(tmp_path):
    workflow.init(str(tmp_path / "wf"))
    yield str(tmp_path / "wf")
    workflow.init(None)


def test_workflow_run_and_memoized_rerun(local_cluster, wf_storage, tmp_path):
    marker = str(tmp_path / "runs")

    @ray_tpu.remote
    def record(x):
        with open(marker, "a") as f:
            f.write("x")
        return x * 10

    @ray_tpu.remote
    def total(*parts):
        return sum(parts)

    dag = total.bind(record.bind(1), record.bind(2))
    assert workflow.run(dag, workflow_id="w1") == 30
    assert workflow.get_status("w1") == "SUCCEEDED"
    assert workflow.get_output("w1") == 30
    n_runs = len(open(marker).read())
    assert n_runs == 2
    # finished workflow: result served from storage, steps NOT re-run
    assert workflow.run(dag, workflow_id="w1") == 30
    assert len(open(marker).read()) == n_runs
    assert ("w1", "SUCCEEDED") in workflow.list_all()


def test_workflow_crash_resume_skips_done_steps(local_cluster, wf_storage,
                                                tmp_path):
    ok_flag = str(tmp_path / "ok")
    count_a = str(tmp_path / "a_runs")

    @ray_tpu.remote(max_retries=0)
    def step_a():
        with open(count_a, "a") as f:
            f.write("x")
        return 7

    @ray_tpu.remote(max_retries=0)
    def step_b(a):
        if not os.path.exists(ok_flag):
            raise RuntimeError("transient outage")
        return a + 1

    dag = step_b.bind(step_a.bind())
    with pytest.raises(ray_tpu.RayError):
        workflow.run(dag, workflow_id="w2")
    assert workflow.get_status("w2") == "FAILED"
    assert len(open(count_a).read()) == 1

    open(ok_flag, "w").close()  # outage over
    assert workflow.resume("w2") == 8
    assert workflow.get_status("w2") == "SUCCEEDED"
    # step_a's checkpoint was reused — it ran exactly once overall
    assert len(open(count_a).read()) == 1


def test_workflow_continuation(local_cluster, wf_storage):
    @ray_tpu.remote
    def fib(n, a=0, b=1):
        if n == 0:
            return a
        return workflow.continuation(fib.bind(n - 1, b, a + b))

    assert workflow.run(fib.bind(10), workflow_id="w3") == 55
    assert workflow.get_output("w3") == 55


def test_workflow_run_async_and_delete(local_cluster, wf_storage):
    @ray_tpu.remote
    def one():
        return 1

    fut = workflow.run_async(one.bind(), workflow_id="w4")
    assert fut.result(timeout=120) == 1
    workflow.delete("w4")
    with pytest.raises(ValueError):
        workflow.get_status("w4")


def test_workflow_rejects_actors(local_cluster, wf_storage):
    @ray_tpu.remote
    class A:
        def m(self):
            return 1

    with pytest.raises(TypeError):
        workflow.run(A.bind().m.bind(), workflow_id="w5")
