"""Autoscaler tests: fast deterministic decision units over fake
head/provider/clock, plus fake-multinode e2e (slow).

Mirrors the reference's suites (reference:
python/ray/tests/test_autoscaler.py MockProvider decision units +
test_autoscaler_fake_multinode.py; autoscaler/_private/autoscaler.py
demand loop, resource_demand_scheduler.py bin-packing): infeasible work
parks as demand, sustained backlog scales up through hysteresis, idle
nodes drain gracefully before termination.
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (AutoscalerConfig, NodeProvider,
                                ProviderNode, StandardAutoscaler)
from ray_tpu.cluster_utils import AutoscalingCluster


# --------------------------------------------------------------- fast units


class _FakeHead:
    """Stands in for the head's autoscaler RPCs: scripted snapshots,
    recorded drain requests, controllable drain status + epoch."""

    def __init__(self):
        self.epoch = "epoch-1"
        self.nodes = []
        self.signals = {"lease_queue_depth": {},
                        "sched_queued_p99_ms": 0.0, "serve": {}}
        self.drain_requests = []
        self.drain_state = {}
        self.calls = []
        self.reports = []

    def node(self, node_id, total, available, pending=(), head=False,
             arena_used=0):
        self.nodes.append({
            "node_id": node_id, "is_head_node": head, "total": dict(total),
            "available": dict(available), "pending": list(pending),
            "draining": False, "heartbeat_age_s": 0.0,
            "memory": {"arena_used": arena_used, "arena_free": 1 << 30,
                       "num_objects": 0}})

    def call(self, method, **kw):
        self.calls.append((method, kw))
        if method == "autoscaler_snapshot":
            return {"epoch": self.epoch, "nodes": [dict(n) for n in
                                                   self.nodes],
                    "pending_pg_bundles": [], "pending_actors": [],
                    "signals": dict(self.signals), "drains": {}}
        if method == "drain_node_graceful":
            self.drain_requests.append(kw["node_id"])
            return {"ok": True, "state": "draining"}
        if method == "drain_status":
            return dict(self.drain_state.get(kw["node_id"],
                                             {"state": "draining"}))
        if method == "autoscaler_report":
            self.reports.append(kw["status"])
        return {"ok": True, "epoch": self.epoch}

    def close(self):
        pass


class _FakeProvider(NodeProvider):
    def __init__(self):
        self.nodes = {}
        self.created = []
        self.terminated = []
        self._n = 0

    def create_node(self, node_type, resources, count=1):
        out = []
        for _ in range(count):
            self._n += 1
            pid = f"fake-{self._n}"
            node = ProviderNode(pid, node_type, f"node-{self._n}")
            self.nodes[pid] = node
            self.created.append((node_type, pid))
            out.append(node)
        return out

    def terminate_node(self, provider_id):
        self.nodes.pop(provider_id, None)
        self.terminated.append(provider_id)

    def non_terminated_nodes(self):
        return list(self.nodes.values())


class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _make(head, provider, clock, **cfg):
    types = cfg.pop("node_types", {
        "cpu-worker": {"resources": {"CPU": 4}, "min_workers": 0,
                       "max_workers": 4}})
    a = StandardAutoscaler(
        None, provider,
        AutoscalerConfig(types, idle_timeout_s=cfg.pop("idle_timeout_s", 5.0),
                         upscale_consecutive=cfg.pop("upscale_consecutive",
                                                     3), **cfg),
        head_client=head, clock=clock)
    return a


def _settle(a):
    """Join in-flight background launches so assertions are stable."""
    for p in list(a._pending):
        p.thread.join(timeout=2)


def test_infeasible_demand_scales_up_immediately():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    # {"CPU": 4} fits NO live node's totals: waiting cannot help
    head.nodes[0]["pending"] = [{"CPU": 4}]
    a = _make(head, provider, clock)
    a.update()
    _settle(a)
    assert [t for t, _ in provider.created] == ["cpu-worker"]
    a.stop()


def test_sustained_backlog_scales_up_after_hysteresis():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    # a busy 4-CPU worker: demand FITS totals, queues behind occupancy
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.node("node-0", {"CPU": 4}, {"CPU": 0},
              pending=[{"CPU": 4}])
    provider.create_node("cpu-worker", {"CPU": 4})  # the busy node
    provider.nodes["fake-1"].cluster_node_id = "node-0"
    head.signals["lease_queue_depth"] = {"node-0": [1, 2, 2]}
    a = _make(head, provider, clock, upscale_consecutive=3)
    a.update()
    a.update()
    _settle(a)
    assert len(provider.created) == 1, "backlog must wait out hysteresis"
    a.update()
    _settle(a)
    assert len(provider.created) == 2, \
        "3 consecutive backlog passes must scale up"
    a.stop()


def test_pending_actor_backlog_scales_despite_quiet_lease_ring():
    """Head-parked demand (PENDING actors) never enters any agent's
    lease queue, so the queue-depth ring stays 0 — its presence in the
    current snapshot must itself count as live pressure, or an actor
    whose shape fits a busy node's totals would park forever."""
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.node("node-0", {"CPU": 4}, {"CPU": 0})  # busy worker
    provider.create_node("cpu-worker", {"CPU": 4})
    provider.nodes["fake-1"].cluster_node_id = "node-0"
    # agents report the gauge every beat — all zeros (no lease queue)
    head.signals["lease_queue_depth"] = {"node-0": [0, 0, 0]}
    a = _make(head, provider, clock, upscale_consecutive=3)

    def call(method, **kw):
        r = _FakeHead.call(head, method, **kw)
        if method == "autoscaler_snapshot":
            r["pending_actors"] = [{"CPU": 4}]
        return r

    head_proxy = type("H", (), {"call": staticmethod(call),
                                "close": head.close})()
    a.head = head_proxy
    a.update()
    a.update()
    a.update()
    _settle(a)
    assert len(provider.created) == 2, \
        "sustained pending-actor demand must launch despite a 0 ring"
    a.stop()


def test_single_spike_rejected_by_hysteresis():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.node("node-0", {"CPU": 4}, {"CPU": 0}, pending=[{"CPU": 4}])
    provider.create_node("cpu-worker", {"CPU": 4})
    provider.nodes["fake-1"].cluster_node_id = "node-0"
    head.signals["lease_queue_depth"] = {"node-0": [3]}
    a = _make(head, provider, clock, upscale_consecutive=3)
    a.update()
    a.update()
    # the spike drains on its own before the streak completes
    head.nodes[1]["pending"] = []
    head.nodes[1]["available"] = {"CPU": 4}
    head.signals["lease_queue_depth"] = {"node-0": [3, 0, 0]}
    for _ in range(4):
        a.update()
    # demand returns once: streak restarted, still no launch
    head.nodes[1]["pending"] = [{"CPU": 4}]
    head.nodes[1]["available"] = {"CPU": 0}
    a.update()
    _settle(a)
    assert len(provider.created) == 1, \
        "a spike that drained must not have launched a node"
    a.stop()


def test_idle_scale_down_is_drain_based_and_blocks_until_drained():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.node("node-0", {"CPU": 4}, {"CPU": 4})
    provider.create_node("cpu-worker", {"CPU": 4})
    provider.nodes["fake-1"].cluster_node_id = "node-0"
    a = _make(head, provider, clock, idle_timeout_s=5.0)
    a.update()          # idle clock starts
    clock.t += 6.0
    a.update()          # idle past timeout: drain requested
    assert head.drain_requests == ["node-0"]
    assert provider.terminated == [], \
        "provider must NOT terminate while the drain is in flight " \
        "(a sole primary copy may still be re-replicating)"
    a.update()          # drain still reports 'draining'
    assert provider.terminated == []
    head.drain_state["node-0"] = {"state": "drained"}
    a.update()
    assert provider.terminated == ["fake-1"], \
        "terminate only after the head reports drained"
    assert a.scale_down_total == 1
    a.stop()


def test_failed_drain_releases_node_back_to_service():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.node("node-0", {"CPU": 4}, {"CPU": 4})
    provider.create_node("cpu-worker", {"CPU": 4})
    provider.nodes["fake-1"].cluster_node_id = "node-0"
    a = _make(head, provider, clock, idle_timeout_s=5.0)
    a.update()
    clock.t += 6.0
    a.update()
    assert head.drain_requests == ["node-0"]
    head.drain_state["node-0"] = {"state": "failed",
                                  "detail": "re-replication failed"}
    a.update()
    assert provider.terminated == []
    assert "node-0" not in a._draining
    a.stop()


def test_idle_scale_down_respects_min_workers():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.node("node-0", {"CPU": 4}, {"CPU": 4})
    provider.create_node("cpu-worker", {"CPU": 4})
    provider.nodes["fake-1"].cluster_node_id = "node-0"
    a = _make(head, provider, clock, node_types={
        "cpu-worker": {"resources": {"CPU": 4}, "min_workers": 1,
                       "max_workers": 4}}, idle_timeout_s=5.0)
    a.update()
    clock.t += 100.0
    a.update()
    a.update()
    assert head.drain_requests == [], \
        "the last min_workers node must never drain"
    a.stop()


def test_drain_victim_is_cheapest_by_store_bytes():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.node("node-0", {"CPU": 4}, {"CPU": 4}, arena_used=500)
    head.node("node-1", {"CPU": 4}, {"CPU": 4}, arena_used=5)
    provider.create_node("cpu-worker", {"CPU": 4})
    provider.create_node("cpu-worker", {"CPU": 4})
    provider.nodes["fake-1"].cluster_node_id = "node-0"
    provider.nodes["fake-2"].cluster_node_id = "node-1"
    a = _make(head, provider, clock, idle_timeout_s=5.0)
    a.update()
    clock.t += 6.0
    a.update()
    assert head.drain_requests == ["node-1"], \
        "the idle node with the fewest stored bytes drains first " \
        "(cheapest re-replication)"
    a.stop()


def test_head_restart_reregisters_node_types_on_epoch_change():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    a = _make(head, provider, clock)  # construction registers once
    a.update()
    a.update()
    regs = [c for c in head.calls if c[0] == "register_autoscaler"]
    assert len(regs) == 1, "steady state: no re-registration per pass"
    head.epoch = "epoch-2"  # head restarted
    a.update()
    regs = [c for c in head.calls if c[0] == "register_autoscaler"]
    assert len(regs) == 2, "epoch change must re-register node types"
    a.stop()


def test_stop_is_idempotent_and_adopts_inflight_launches():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.nodes[0]["pending"] = [{"CPU": 4}]
    a = _make(head, provider, clock)
    a.update()
    a.stop()
    a.stop()  # second stop must be a no-op, not a crash
    # the launch the first pass started was joined or adopted: the
    # provider still tracks its node either way
    assert provider.non_terminated_nodes(), "launched node was adopted"


def test_status_reported_to_head():
    head, provider, clock = _FakeHead(), _FakeProvider(), _Clock()
    head.node("head-1", {"CPU": 2}, {"CPU": 2}, head=True)
    head.nodes[0]["pending"] = [{"CPU": 4}]
    a = _make(head, provider, clock)
    a.update()
    _settle(a)
    a.update()
    assert head.reports, "every pass reports status to the head"
    last = head.reports[-1]
    assert "last_decision" in last and "pending_launches" in last
    # every completed launch was reported exactly once (the fake head
    # never shows the new nodes, so each pass may launch again)
    assert sum(r.get("events_delta", {}).get("up", 0)
               for r in head.reports) == len(provider.created)
    a.stop()


def test_serve_autoscale_decision_hysteresis():
    """ServeController._autoscale_desired as a pure decision unit:
    upscale needs consecutive rounds over target, a shed jumps past
    the current count, downscale waits out the delay."""
    from ray_tpu.serve.api import ServeController

    ctrl = object.__new__(ServeController)
    import threading

    ctrl._lock = threading.Lock()
    now = time.monotonic()
    app = {"desired": 1, "ongoing": {"h1": (6, now)}, "sheds": {},
           "autoscaling": {"min_replicas": 1, "max_replicas": 8,
                           "target_ongoing_requests": 2,
                           "upscale_consecutive": 2,
                           "downscale_delay_s": 5.0}}
    assert ctrl._autoscale_desired(app, 1) == 1, \
        "first over-target round must not scale yet (hysteresis)"
    assert ctrl._autoscale_desired(app, 1) == 3, \
        "second consecutive round scales to ceil(6/2)"
    # load vanishes: downscale only after the delay
    app["ongoing"] = {}
    assert ctrl._autoscale_desired(app, 3) == 3
    app["below_since"] = time.monotonic() - 6.0
    assert ctrl._autoscale_desired(app, 3) == 1
    # a shed means capacity is short NOW: desired jumps past current
    app["desired"] = 1
    app["up_streak"] = 0
    app["sheds"] = {"h1": (3, time.monotonic())}
    ctrl._autoscale_desired(app, 2)
    assert ctrl._autoscale_desired(app, 2) == 3, \
        "sheds push desired past the current replica count"


def test_llm_engine_queue_feeds_autoscale_decision():
    from ray_tpu.serve.api import ServeController

    ctrl = object.__new__(ServeController)
    import threading

    ctrl._lock = threading.Lock()
    app = {"desired": 1, "ongoing": {}, "sheds": {},
           "replica_queue": {"r1": 8},
           "autoscaling": {"min_replicas": 1, "max_replicas": 8,
                           "target_ongoing_requests": 2,
                           "upscale_consecutive": 1}}
    assert ctrl._autoscale_desired(app, 1) == 4, \
        "replica-side queued sequences count as load"


# ------------------------------------------------------ fake-multinode e2e


@pytest.fixture
def autoscaling_cluster():
    cluster = AutoscalingCluster(
        head_resources={"CPU": 2},
        worker_node_types={
            "cpu-worker": {"resources": {"CPU": 4}, "min_workers": 0,
                           "max_workers": 2},
            "tpu-worker": {"resources": {"CPU": 2, "TPU": 4},
                           "min_workers": 0, "max_workers": 2},
        },
        idle_timeout_s=2.0, update_period_s=0.3)
    ray_tpu.init(address=cluster.address)
    try:
        yield cluster
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.mark.slow
def test_scale_up_on_infeasible_task(autoscaling_cluster):
    """A {"CPU": 4} task cannot fit the 2-CPU head; the autoscaler must
    launch a cpu-worker and the task must then run (reference:
    autoscaler.py resolves infeasibility — the task pends, not fails)."""
    @ray_tpu.remote(num_cpus=4)
    def big():
        return "scaled"

    assert ray_tpu.get(big.remote(), timeout=120) == "scaled"
    assert len(autoscaling_cluster.provider.non_terminated_nodes()) >= 1


@pytest.mark.slow
def test_scale_up_for_tpu_resource(autoscaling_cluster):
    @ray_tpu.remote(resources={"TPU": 4})
    def tpu_task():
        return "tpu"

    assert ray_tpu.get(tpu_task.remote(), timeout=120) == "tpu"
    types = [n.node_type for n in
             autoscaling_cluster.provider.non_terminated_nodes()]
    assert "tpu-worker" in types


@pytest.mark.slow
def test_pending_actor_triggers_scale_up(autoscaling_cluster):
    @ray_tpu.remote(num_cpus=4)
    class Big:
        def ping(self):
            return "actor-scaled"

    a = Big.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=120) == "actor-scaled"


@pytest.mark.slow
def test_pending_pg_triggers_scale_up(autoscaling_cluster):
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    pg = placement_group([{"CPU": 4}], strategy="PACK")
    assert pg.wait(timeout=120), "placement group never became ready"
    remove_placement_group(pg)


@pytest.mark.slow
def test_idle_nodes_scale_down(autoscaling_cluster):
    @ray_tpu.remote(num_cpus=4)
    def big():
        return 1

    assert ray_tpu.get(big.remote(), timeout=120) == 1
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if not autoscaling_cluster.provider.non_terminated_nodes():
            return
        time.sleep(0.5)
    raise AssertionError("idle worker was never scaled down")


@pytest.mark.slow
def test_max_workers_cap(autoscaling_cluster):
    """More demand than max_workers allows: cluster grows to the cap and
    work completes there (queued, not failed)."""
    @ray_tpu.remote(num_cpus=4)
    def big(i):
        time.sleep(0.2)
        return i

    refs = [big.remote(i) for i in range(8)]
    assert sorted(ray_tpu.get(refs, timeout=180)) == list(range(8))
    cpu_workers = [n for n in
                   autoscaling_cluster.provider.non_terminated_nodes()
                   if n.node_type == "cpu-worker"]
    assert len(cpu_workers) <= 2


@pytest.mark.slow
def test_truly_infeasible_still_errors(autoscaling_cluster):
    """Demand no configured node type can ever satisfy fails fast."""
    @ray_tpu.remote(resources={"GPU": 8})
    def impossible():
        return 0

    with pytest.raises(ray_tpu.SchedulingError):
        ray_tpu.get(impossible.remote(), timeout=60)


# ---------------------------------------------------- graceful drain (e2e)


def _head_call(method, **kw):
    return ray_tpu.api._worker().head.call(method, timeout=30, **kw)


def _wait_drained(node_id, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        rec = _head_call("drain_status", node_id=node_id)
        if rec.get("state") == "drained":
            return rec
        assert rec.get("state") != "failed", f"drain failed: {rec}"
        time.sleep(0.2)
    raise AssertionError("drain never completed")


@pytest.mark.slow
def test_graceful_drain_preserves_objects_and_actor_state():
    """The drain-loses-nothing contract: a node holding the SOLE
    primary copies of live objects and a stateful actor drains — the
    copies re-replicate over the bulk plane (promoted to primary on the
    target, findable via the directory), the actor migrates via
    __rt_save__/__rt_restore__ with state intact, and the leak gauge
    stays 0."""
    import urllib.request

    import numpy as np

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 0})
    worker_a = cluster.add_node(num_cpus=4)  # the only CPU node at first
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)

        @ray_tpu.remote(num_cpus=1, max_retries=0)
        def produce(n):
            # max_retries=0: if the copy died with the node, get()
            # raises instead of reconstruction masking the loss
            return np.arange(n, dtype=np.uint8)

        # one directory-worthy object (>= locality_min_bytes) and one
        # small sole-copy object (only the head's injected directory
        # entry makes it findable after the drain)
        big = produce.remote(2 * 1024 * 1024)
        small = produce.remote(200 * 1024)

        # max_restarts=0: a crash would NOT revive this actor — only
        # the drain's save-hook migration can.  max_task_retries covers
        # the caller's stale-address push racing the migration, same
        # contract as chaos restarts (test_chaos.py).
        @ray_tpu.remote(num_cpus=1, max_restarts=0, max_task_retries=2)
        class Counter:
            def __init__(self):
                self.n = 0

            def incr(self):
                self.n += 1
                return self.n

            def node(self):
                import ray_tpu as rt

                return rt.api._worker().node_id

            def __rt_save__(self):
                return {"n": self.n}

            def __rt_restore__(self, state):
                self.n = state["n"]

        counter = Counter.remote()
        assert ray_tpu.get(
            [counter.incr.remote() for _ in range(3)], timeout=60
        ) == [1, 2, 3]
        assert ray_tpu.get(counter.node.remote(),
                           timeout=30) == worker_a.node_id
        assert ray_tpu.get(big, timeout=60).shape == (2 * 1024 * 1024,)

        # fresh capacity for the migration target, then drain A
        cluster.add_node(num_cpus=4)
        cluster.wait_for_nodes(3)
        r = _head_call("drain_node_graceful", node_id=worker_a.node_id)
        assert r.get("ok"), r
        rec = _wait_drained(worker_a.node_id)
        assert rec["replicated_objects"] >= 2, rec
        assert rec["migrated_actors"] == 1, rec

        # the node is gone from the table
        assert worker_a.node_id not in {
            n["node_id"] for n in ray_tpu.nodes()}
        # drain lost nothing: both sole copies survive (no lineage —
        # max_retries=0 — so this is the re-replicated bytes)
        a = ray_tpu.get(big, timeout=60)
        assert a.shape == (2 * 1024 * 1024,) and a[-1] == 255
        assert ray_tpu.get(small, timeout=60).shape == (200 * 1024,)
        # the actor resumed elsewhere with state intact
        assert ray_tpu.get(counter.incr.remote(), timeout=120) == 4
        assert ray_tpu.get(counter.node.remote(),
                           timeout=30) != worker_a.node_id
        # and the leak tripwires saw nothing across the scale-down
        port = _head_call("metrics_port")["port"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            text = resp.read().decode()
        for ln in text.splitlines():
            if ln.startswith("ray_tpu_object_leaked_bytes"):
                assert float(ln.rsplit(" ", 1)[1]) == 0.0, ln
        # the scale event is debuggable: /api/autoscaler carries the
        # drain record with its migration/replication counts
        import json as _json

        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/api/autoscaler",
                timeout=10) as resp:
            view = _json.loads(resp.read().decode())
        rec2 = view["drains"][worker_a.node_id]
        assert rec2["state"] == "drained"
        assert rec2["replicated_objects"] >= 2
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


@pytest.mark.slow
def test_autoscaler_grows_and_drains_back_under_burst():
    """Fake provider grows 1 -> 3 nodes under a task burst, then the
    drain-based scale-down empties the fleet once idle (the subprocess
    e2e half of the scale-event coverage)."""
    cluster = AutoscalingCluster(
        head_resources={"CPU": 2},
        worker_node_types={
            "cpu-worker": {"resources": {"CPU": 4}, "min_workers": 0,
                           "max_workers": 2}},
        idle_timeout_s=2.0, update_period_s=0.3)
    ray_tpu.init(address=cluster.address)
    try:
        @ray_tpu.remote(num_cpus=4)
        def burst(i):
            time.sleep(0.5)
            return i

        refs = [burst.remote(i) for i in range(6)]
        grew = 0
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            grew = max(grew,
                       len(cluster.provider.non_terminated_nodes()))
            if grew >= 2:
                break
            time.sleep(0.2)
        assert sorted(ray_tpu.get(refs, timeout=120)) == list(range(6))
        assert grew >= 2, "burst must have grown the fleet to 3 nodes"
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            if not cluster.provider.non_terminated_nodes():
                break
            time.sleep(0.3)
        assert not cluster.provider.non_terminated_nodes(), \
            "idle fleet must drain back down"
        # the provider empties the moment the drained agent process
        # exits; the autoscaler's drain-status poll records the
        # scale-down a pass later — wait it out
        deadline = time.monotonic() + 15
        st = cluster.status()
        while time.monotonic() < deadline \
                and st["scale_down_total"] < 2:
            time.sleep(0.3)
            st = cluster.status()
        assert st["scale_up_total"] >= 1
        assert st["scale_down_total"] >= 2, st
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
