"""Autoscaler tests over the fake node provider.

Mirrors the reference's fake-multinode autoscaler suite
(reference: python/ray/tests/test_autoscaler_fake_multinode.py;
autoscaler/_private/autoscaler.py demand loop,
resource_demand_scheduler.py bin-packing): infeasible work parks as
demand, the autoscaler launches local node-agent processes to satisfy
it, idle nodes are reaped.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import AutoscalingCluster


@pytest.fixture
def autoscaling_cluster():
    cluster = AutoscalingCluster(
        head_resources={"CPU": 2},
        worker_node_types={
            "cpu-worker": {"resources": {"CPU": 4}, "min_workers": 0,
                           "max_workers": 2},
            "tpu-worker": {"resources": {"CPU": 2, "TPU": 4},
                           "min_workers": 0, "max_workers": 2},
        },
        idle_timeout_s=2.0, update_period_s=0.3)
    ray_tpu.init(address=cluster.address)
    try:
        yield cluster
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_scale_up_on_infeasible_task(autoscaling_cluster):
    """A {"CPU": 4} task cannot fit the 2-CPU head; the autoscaler must
    launch a cpu-worker and the task must then run (reference:
    autoscaler.py resolves infeasibility — the task pends, not fails)."""
    @ray_tpu.remote(num_cpus=4)
    def big():
        return "scaled"

    assert ray_tpu.get(big.remote(), timeout=120) == "scaled"
    assert len(autoscaling_cluster.provider.non_terminated_nodes()) >= 1


def test_scale_up_for_tpu_resource(autoscaling_cluster):
    @ray_tpu.remote(resources={"TPU": 4})
    def tpu_task():
        return "tpu"

    assert ray_tpu.get(tpu_task.remote(), timeout=120) == "tpu"
    types = [n.node_type for n in
             autoscaling_cluster.provider.non_terminated_nodes()]
    assert "tpu-worker" in types


def test_pending_actor_triggers_scale_up(autoscaling_cluster):
    @ray_tpu.remote(num_cpus=4)
    class Big:
        def ping(self):
            return "actor-scaled"

    a = Big.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=120) == "actor-scaled"


def test_pending_pg_triggers_scale_up(autoscaling_cluster):
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)

    pg = placement_group([{"CPU": 4}], strategy="PACK")
    assert pg.wait(timeout=120), "placement group never became ready"
    remove_placement_group(pg)


def test_idle_nodes_scale_down(autoscaling_cluster):
    @ray_tpu.remote(num_cpus=4)
    def big():
        return 1

    assert ray_tpu.get(big.remote(), timeout=120) == 1
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if not autoscaling_cluster.provider.non_terminated_nodes():
            return
        time.sleep(0.5)
    raise AssertionError("idle worker was never scaled down")


def test_max_workers_cap(autoscaling_cluster):
    """More demand than max_workers allows: cluster grows to the cap and
    work completes there (queued, not failed)."""
    @ray_tpu.remote(num_cpus=4)
    def big(i):
        time.sleep(0.2)
        return i

    refs = [big.remote(i) for i in range(8)]
    assert sorted(ray_tpu.get(refs, timeout=180)) == list(range(8))
    cpu_workers = [n for n in
                   autoscaling_cluster.provider.non_terminated_nodes()
                   if n.node_type == "cpu-worker"]
    assert len(cpu_workers) <= 2


def test_truly_infeasible_still_errors(autoscaling_cluster):
    """Demand no configured node type can ever satisfy fails fast."""
    @ray_tpu.remote(resources={"GPU": 8})
    def impossible():
        return 0

    with pytest.raises(ray_tpu.SchedulingError):
        ray_tpu.get(impossible.remote(), timeout=60)
