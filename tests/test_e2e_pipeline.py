"""End-to-end user pipeline: Data -> Train -> checkpoint storage ->
Serve -> binary ingress query — the full stack the way a user strings
it together (reference: the doc examples combining ray.data +
ray.train + ray.serve)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data, serve
from tests.conftest import force_cpu_jax


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        try:
            serve.shutdown()
        except Exception:
            pass
        ray_tpu.shutdown()


def test_data_train_checkpoint_serve(cluster, tmp_path):
    force_cpu_jax()
    from ray_tpu.train import JaxTrainer, ScalingConfig
    from ray_tpu.train.storage import StorageContext

    # 1. Data: a toy linear regression dataset, y = 3x - 1
    xs = np.linspace(-1, 1, 256).astype(np.float32)
    ds = data.from_numpy({"x": xs, "y": 3.0 * xs - 1.0})

    storage_root = str(tmp_path / "store")

    # 2. Train: per-worker loop ingesting its dataset shard, reporting
    # metrics and a checkpoint with the learned weights
    def loop(config):
        import json
        import os

        from ray_tpu.train import get_context, get_dataset_shard, report

        shard = get_dataset_shard("train")
        rows = shard.take_all()
        x = np.array([r["x"] for r in rows], dtype=np.float32)
        y = np.array([r["y"] for r in rows], dtype=np.float32)
        w, b = 0.0, 0.0
        for step in range(200):
            pred = w * x + b
            err = pred - y
            w -= 0.3 * float((err * x).mean())
            b -= 0.3 * float(err.mean())
            if step % 50 == 49:
                ckpt_dir = os.path.join(
                    config["out"], f"w{get_context().rank}-{step}")
                os.makedirs(ckpt_dir, exist_ok=True)
                with open(os.path.join(ckpt_dir, "weights.json"), "w") as f:
                    json.dump({"w": w, "b": b}, f)
                report({"loss": float((err ** 2).mean())},
                       checkpoint=ckpt_dir)
        return {"w": w, "b": b}

    result = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2),
        train_loop_config={"out": storage_root},
        datasets={"train": ds}).fit()
    final = result.per_worker_final[0]
    assert abs(final["w"] - 3.0) < 0.1 and abs(final["b"] + 1.0) < 0.1

    # 3. Checkpoint storage: persist the final weights to "remote"
    # storage and restore on a "fresh host" path
    sc = StorageContext("memory://e2e/run", "exp")
    src = tmp_path / "final"
    src.mkdir()
    (src / "weights.json").write_text(
        __import__("json").dumps(final))
    sc.persist_dir(str(src), "checkpoints/final")
    restored_dir = sc.fetch_dir("checkpoints/final",
                                str(tmp_path / "restored"))
    weights = __import__("json").loads(
        open(f"{restored_dir}/weights.json").read())

    # 4. Serve: deploy the trained model, query via handle AND the
    # binary ingress
    @serve.deployment(name="linreg", num_replicas=2)
    class LinReg:
        def __init__(self, w, b):
            self.w, self.b = w, b

        def __call__(self, x):
            return {"y": self.w * float(x) + self.b}

    handle = serve.run(LinReg.bind(weights["w"], weights["b"]))
    y = ray_tpu.get(handle.remote(2.0), timeout=60)["y"]
    assert abs(y - 5.0) < 0.3

    host, port = serve.start_rpc_ingress()
    client = serve.RpcIngressClient(host, port)
    try:
        assert abs(client.invoke("linreg", 0.0)["y"] + 1.0) < 0.3
    finally:
        client.close()
        serve.stop_rpc_ingress()
        serve.delete("linreg")
