"""Bulk object-transfer plane + locality-aware scheduling tests.

In-process harness: a HeadService plus N NodeAgents on one event loop
(they are all asyncio-native), so cross-node pulls, the head's object
directory, multi-source retry and prefetch-on-lease are exercised
without process spawn costs.  End-to-end locality routing rides the
real multi-process Cluster in TestLocalityE2E.
"""

import asyncio
import os
import uuid

import pytest

import ray_tpu
from ray_tpu._private.head import HeadService
from ray_tpu._private.node_agent import NodeAgent
from ray_tpu._private.task_spec import TaskSpec, WireArg

MB = 1024 * 1024


async def _boot(tmp_path, n=2, capacities=None):
    head = HeadService()
    head_port = await head.start()
    agents = []
    for i in range(n):
        cap = (capacities or {}).get(i, 32 * MB)
        ag = NodeAgent(("127.0.0.1", head_port), str(tmp_path), {"CPU": 1},
                       arena_path=str(tmp_path / f"arena-{i}-{uuid.uuid4().hex[:6]}"),
                       capacity=cap)
        await ag.start()
        agents.append(ag)
    return head, agents


async def _down(head, agents):
    for ag in agents:
        try:
            await ag.stop()
        except Exception:
            pass
    await head.stop()


def _seed_object(agent, oid, payload):
    """Create+seal a sealed shm/disk object directly in an agent's store."""
    loc = agent.store.create(oid, len(payload))
    if loc["location"] == "shm":
        agent.store.arena.view[
            loc["offset"]:loc["offset"] + len(payload)] = payload
    else:
        with open(loc["path"], "r+b") as f:
            f.write(payload)
    agent.store.seal(oid)


def _read_object(agent, oid, size):
    entry = agent.store.objects[oid]
    if entry.location == "shm":
        return bytes(agent.store.arena.view[entry.offset:entry.offset + size])
    with open(entry.path, "rb") as f:
        return f.read()


def _run(coro):
    asyncio.run(coro)


async def _assert_no_pull_residue(*agents, deadline_s: float = 5.0):
    """After pulls complete: zero pinned bytes (the puller's obj_unpin
    oneway may still be in flight, hence the short wait), zero mmap-
    cache residue for shm pulls, and a breakdown whose shm bucket
    reconciles exactly with the allocator's occupancy."""
    import time as _time

    deadline = _time.monotonic() + deadline_s
    while True:
        bds = [ag.store.byte_breakdown() for ag in agents]
        if all(bd["pinned_bytes"] == 0 for bd in bds):
            break
        if _time.monotonic() > deadline:
            raise AssertionError(f"pinned bytes survived the pull: {bds}")
        await asyncio.sleep(0.05)
    for ag, bd in zip(agents, bds):
        assert bd["shm_bytes"] == bd["arena_used"], bd
        assert bd["pinned_objects"] == 0, bd


class TestBulkPull:
    def test_shm_to_shm(self, tmp_path):
        async def main():
            head, agents = await _boot(tmp_path)
            a, b = agents
            try:
                payload = os.urandom(2 * MB)
                _seed_object(a, "oid1", payload)
                r = await b.rpc_ensure_local("oid1", src=[a.host, a.port])
                assert r.get("ok"), r
                assert b.store.contains("oid1")
                assert _read_object(b, "oid1", len(payload)) == payload
                assert b.xfer_stats["bulk_pulls"] == 1
                assert b.xfer_stats["rpc_pulls"] == 0
                assert b.xfer_stats["bytes_in"] == len(payload)
                # accounting tripwire (ISSUE 9 satellite): once the pull
                # completes, no transfer pin or mmap-cache entry survives
                # on either side, and each breakdown reconciles with the
                # allocator's own occupancy gauge
                await _assert_no_pull_residue(a, b)
            finally:
                await _down(head, agents)
        _run(main())

    def test_disk_fallback_both_sides(self, tmp_path):
        async def main():
            # destination arena too small -> disk fallback on the puller;
            # source seeded straight to a disk entry exercises the
            # holder-side mmap path too
            head, agents = await _boot(tmp_path, capacities={1: 1 * MB})
            a, b = agents
            try:
                payload = os.urandom(3 * MB)
                _seed_object(a, "oid-big", payload)
                r = await b.rpc_ensure_local("oid-big", src=[a.host, a.port])
                assert r.get("ok"), r
                assert b.store.objects["oid-big"].location == "disk"
                assert _read_object(b, "oid-big", len(payload)) == payload
                await _assert_no_pull_residue(a, b)
            finally:
                await _down(head, agents)
        _run(main())

    def test_concurrent_pulls_dedup(self, tmp_path):
        async def main():
            head, agents = await _boot(tmp_path)
            a, b = agents
            try:
                payload = os.urandom(2 * MB)
                _seed_object(a, "oid-dup", payload)
                src = [a.host, a.port]
                replies = await asyncio.gather(
                    *[b.rpc_ensure_local("oid-dup", src=src)
                      for _ in range(4)])
                assert all(r.get("ok") for r in replies), replies
                assert b.xfer_stats["pulls"] == 1  # one transfer, 4 waiters
                assert _read_object(b, "oid-dup", len(payload)) == payload
            finally:
                await _down(head, agents)
        _run(main())

    def test_legacy_rpc_chunk_fallback(self, tmp_path, monkeypatch):
        async def main():
            head, agents = await _boot(tmp_path,
                                       capacities={0: 4 * MB, 1: 1 * MB})
            a, b = agents
            try:
                shm, disk = os.urandom(2 * MB), os.urandom(5 * MB)
                _seed_object(a, "oid-shm", shm)    # fits A's arena
                _seed_object(a, "oid-disk", disk)  # > arena: disk on A
                assert a.store.objects["oid-disk"].location == "disk"
                for oid, payload in (("oid-shm", shm), ("oid-disk", disk)):
                    r = await b.rpc_ensure_local(oid, src=[a.host, a.port])
                    assert r.get("ok"), r
                    assert _read_object(b, oid, len(payload)) == payload
                assert b.xfer_stats["rpc_pulls"] == 2
                assert b.xfer_stats["bulk_pulls"] == 0
                # fds/mappings held across the pull are dropped on unpin
                await asyncio.sleep(0.1)
                assert not a._xfer._maps
            finally:
                await _down(head, agents)
        monkeypatch.setenv("RT_OBJECT_TRANSFER_ENABLED", "false")
        _run(main())

    def test_bulk_transport_failure_falls_back_to_rpc_chunks(self, tmp_path):
        async def main():
            head, agents = await _boot(tmp_path)
            a, b = agents
            try:
                payload = os.urandom(2 * MB)
                _seed_object(a, "oid-fb", payload)
                # the holder's transfer listener is gone but its control
                # RPC still works: the pull must ride the chunk path
                await a._xfer.stop()
                r = await b.rpc_ensure_local("oid-fb", src=[a.host, a.port])
                assert r.get("ok"), r
                assert b.xfer_stats["bulk_fallbacks"] == 1
                assert b.xfer_stats["rpc_pulls"] == 1
                assert _read_object(b, "oid-fb", len(payload)) == payload
            finally:
                await _down(head, agents)
        _run(main())

    def test_source_vanished_retries_alternate_holder(self, tmp_path):
        async def main():
            head, agents = await _boot(tmp_path, n=3)
            a, b, c = agents
            try:
                payload = os.urandom(2 * MB)
                _seed_object(a, "oid-ha", payload)
                _seed_object(c, "oid-ha", payload)
                # the directory learns holders from (seal-triggered)
                # heartbeats; wait until C's copy is visible at the head
                for _ in range(100):
                    r = await head.rpc_object_locations(oids=["oid-ha"])
                    holders = r["locations"].get("oid-ha", [])
                    if [c.host, c.port] in holders:
                        break
                    await asyncio.sleep(0.05)
                else:
                    raise AssertionError(f"directory never saw C: {holders}")
                # kill A (listener + transfer plane) mid-everything, then
                # pull on B with the now-dead source recorded
                await a.stop()
                r = await b.rpc_ensure_local("oid-ha", src=[a.host, a.port])
                assert r.get("ok"), r
                assert b.xfer_stats["alt_source_retries"] == 1
                assert _read_object(b, "oid-ha", len(payload)) == payload
            finally:
                await _down(head, [b, c])
        _run(main())

    def test_no_source_and_no_holder_errors(self, tmp_path):
        async def main():
            head, agents = await _boot(tmp_path)
            _a, b = agents
            try:
                r = await b.rpc_ensure_local("oid-none", src=None)
                assert not r.get("ok")
            finally:
                await _down(head, agents)
        _run(main())


class TestPrefetch:
    def test_prefetch_on_lease_hints(self, tmp_path):
        async def main():
            head, agents = await _boot(tmp_path)
            a, b = agents
            try:
                payload = os.urandom(2 * MB)
                _seed_object(a, "oid-pf", payload)
                spec = TaskSpec(
                    task_id="ab" * 12, job_id="01", resources={"CPU": 1},
                    args=[WireArg(object_id="oid-pf",
                                  owner_addr=("127.0.0.1", 1),
                                  size=len(payload), loc=(a.host, a.port))])
                b._prefetch_args(spec)
                assert b.xfer_stats["prefetch_started"] == 1
                for _ in range(200):
                    if b.store.contains("oid-pf"):
                        break
                    await asyncio.sleep(0.02)
                assert b.store.contains("oid-pf")
                assert _read_object(b, "oid-pf", len(payload)) == payload
                # already local: a second lease for the same arg starts
                # nothing new
                b._prefetch_args(spec)
                assert b.xfer_stats["prefetch_started"] == 1
                assert b.xfer_stats["pulls"] == 1
            finally:
                await _down(head, agents)
        _run(main())

    def test_prefetch_dedups_against_ensure_local(self, tmp_path):
        async def main():
            head, agents = await _boot(tmp_path)
            a, b = agents
            try:
                payload = os.urandom(2 * MB)
                _seed_object(a, "oid-pd", payload)
                spec = TaskSpec(
                    task_id="cd" * 12, job_id="01", resources={"CPU": 1},
                    args=[WireArg(object_id="oid-pd",
                                  owner_addr=("127.0.0.1", 1),
                                  size=len(payload), loc=(a.host, a.port))])
                b._prefetch_args(spec)
                # the worker's fetch arrives while the prefetch flies
                r = await b.rpc_ensure_local("oid-pd", src=[a.host, a.port])
                assert r.get("ok")
                assert b.xfer_stats["pulls"] == 1
            finally:
                await _down(head, agents)
        _run(main())


class TestDirectory:
    def test_heartbeat_delta_feeds_sharded_directory(self, tmp_path):
        """Agents report object DELTAS; the head folds them into the
        sharded directory, and other agents' mirrors converge via the
        shard-versioned updates on heartbeat replies."""
        async def main():
            head, agents = await _boot(tmp_path)
            a, b = agents
            try:
                payload = os.urandom(2 * MB)
                _seed_object(a, "oid-dir", payload)
                # small objects stay out of the directory
                _seed_object(a, "oid-small", b"x" * 1024)
                a._hb_wake.set()
                for _ in range(100):
                    if head.dir.locations("oid-dir"):
                        break
                    await asyncio.sleep(0.05)
                assert head.dir.locations("oid-dir") == {
                    a.node_id: len(payload)}
                assert not head.dir.locations("oid-small")
                assert head.dir.node_entries(a.node_id) == {
                    "oid-dir": len(payload)}
                view = head._cluster_view()
                assert view[a.node_id]["xfer"] == a.xfer_port
                # the PEER agent's mirror learns the holder too (its
                # next heartbeat reply carries the changed shard)
                b._hb_wake.set()
                for _ in range(100):
                    if b._dir_mirror.holders("oid-dir"):
                        break
                    await asyncio.sleep(0.05)
                assert b._dir_mirror.holders("oid-dir") == {
                    a.node_id: len(payload)}
                # freeing the object flows a removal delta through
                a.store.free(["oid-dir"])
                a._hb_wake.set()
                for _ in range(100):
                    if not head.dir.locations("oid-dir"):
                        break
                    a._hb_wake.set()
                    await asyncio.sleep(0.05)
                assert not head.dir.locations("oid-dir")
            finally:
                await _down(head, agents)
        _run(main())


class TestLocalityE2E:
    @pytest.fixture(scope="class")
    def locality_cluster(self):
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(head_node_args={"num_cpus": 2})
        cluster.add_node(num_cpus=2, resources={"nodeA": 1})
        cluster.add_node(num_cpus=2, resources={"nodeB": 1})
        ray_tpu.init(address=cluster.address)
        cluster.wait_for_nodes(3)
        try:
            yield cluster
        finally:
            ray_tpu.shutdown()
            cluster.shutdown()

    def _agent_info(self, node):
        from ray_tpu._private.rpc import EventLoopThread, SyncRpcClient

        io = EventLoopThread()
        try:
            c = SyncRpcClient(node.addr[0], node.addr[1], io)
            info = c.call("node_info", timeout=10.0)
            c.close()
            return info
        finally:
            io.stop()

    def test_locality_routes_to_holder_zero_pull(self, locality_cluster):
        import numpy as np

        @ray_tpu.remote(resources={"nodeA": 0.1})
        def produce():
            return np.arange(500_000, dtype=np.float64)  # 4MB plasma

        @ray_tpu.remote  # NO placement constraint: locality must route it
        def consume(arr):
            return os.environ["RT_NODE_ID"], float(arr.sum())

        ref = produce.remote()
        producer_node = locality_cluster.nodes[1].node_id  # nodeA
        ran_on, total = ray_tpu.get(consume.remote(ref), timeout=60)
        assert total == float(np.arange(500_000, dtype=np.float64).sum())
        assert ran_on == producer_node
        # the co-located arg was never transferred: no node pulled
        for node in locality_cluster.nodes:
            stats = self._agent_info(node)["xfer_stats"]
            assert stats["pulls"] == 0, (node.node_id, stats)

    def test_warm_lease_elsewhere_does_not_defeat_locality(self,
                                                           locality_cluster):
        import numpy as np

        @ray_tpu.remote(resources={"nodeA": 0.1})
        def produce():
            return np.ones(300_000, dtype=np.float64)

        @ray_tpu.remote
        def consume(a):
            return os.environ["RT_NODE_ID"]

        ref = produce.remote()
        ray_tpu.wait([ref], timeout=60)
        # prime a warm lease for consume's scheduling class on the
        # DRIVER's node (inline arg, local preference)
        ray_tpu.get(consume.remote(1), timeout=60)
        # submitted immediately, while that lease is warm: the pump
        # must defer past it and route via locality to the holder
        ran_on = ray_tpu.get(consume.remote(ref), timeout=60)
        assert ran_on == locality_cluster.nodes[1].node_id

    def test_prefetch_overlap_on_pinned_consumer(self, locality_cluster):
        import numpy as np

        @ray_tpu.remote(resources={"nodeA": 0.1})
        def produce():
            return np.ones(500_000, dtype=np.float64)

        @ray_tpu.remote(resources={"nodeB": 0.1})  # forced off the holder
        def consume(arr):
            return float(arr.sum())

        ref = produce.remote()
        assert ray_tpu.get(consume.remote(ref), timeout=60) == 500_000.0
        stats = self._agent_info(locality_cluster.nodes[2])["xfer_stats"]
        # the grant-side agent started the pull before the worker asked
        assert stats["prefetch_started"] >= 1, stats
        assert stats["pulls"] >= 1, stats
        assert stats["bulk_pulls"] >= 1, stats
