"""ray_tpu.tune tests (reference: python/ray/tune/tests/ patterns —
mock-fast trainables, deterministic search spaces)."""

import pytest

import ray_tpu
from ray_tpu import tune


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def test_grid_search_finds_best(cluster):
    def trainable(config):
        from ray_tpu import train

        score = (config["x"] - 3) ** 2
        train.report({"score": score})
        return score

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="score", mode="min"),
    )
    results = tuner.fit()
    assert len(results) == 5
    best = results.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 0


def test_random_sampling_and_seed(cluster):
    variants = tune.search.generate_variants(
        {"lr": tune.loguniform(1e-5, 1e-1), "b": tune.choice([1, 2])},
        num_samples=4, seed=0)
    again = tune.search.generate_variants(
        {"lr": tune.loguniform(1e-5, 1e-1), "b": tune.choice([1, 2])},
        num_samples=4, seed=0)
    assert variants == again
    assert len(variants) == 4
    assert all(1e-5 <= v["lr"] <= 1e-1 for v in variants)


def test_asha_prunes_bad_trials(cluster):
    def trainable(config):
        import time as _t

        from ray_tpu import train

        for step in range(1, 21):
            # bad trials plateau high; good ones descend
            loss = config["quality"] * 10.0 / step
            train.report({"loss": loss, "training_iteration": step})
            _t.sleep(0.005)
        return True

    scheduler = tune.ASHAScheduler(metric="loss", mode="min", max_t=20,
                                   grace_period=2, reduction_factor=2)
    tuner = tune.Tuner(
        trainable,
        param_space={"quality": tune.grid_search([1, 1, 8, 8, 8, 8])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    scheduler=scheduler,
                                    max_concurrent_trials=6),
    )
    results = tuner.fit()
    states = [r.state for r in results]
    assert "STOPPED" in states  # some bad trial was pruned early
    best = results.get_best_result()
    assert best.config["quality"] == 1


def test_trial_error_recorded(cluster):
    def trainable(config):
        if config["x"] == 1:
            raise ValueError("bad trial")
        from ray_tpu import train

        train.report({"ok": 1})
        return True

    results = tune.Tuner(
        trainable, param_space={"x": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="ok", mode="max")).fit()
    by_x = {r.config["x"]: r for r in results}
    assert by_x[0].state == "TERMINATED"
    assert by_x[1].state == "ERROR"
    assert "bad trial" in by_x[1].error


def test_result_dataframe(cluster):
    def trainable(config):
        from ray_tpu import train

        train.report({"m": config["x"] * 2})

    results = tune.Tuner(
        trainable, param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="m", mode="max")).fit()
    df = results.get_dataframe()
    assert set(df["config/x"]) == {1, 2}
    assert set(df["m"]) == {2, 4}
