"""ray_tpu.tune tests (reference: python/ray/tune/tests/ patterns —
mock-fast trainables, deterministic search spaces)."""

import pytest

import ray_tpu
from ray_tpu import tune


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def test_grid_search_finds_best(cluster):
    def trainable(config):
        from ray_tpu import train

        score = (config["x"] - 3) ** 2
        train.report({"score": score})
        return score

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2, 3, 4])},
        tune_config=tune.TuneConfig(metric="score", mode="min"),
    )
    results = tuner.fit()
    assert len(results) == 5
    best = results.get_best_result()
    assert best.config["x"] == 3
    assert best.metrics["score"] == 0


def test_random_sampling_and_seed(cluster):
    variants = tune.search.generate_variants(
        {"lr": tune.loguniform(1e-5, 1e-1), "b": tune.choice([1, 2])},
        num_samples=4, seed=0)
    again = tune.search.generate_variants(
        {"lr": tune.loguniform(1e-5, 1e-1), "b": tune.choice([1, 2])},
        num_samples=4, seed=0)
    assert variants == again
    assert len(variants) == 4
    assert all(1e-5 <= v["lr"] <= 1e-1 for v in variants)


def test_asha_prunes_bad_trials(cluster):
    def trainable(config):
        import time as _t

        from ray_tpu import train

        for step in range(1, 21):
            # bad trials plateau high; good ones descend
            loss = config["quality"] * 10.0 / step
            train.report({"loss": loss, "training_iteration": step})
            _t.sleep(0.005)
        return True

    scheduler = tune.ASHAScheduler(metric="loss", mode="min", max_t=20,
                                   grace_period=2, reduction_factor=2)
    tuner = tune.Tuner(
        trainable,
        param_space={"quality": tune.grid_search([1, 1, 8, 8, 8, 8])},
        tune_config=tune.TuneConfig(metric="loss", mode="min",
                                    scheduler=scheduler,
                                    max_concurrent_trials=6),
    )
    results = tuner.fit()
    states = [r.state for r in results]
    assert "STOPPED" in states  # some bad trial was pruned early
    best = results.get_best_result()
    assert best.config["quality"] == 1


def test_trial_error_recorded(cluster):
    def trainable(config):
        if config["x"] == 1:
            raise ValueError("bad trial")
        from ray_tpu import train

        train.report({"ok": 1})
        return True

    results = tune.Tuner(
        trainable, param_space={"x": tune.grid_search([0, 1])},
        tune_config=tune.TuneConfig(metric="ok", mode="max")).fit()
    by_x = {r.config["x"]: r for r in results}
    assert by_x[0].state == "TERMINATED"
    assert by_x[1].state == "ERROR"
    assert "bad trial" in by_x[1].error


def test_result_dataframe(cluster):
    def trainable(config):
        from ray_tpu import train

        train.report({"m": config["x"] * 2})

    results = tune.Tuner(
        trainable, param_space={"x": tune.grid_search([1, 2])},
        tune_config=tune.TuneConfig(metric="m", mode="max")).fit()
    df = results.get_dataframe()
    assert set(df["config/x"]) == {1, 2}
    assert set(df["m"]) == {2, 4}


def test_experiment_snapshot_and_restore(tmp_path, cluster):
    """Tuner writes experiment state; Tuner.restore resumes it with
    completed trials intact (reference: experiment_state.py,
    Tuner.restore)."""
    import os

    from ray_tpu import train
    from ray_tpu.tune import TuneConfig, Tuner
    from ray_tpu.tune import grid_search

    marker_dir = str(tmp_path / "runs")
    os.makedirs(marker_dir, exist_ok=True)

    def trainable(config):
        # side-effect marker: lets the test count actual executions
        open(os.path.join(config["marker_dir"],
                          f"run-{config['x']}"), "a").write("x")
        train.report({"loss": config["x"] * 1.0})

    tuner = Tuner(
        trainable,
        param_space={"x": grid_search([1, 2, 3]),
                     "marker_dir": marker_dir},
        tune_config=TuneConfig(metric="loss", mode="min"),
        storage_path=str(tmp_path), name="exp1")
    grid = tuner.fit()
    assert len(grid) == 3
    assert grid.get_best_result().metrics["loss"] == 1.0
    assert os.path.exists(str(tmp_path / "exp1" / "experiment_state.pkl"))
    runs_before = len(os.listdir(marker_dir))

    restored = Tuner.restore(str(tmp_path / "exp1"), trainable)
    grid2 = restored.fit()
    assert len(grid2) == 3
    assert grid2.get_best_result().metrics["loss"] == 1.0
    # completed trials did NOT re-execute
    assert len(os.listdir(marker_dir)) == runs_before


def test_pbt_exploits_and_explores(cluster):
    """Bottom-quantile trials are stopped and replaced by perturbed
    clones of top performers carrying the donor's checkpoint
    (reference: tune/schedulers/pbt.py)."""
    import json

    from ray_tpu import train
    from ray_tpu.tune import TuneConfig, Tuner, PopulationBasedTraining

    def trainable(config):
        # cumulative score: good lr (near 1.0) climbs faster; clones
        # resume from the donor's accumulated score via the checkpoint.
        # The sleep interleaves reports across the population so the
        # scheduler sees concurrent progress, as in real training.
        import time as _time

        state = {"score": 0.0}
        ck = config.get("__restore_checkpoint__")
        if ck:
            state = json.loads(ck)
        for _ in range(6):
            _time.sleep(0.1)
            state["score"] += 1.0 - abs(config["lr"] - 1.0)
            train.report({"score": state["score"]},
                         checkpoint=json.dumps(state))

    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=2,
        quantile_fraction=0.25,
        hyperparam_mutations={"lr": [0.25, 0.5, 1.0, 2.0]}, seed=0)
    tuner = Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.25, 0.5, 1.0, 2.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=pbt,
                               max_concurrent_trials=4))
    grid = tuner.fit()
    # clones were created (exploit happened) and the best result beats
    # what the worst starting lr could ever reach alone (6 * 0.0 = 0)
    clone_results = [r for r in grid if r.trial_id.startswith("clone_")]
    assert clone_results, "PBT never exploited a top performer"
    best = grid.get_best_result()
    assert best.metrics["score"] > 3.0
