"""Head control-plane sharding tests (_private/head_shards.py + the
rpc.py per-op loop routing it rides on).

Units: op -> owning-loop dispatch, cross-shard queue drain batching,
versioned-snapshot monotonicity across a simulated head restart.
E2e: a 10k-task burst through a sharded head (``head_ingest_shards=2``)
completes flat with zero dropped task events, and the single-loop
compat mode (``head_ingest_shards=0``) runs the same surface.
"""

import asyncio
import os
import threading
import time

import pytest

from ray_tpu._private import rpc as rpcmod
from ray_tpu._private.head_shards import (CrossShardQueue, HeadShards,
                                          VersionedSnapshot)


# --------------------------------------------------------- VersionedSnapshot


def test_versioned_snapshot_read_is_consistent_pair():
    s = VersionedSnapshot(payload={"a": 1})
    v0, p0 = s.read()
    assert p0 == {"a": 1}
    v1 = s.publish({"a": 2})
    assert v1 == v0 + 1
    ver, payload = s.read()
    assert (ver, payload) == (v1, {"a": 2})
    assert s.version == v1 and s.payload == {"a": 2}


def test_versioned_snapshot_monotonic_across_restart():
    """A restarted publisher (head restart rebuilding its snapshots)
    must seed ABOVE anything the old incarnation published, so 'only
    apply newer' guards downstream stay correct across the boundary."""
    old = VersionedSnapshot(payload=None)
    last = 0
    for i in range(50):
        last = old.publish({"i": i})
    time.sleep(0.001)  # the old head dies; a new one comes up
    fresh = VersionedSnapshot(payload=None)
    assert fresh.version > last
    assert fresh.publish({"rebuilt": True}) > last


def test_versioned_snapshot_explicit_seed():
    s = VersionedSnapshot(payload=None, start_version=7)
    assert s.version == 7
    assert s.publish("x") == 8


# ----------------------------------------------------------- CrossShardQueue


def test_cross_shard_queue_drains_backlog_in_one_callback():
    """N producer puts must cost the consumer loop far fewer than N
    callbacks: the drain sweeps the whole backlog per scheduled tick."""
    io = rpcmod.EventLoopThread(name="test-core")
    got = []
    drains = []

    def _drain(items):
        drains.append(len(items))
        got.extend(items)

    q = CrossShardQueue(io.loop, _drain, name="test")
    try:
        # stall the consumer loop so puts pile up behind one callback
        async def _stall():
            time.sleep(0.15)

        fut = asyncio.run_coroutine_threadsafe(_stall(), io.loop)
        n = 500
        for i in range(n):
            q.put(i)
        fut.result(timeout=5)
        deadline = time.monotonic() + 5
        while len(got) < n and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sorted(got) == list(range(n))
        assert len(drains) < n / 10, (
            f"{len(drains)} callbacks for {n} puts — batching broken")
        assert q.take_high_water() >= 1
        assert q.take_high_water() == 0  # reset after take
    finally:
        io.stop()


def test_cross_shard_queue_survives_drain_exception():
    io = rpcmod.EventLoopThread(name="test-core2")
    seen = []

    def _drain(items):
        seen.extend(items)
        if items[0] == "boom":
            raise RuntimeError("drain_cb blew up")

    q = CrossShardQueue(io.loop, _drain, name="test")
    try:
        q.put("boom")
        deadline = time.monotonic() + 5
        while "boom" not in seen and time.monotonic() < deadline:
            time.sleep(0.01)
        q.put("after")  # the queue must keep working after a cb error
        while "after" not in seen and time.monotonic() < deadline:
            time.sleep(0.01)
        assert seen == ["boom", "after"]
    finally:
        io.stop()


# -------------------------------------------------------- per-op loop routing


class _RoutedHost(rpcmod.RpcHost):
    """Records which loop each handler ran on."""

    def __init__(self, shard_loop):
        self.rpc_op_loops = {"shard_op": shard_loop, "shard_note": shard_loop}
        self.notes = []

    async def rpc_shard_op(self, x=0):
        return {"x": x, "loop": id(asyncio.get_running_loop()),
                "thread": threading.get_ident()}

    async def rpc_main_op(self, x=0):
        return {"x": x, "loop": id(asyncio.get_running_loop()),
                "thread": threading.get_ident()}

    async def rpc_shard_note(self, x=0):
        self.notes.append((x, id(asyncio.get_running_loop())))


def test_routed_op_dispatches_on_owning_loop():
    """A frame for a shard-owned op must run its handler on the owning
    shard's loop (and still reply correctly over the serving loop's
    writer); unrouted ops stay on the serving loop."""
    serve = rpcmod.EventLoopThread(name="test-serve")
    shard = rpcmod.EventLoopThread(name="test-shard")
    cli_io = rpcmod.EventLoopThread(name="test-cli")
    host = _RoutedHost(shard.loop)
    server = rpcmod.RpcServer(host)
    client = None
    try:
        port = serve.run(server.start(), timeout=10)
        client = rpcmod.SyncRpcClient("127.0.0.1", port, cli_io)
        routed = client.call("shard_op", x=1, timeout=10)
        plain = client.call("main_op", x=2, timeout=10)
        assert routed["x"] == 1 and plain["x"] == 2
        assert routed["loop"] == id(shard.loop)
        assert plain["loop"] == id(serve.loop)
        assert routed["thread"] != plain["thread"]

        # oneway frames route too (the task-event ingest path)
        for i in range(5):
            client.oneway("shard_note", x=i)
        deadline = time.monotonic() + 5
        while len(host.notes) < 5 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert [x for x, _ in host.notes] == [0, 1, 2, 3, 4]
        assert all(lp == id(shard.loop) for _, lp in host.notes)
    finally:
        if client is not None:
            client.close()
        try:
            serve.run(server.stop(), timeout=10)
        except Exception:
            pass
        for elt in (serve, shard, cli_io):
            elt.stop()


def test_route_map_empty_means_serving_loop():
    serve = rpcmod.EventLoopThread(name="test-serve2")
    cli_io = rpcmod.EventLoopThread(name="test-cli2")

    class _Plain(rpcmod.RpcHost):
        async def rpc_echo(self, x=0):
            return {"x": x, "loop": id(asyncio.get_running_loop())}

    server = rpcmod.RpcServer(_Plain())
    client = None
    try:
        port = serve.run(server.start(), timeout=10)
        client = rpcmod.SyncRpcClient("127.0.0.1", port, cli_io)
        out = client.call("echo", x=9, timeout=10)
        assert out == {"x": 9, "loop": id(serve.loop)}
    finally:
        if client is not None:
            client.close()
        try:
            serve.run(server.stop(), timeout=10)
        except Exception:
            pass
        serve.stop()
        cli_io.stop()


# -------------------------------------------------------- HeadShards topology


def test_head_shards_topology_by_count():
    head_loop = asyncio.new_event_loop()
    try:
        compat = HeadShards(0, head_loop)
        assert not compat.sharded
        assert compat.task_events.loop is head_loop
        assert compat.telemetry.loop is head_loop
        assert not compat.task_events.own_thread
        assert compat.op_loops() == {}
        compat.stop()  # must not close the head loop it wrapped
        assert not head_loop.is_closed()

        shared = HeadShards(1, head_loop)
        try:
            assert shared.sharded
            assert shared.task_events.loop is shared.telemetry.loop
            assert shared.task_events.loop is not head_loop
            ops = shared.op_loops()
            assert ops["task_events"] is ops["heartbeat"]
        finally:
            shared.stop()

        two = HeadShards(2, head_loop)
        try:
            assert two.task_events.loop is not two.telemetry.loop
            ops = two.op_loops()
            assert ops["task_events"] is two.task_events.loop
            assert ops["trace_spans"] is two.task_events.loop
            assert ops["list_tasks"] is two.task_events.loop
            assert ops["heartbeat"] is two.telemetry.loop
            assert ops["timeseries"] is two.telemetry.loop
        finally:
            two.stop()
    finally:
        head_loop.close()


def test_run_sync_inline_and_cross_loop():
    shards = HeadShards(2, asyncio.new_event_loop())
    drv = rpcmod.EventLoopThread(name="test-drv")
    try:
        async def _from_foreign_loop():
            return await shards.task_events.run_sync(
                lambda: threading.get_ident())

        tid = asyncio.run_coroutine_threadsafe(
            _from_foreign_loop(), drv.loop).result(timeout=10)
        on_shard = asyncio.run_coroutine_threadsafe(
            shards.task_events.run_sync(lambda: threading.get_ident()),
            shards.task_events.loop).result(timeout=10)
        assert tid == on_shard  # both executed on the shard thread
        assert tid != threading.get_ident()
    finally:
        shards.stop()
        drv.stop()


# ------------------------------------------------------------------- e2e


def _head():
    import ray_tpu

    return ray_tpu.api._worker().head


@pytest.fixture
def sharded_cluster():
    import ray_tpu

    os.environ["RT_HEAD_INGEST_SHARDS"] = "2"
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RT_HEAD_INGEST_SHARDS", None)


@pytest.fixture
def single_loop_cluster():
    import ray_tpu

    os.environ["RT_HEAD_INGEST_SHARDS"] = "0"
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()
        os.environ.pop("RT_HEAD_INGEST_SHARDS", None)


def test_sharded_head_admits_10k_task_burst(sharded_cluster):
    """The acceptance e2e: 10k tasks through a 2-shard head complete
    flat, the head reports the sharded topology, and ZERO task events
    were dropped on the ingest inbox."""
    ray_tpu = sharded_cluster

    @ray_tpu.remote
    def unit(i):
        return i

    n = 10_000
    refs = [unit.remote(i) for i in range(n)]
    out = ray_tpu.get(refs, timeout=300)
    assert out == list(range(n))

    snap = _head().call("autoscaler_snapshot", timeout=30)
    sh = snap["shards"]
    assert sh["count"] == 2
    assert sh["planes"]["task_events"]["own_thread"]
    assert sh["planes"]["telemetry"]["own_thread"]
    assert sh["planes"]["task_events"]["dropped"] == 0

    # the event store saw the burst: every task reached a terminal
    # state (finished_total is monotonic — cap-trimming old records
    # must not deflate it)
    deadline = time.monotonic() + 30
    fin = 0
    while time.monotonic() < deadline:
        snap = _head().call("autoscaler_snapshot", timeout=30)
        fin = snap["signals"]["tasks_finished_total"]
        if fin >= n:
            break
        time.sleep(0.25)
    assert fin >= n
    assert snap["signals"]["task_events_version"] > 0

    # routed read path: list_tasks serves off the task-event shard
    tasks = _head().call("list_tasks", state="FINISHED", limit=10,
                         timeout=30)
    assert tasks


def test_single_loop_compat_mode(single_loop_cluster):
    """head_ingest_shards=0: same planes, same rpc surface, no extra
    threads — the upgrade-safety escape hatch."""
    ray_tpu = single_loop_cluster

    @ray_tpu.remote
    def unit(i):
        return i * 2

    n = 300
    out = ray_tpu.get([unit.remote(i) for i in range(n)], timeout=120)
    assert out == [i * 2 for i in range(n)]

    snap = _head().call("autoscaler_snapshot", timeout=30)
    sh = snap["shards"]
    assert sh["count"] == 0
    assert not sh["planes"]["task_events"]["own_thread"]
    assert sh["planes"]["task_events"]["dropped"] == 0
    assert _head().call("list_tasks", limit=5, timeout=30)
    # heartbeat-fed surfaces still flow on the single loop
    ts = _head().call("timeseries", timeout=30)
    assert isinstance(ts.get("series"), list)


# ------------------------------------------------------------- static scan


def test_no_bare_get_event_loop_anywhere():
    """Lock in the multi-loop cleanup: every loop lookup in the package
    must be ``asyncio.get_running_loop()``.  Bare ``get_event_loop()``
    silently creates a NEW loop on a non-main thread (and a deprecated
    implicit one on the main thread), which breaks the per-op loop
    routing the sharded head relies on — a regression here reintroduces
    cross-loop futures that never resolve."""
    import pathlib

    pkg = pathlib.Path(__file__).resolve().parent.parent / "ray_tpu"
    offenders = []
    for path in sorted(pkg.rglob("*.py")):
        for ln, line in enumerate(path.read_text().splitlines(), 1):
            if "get_event_loop(" in line and "get_running_loop(" not in line:
                offenders.append(f"{path.relative_to(pkg.parent)}:{ln}: "
                                 f"{line.strip()}")
    assert not offenders, (
        "bare asyncio.get_event_loop() found (use get_running_loop):\n"
        + "\n".join(offenders))
