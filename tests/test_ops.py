"""Kernel tests: ring attention (sequence parallel) and flash attention.

Green-field coverage (the reference has no SP/CP — SURVEY §5.7); the
correctness oracle is the dense reference attention.
"""

import numpy as np
import pytest

from tests.conftest import force_cpu_jax


def _qkv(jax, B=2, S=64, H=4, Hkv=2, D=16):
    import jax.numpy as jnp

    q = jax.random.normal(jax.random.PRNGKey(0), (B, S, H, D), dtype=jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (B, S, Hkv, D), dtype=jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (B, S, Hkv, D), dtype=jnp.float32)
    return q, k, v


def test_ring_attention_matches_dense():
    jax = force_cpu_jax()
    import jax.numpy as jnp

    from ray_tpu.models.llama import default_attention
    from ray_tpu.ops.ring_attention import ring_attention
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(dp=2, sp=4), devices=jax.devices()[:8])
    q, k, v = _qkv(jax)
    dense = default_attention(q, k, v, causal=True)
    with mesh:
        ring = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, head_axis=None)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_non_causal():
    jax = force_cpu_jax()
    from ray_tpu.models.llama import default_attention
    from ray_tpu.ops.ring_attention import ring_attention
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(sp=8), devices=jax.devices()[:8])
    q, k, v = _qkv(jax)
    dense = default_attention(q, k, v, causal=False)
    with mesh:
        ring = jax.jit(lambda q, k, v: ring_attention(
            q, k, v, mesh, causal=False, head_axis=None))(q, k, v)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_grad_matches_dense():
    jax = force_cpu_jax()
    import jax.numpy as jnp

    from ray_tpu.models.llama import default_attention
    from ray_tpu.ops.ring_attention import ring_attention
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(sp=4), devices=jax.devices()[:4])
    q, k, v = _qkv(jax, S=32)
    with mesh:
        g_ring = jax.jit(jax.grad(lambda q: ring_attention(
            q, k, v, mesh, head_axis=None).sum()))(q)
    g_dense = jax.grad(lambda q: default_attention(q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g_dense), np.asarray(g_ring),
                               atol=5e-5, rtol=5e-5)


def test_flash_attention_matches_dense():
    jax = force_cpu_jax()
    from ray_tpu.models.llama import default_attention
    from ray_tpu.ops.flash_attention import flash_attention

    q, k, v = _qkv(jax, S=128, D=64)
    dense = default_attention(q, k, v, causal=True)
    flash = flash_attention(q, k, v, True, 32, 32, True)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(flash),
                               atol=2e-5, rtol=2e-5)


def test_flash_attention_backward():
    jax = force_cpu_jax()
    from ray_tpu.models.llama import default_attention
    from ray_tpu.ops.flash_attention import flash_attention

    q, k, v = _qkv(jax, S=64, D=32)
    g1 = jax.grad(lambda q: flash_attention(q, k, v, True, 32, 32, True).sum())(q)
    g2 = jax.grad(lambda q: default_attention(q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               atol=2e-4, rtol=2e-4)


def test_default_attention_routes_long_prefill_through_flash(monkeypatch):
    """A/B equivalence for the length-threshold routing: at or above
    FLASH_PREFILL_MIN_SEQ (and a multiple of the flash block),
    default_attention must go through the Pallas flash kernel and agree
    with the dense math it replaces."""
    jax = force_cpu_jax()
    from ray_tpu.models import llama
    from ray_tpu.ops import flash_attention as fa

    calls = []
    real = fa.flash_attention

    def spy(q, k, v, *a, **kw):
        calls.append(tuple(q.shape))
        return real(q, k, v, *a, **kw)

    monkeypatch.setattr(fa, "flash_attention", spy)
    monkeypatch.setattr(llama, "FLASH_PREFILL_MIN_SEQ", 128)
    q, k, v = _qkv(jax, S=128, D=32)
    routed = llama.default_attention(q, k, v, causal=True)
    assert calls, "long causal prefill did not route through flash"
    dense = llama.dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(routed), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)
    # grad still traces through the routed path: flash carries a
    # dense-recompute custom_vjp that targets dense_attention directly
    # — if it routed back through default_attention this trace would
    # recurse forever.  (Backward NUMERICS are covered by
    # test_flash_attention_backward; tracing alone proves the wiring
    # without paying a second kernel compile.)
    jax.make_jaxpr(
        jax.grad(lambda q: llama.default_attention(q, k, v).sum()))(q)


def test_default_attention_short_or_unaligned_stays_dense(monkeypatch):
    """Below the threshold, non-causal, cross-attention (s != t), or
    non-128-multiple sequences keep the XLA dense path."""
    jax = force_cpu_jax()
    from ray_tpu.models import llama
    from ray_tpu.ops import flash_attention as fa

    def boom(*a, **kw):
        raise AssertionError("flash kernel must not be used here")

    monkeypatch.setattr(fa, "flash_attention", boom)
    monkeypatch.setattr(llama, "FLASH_PREFILL_MIN_SEQ", 128)
    q, k, v = _qkv(jax, S=64, D=32)
    llama.default_attention(q, k, v, causal=True)       # short
    llama.default_attention(q, k, v, causal=False)      # non-causal
    q2, k2, v2 = _qkv(jax, S=192, D=32)
    monkeypatch.setattr(llama, "FLASH_PREFILL_MIN_SEQ", 200)
    llama.default_attention(q2, k2, v2, causal=True)    # below threshold


def test_llama_trains_with_sequence_parallelism():
    jax = force_cpu_jax()
    import jax.numpy as jnp

    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import MeshSpec, make_mesh
    from ray_tpu.train.gspmd import build_llama_train_state

    mesh = make_mesh(MeshSpec(dp=2, sp=2, tp=2), devices=jax.devices()[:8])
    cfg = LlamaConfig.tiny()
    params, opt, step, _ = build_llama_train_state(cfg, mesh, batch_size=2,
                                                   seq_len=64)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 64), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    losses = []
    for _ in range(3):
        params, opt, loss = step(params, opt, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
