"""ray_tpu.data tests.

Mirrors the reference's Data test strategy (reference:
python/ray/data/tests/ — local cluster, deterministic block sizes).
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rtd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def test_range_count_take(cluster):
    ds = rtd.range(100, num_blocks=5)
    assert ds.count() == 100
    assert ds.num_blocks() == 5
    assert [r["id"] for r in ds.take(3)] == [0, 1, 2]


def test_map_batches_runs_in_tasks(cluster):
    ds = rtd.range(100, num_blocks=4).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    rows = ds.take_all()
    assert len(rows) == 100
    assert all(r["sq"] == r["id"] ** 2 for r in rows)


def test_fusion_map_filter_chain(cluster):
    ds = (rtd.range(50, num_blocks=4)
          .map(lambda r: {"v": r["id"] * 2})
          .filter(lambda r: r["v"] % 4 == 0)
          .map(lambda r: {"v": r["v"] + 1}))
    vals = sorted(r["v"] for r in ds.take_all())
    expect = sorted(v * 2 + 1 for v in range(50) if (v * 2) % 4 == 0)
    assert vals == expect


def test_flat_map(cluster):
    ds = rtd.from_items([1, 2, 3], num_blocks=2).flat_map(
        lambda r: [{"x": r["item"]}] * r["item"])
    assert ds.count() == 6


def test_iter_batches_sizes(cluster):
    ds = rtd.range(103, num_blocks=4)
    batches = list(ds.iter_batches(batch_size=25))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 103
    assert all(s == 25 for s in sizes[:-1])


def test_aggregates(cluster):
    ds = rtd.range(10, num_blocks=3)
    assert ds.sum("id") == 45.0
    assert ds.min("id") == 0.0
    assert ds.max("id") == 9.0
    assert ds.mean("id") == 4.5


def test_random_shuffle_preserves_multiset(cluster):
    ds = rtd.range(60, num_blocks=3).random_shuffle(seed=7)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(60))
    assert vals != list(range(60))  # actually shuffled


def test_repartition(cluster):
    ds = rtd.range(40, num_blocks=2).repartition(8)
    assert ds.num_blocks() == 8
    assert ds.count() == 40


def test_sort(cluster):
    ds = rtd.from_items([{"k": v} for v in [5, 3, 9, 1]]).sort("k")
    assert [r["k"] for r in ds.take_all()] == [1, 3, 5, 9]


def test_split_for_ingest(cluster):
    shards = rtd.range(40, num_blocks=4).split(2)
    assert len(shards) == 2
    assert shards[0].count() + shards[1].count() == 40


def test_parquet_roundtrip(cluster, tmp_path):
    ds = rtd.range(30, num_blocks=3)
    ds.write_parquet(str(tmp_path / "out"))
    back = rtd.read_parquet(str(tmp_path / "out"))
    assert back.count() == 30
    assert sorted(r["id"] for r in back.take_all()) == list(range(30))


def test_tensor_columns(cluster):
    arr = np.random.rand(20, 8).astype(np.float32)
    ds = rtd.from_numpy({"feat": arr, "label": np.arange(20)})
    batch = next(ds.iter_batches(batch_size=20))
    assert batch["feat"].shape == (20, 8)
    np.testing.assert_allclose(batch["feat"], arr)


def test_map_batches_actor_pool(cluster):
    """Class UDFs run on an actor pool; the instance is constructed once
    per actor and reused across batches (reference:
    actor_pool_map_operator.py)."""
    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    class AddBase:
        def __init__(self, base):
            import os

            self.base = base
            self.pid = os.getpid()
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return {"id": batch["id"] + self.base, "pid":
                    __import__("numpy").full(len(batch["id"]), self.pid)}

    ds = data.range(40, num_blocks=8).map_batches(
        AddBase, compute=ActorPoolStrategy(size=2),
        fn_constructor_args=(100,))
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(100, 140))
    # exactly 2 pool actors served all 8 blocks
    assert len({r["pid"] for r in rows}) == 2


def test_map_batches_class_requires_no_fn_args_for_plain_fn(cluster):
    from ray_tpu import data

    with pytest.raises(ValueError):
        data.range(4).map_batches(lambda b: b, fn_constructor_args=(1,))


def test_stream_budget_admission_curve():
    """Unit: the bytes budget bounds in-flight tasks between the count
    clamps, and the estimate tracks consumed block sizes."""
    from ray_tpu.data import dataset as ds_mod

    b = ds_mod._StreamBudget(budget_bytes=4 * 1024 * 1024)
    b._probe_at = float("inf")  # no store probes in a unit test
    b.est_bytes = 1024 * 1024.0
    launched = 0
    while b.admit():
        b.launched()
        launched += 1
    assert launched == 4  # 4MB budget / 1MB blocks
    b.consumed(1024 * 1024)
    assert b.admit()
    # huge blocks: admission floors at _WINDOW_MIN, never deadlocks
    big = ds_mod._StreamBudget(budget_bytes=1)
    big._probe_at = float("inf")
    assert big.admit()
    big.launched()
    assert big.admit()
    big.launched()
    assert not big.admit()  # _WINDOW_MIN reached, budget exhausted
    # tiny blocks: the count ceiling still bounds task fan-out
    tiny = ds_mod._StreamBudget(budget_bytes=1 << 40)
    tiny._probe_at = float("inf")
    tiny.est_bytes = 1.0
    for _ in range(ds_mod._WINDOW_MAX):
        assert tiny.admit()
        tiny.launched()
    assert not tiny.admit()


def test_stream_budget_is_per_execution(cluster):
    """Two concurrent iterations each get their OWN backpressure budget
    (VERDICT item 7: the former process-global 2-entry window cache made
    iterator A's refresh dictate iterator B's concurrency)."""
    from ray_tpu.data import dataset as ds_mod

    assert not hasattr(ds_mod, "_window_cache")  # the global is gone
    assert not hasattr(ds_mod, "_stream_window")
    made = []
    orig = rtd.Dataset._make_budget

    def tracking(self):
        b = orig(self)
        made.append(b)
        return b

    rtd.Dataset._make_budget = tracking
    try:
        it1 = rtd.range(40, num_blocks=8).map(lambda r: r).iter_blocks()
        it2 = rtd.range(40, num_blocks=8).map(lambda r: r).iter_blocks()
        # interleave: both generators live at once
        next(it1), next(it2), next(it1), next(it2)
        for it in (it1, it2):
            for _ in it:
                pass
    finally:
        rtd.Dataset._make_budget = orig
    assert len(made) == 2
    assert made[0] is not made[1]


def test_stream_budget_bounds_inflight_bytes(cluster):
    """Streaming a dataset far larger than the budget keeps launched-
    but-unconsumed blocks (the object-store occupancy the iteration
    adds) bounded by the BYTES budget, not by the dataset's length —
    the former executor launched a fixed 2 chunks (half the dataset
    here) ahead regardless of block size."""
    from ray_tpu.data import dataset as ds_mod

    rows_per_block = 50 * 1024
    block_bytes = 8 * rows_per_block  # int64 column
    ds = rtd.range(32 * rows_per_block, num_blocks=32).map_batches(
        lambda b: {"id": b["id"]})
    budget = ds_mod._StreamBudget(budget_bytes=4 * block_bytes)
    budget._probe_at = float("inf")
    budget.est_bytes = float(block_bytes)  # skip the warm-up estimate
    peaks = []
    orig_launched = ds_mod._StreamBudget.launched

    def peak_launched(self):
        orig_launched(self)
        peaks.append(self.inflight)

    ds._make_budget = lambda: budget
    ds_mod._StreamBudget.launched = peak_launched
    try:
        n = sum(1 for _ in ds.iter_blocks())
    finally:
        ds_mod._StreamBudget.launched = orig_launched
    assert n == 32
    # 4-block budget, chunk granularity 2: peak launched-unconsumed is
    # budget + chunk - 1 = 5 blocks; without the budget the executor
    # would run 2 chunks of 8 (16 blocks) ahead
    assert max(peaks) <= 6, peaks
    assert budget.inflight == 0


def test_explain_and_stats(cluster):
    from ray_tpu import data

    ds = data.range(20, num_blocks=4).map(lambda r: r).filter(
        lambda r: r["id"] % 2 == 0)
    plan = ds.explain()
    assert "Source[4 blocks]" in plan and "map" in plan and "filter" in plan
    assert ds.count() == 10
    stats = ds.stats()
    assert stats["blocks"] == 4 and stats["rows"] == 10
    assert stats["wall_s"] > 0


def test_distributed_sort_multiblock(cluster):
    """Sample-based range-partition sort: result blocks are ordered
    ranges — no driver-side row merge (reference:
    _internal/planner/exchange/sort_task_spec.py)."""
    import random as _r

    vals = list(range(200))
    _r.Random(7).shuffle(vals)
    ds = rtd.from_items([{"v": v} for v in vals], num_blocks=6).sort("v")
    assert [r["v"] for r in ds.take_all()] == list(range(200))
    # block count preserved (one block per range, not one driver blob)
    assert ds.num_blocks() == 6

    desc = rtd.from_items([{"v": v} for v in vals],
                          num_blocks=5).sort("v", descending=True)
    assert [r["v"] for r in desc.take_all()] == list(range(199, -1, -1))


def test_sort_with_duplicate_keys(cluster):
    rows = [{"k": i % 4, "p": i} for i in range(40)]
    ds = rtd.from_items(rows, num_blocks=4).sort("k")
    ks = [r["k"] for r in ds.take_all()]
    assert ks == sorted(ks)
    assert len(ks) == 40


def test_groupby_aggregates(cluster):
    """Distributed hash-partitioned groupby (reference:
    grouped_data.py:36)."""
    rows = [{"g": f"k{i % 5}", "x": float(i)} for i in range(100)]
    ds = rtd.from_items(rows, num_blocks=8)

    counts = {r["g"]: r["count()"] for r in ds.groupby("g").count().take_all()}
    assert counts == {f"k{i}": 20 for i in range(5)}

    sums = {r["g"]: r["sum(x)"] for r in ds.groupby("g").sum("x").take_all()}
    assert sums["k0"] == sum(float(i) for i in range(0, 100, 5))

    means = {r["g"]: r["mean(x)"]
             for r in ds.groupby("g").mean("x").take_all()}
    assert abs(means["k1"] - (sum(range(1, 100, 5)) / 20)) < 1e-9

    multi = ds.groupby("g").aggregate(("min", "x"), ("max", "x")).take_all()
    m = {r["g"]: (r["min(x)"], r["max(x)"]) for r in multi}
    assert m["k2"] == (2.0, 97.0)


def test_groupby_canonicalizes_equal_keys(cluster):
    """Keys equal under == but with different reprs (2 vs 2.0 vs
    np.int64(2), True vs 1) must land in ONE partition and emit ONE
    aggregate row — repr-hash partitioning used to split them."""
    # two rows per variant so from_items(num_blocks=8) gives each repr
    # its own TYPE-HOMOGENEOUS block (Arrow blocks can't mix bool/int),
    # and equal keys genuinely arrive from different blocks
    variants = [2, 2.0, True, 1, 2.5, 0, False, np.float64(2.5)]
    rows = [{"g": v, "x": 1.0} for v in variants for _ in range(2)]
    ds = rtd.from_items(rows, num_blocks=8)
    out = ds.groupby("g").count().take_all()
    counts = {r["g"]: r["count()"] for r in out}
    assert len(out) == len(counts), f"duplicate group rows: {out}"
    assert counts == {2: 4, 1: 4, 0: 4, 2.5: 4}
    # canonical key lands in the output row: integral floats report int
    assert all(isinstance(r["g"], (int, float)) for r in out)


def test_groupby_rejects_unsupported_key_types(cluster):
    from ray_tpu.data.dataset import _canon_key

    with pytest.raises(TypeError, match="unsupported groupby key type"):
        _canon_key({"a": 1})
    with pytest.raises(TypeError, match="NaN"):
        _canon_key(float("nan"))
    # supported types pass through canonically
    assert _canon_key(np.int32(7)) == 7
    assert _canon_key(True) == 1 and _canon_key(True) is not True
    assert _canon_key(3.0) == 3 and isinstance(_canon_key(3.0), int)
    assert _canon_key(None) is None and _canon_key(b"k") == b"k"
    # sequence keys canonicalize element-wise to a hashable tuple;
    # Arrow list columns round-trip tuple keys as lists, so both forms
    # must share one canonical value
    assert _canon_key((1, 2.0)) == (1, 2)
    assert _canon_key([1, 2]) == _canon_key((1, 2.0))


def test_groupby_sequence_keys(cluster):
    """Homogeneous tuple keys (stored by Arrow as list columns, read
    back as Python lists) group correctly across blocks."""
    rows = [{"g": (i % 2, i % 2), "x": 1.0} for i in range(12)]
    ds = rtd.from_items(rows, num_blocks=4)
    out = ds.groupby("g").count().take_all()
    counts = {tuple(r["g"]): r["count()"] for r in out}
    assert counts == {(0, 0): 6, (1, 1): 6}


def test_groupby_map_groups(cluster):
    rows = [{"g": i % 3, "x": i} for i in range(30)]
    ds = rtd.from_items(rows, num_blocks=5)

    def summarize(group_rows):
        g = group_rows[0]["g"]
        return [{"g": g, "n": len(group_rows),
                 "total": sum(r["x"] for r in group_rows)}]

    out = {r["g"]: (r["n"], r["total"])
           for r in ds.groupby("g").map_groups(summarize).take_all()}
    assert out[0] == (10, sum(range(0, 30, 3)))
    assert out[1] == (10, sum(range(1, 30, 3)))


def test_logical_plan_rewrite(cluster):
    """The planner seam: logical ops fuse via the rewrite rule and
    explain() shows both plans (reference: rules/operator_fusion.py)."""
    from ray_tpu.data import logical

    ds = rtd.range(10, num_blocks=2).map(lambda r: r).filter(
        lambda r: True).flat_map(lambda r: [r])
    assert len(ds._logical) == 3
    optimized = logical.optimize(ds._logical)
    assert len(optimized) == 1 and optimized[0].name == "fused_map"
    assert len(optimized[0].payload) == 3  # one task runs all three
    plan = ds.explain()
    assert "logical:" in plan and "Fused[" in plan


def test_limit_pushdown_rule_units():
    """LimitPushdown: adjacent limits merge to the min; a limit hops
    left past 1:1 maps (then merges) but never past filter/flat_map/
    map_batches (reference: rules/limit_pushdown.py)."""
    from ray_tpu.data.dataset import _Op
    from ray_tpu.data.logical import LimitPushdown, LogicalOp

    rule = LimitPushdown()

    def names(ops):
        return [(o.name, o.payload if o.name == "limit" else None)
                for o in ops]

    # merge: limit(10).limit(5) -> limit(5)
    out = rule.apply([LogicalOp("limit", 10), LogicalOp("limit", 5)])
    assert names(out) == [("limit", 5)]
    # hop + merge: limit(10).map.limit(5) -> limit(5).map
    out = rule.apply([LogicalOp("limit", 10),
                      LogicalOp("map", _Op("map")),
                      LogicalOp("limit", 5)])
    assert names(out) == [("limit", 5), ("map", None)]
    # filter blocks the hop
    out = rule.apply([LogicalOp("filter", _Op("filter")),
                      LogicalOp("limit", 7)])
    assert names(out) == [("filter", None), ("limit", 7)]
    # map_batches can change row counts: no hop
    out = rule.apply([LogicalOp("map_batches", _Op("map_batches")),
                      LogicalOp("limit", 3)])
    assert names(out) == [("map_batches", None), ("limit", 3)]


def test_limit_stops_launching_block_tasks(cluster, tmp_path):
    """limit(n)/take(n) must stop LAUNCHING block tasks once n rows
    exist instead of materializing the whole dataset on the driver —
    each executed block task drops a marker file, and most of the 24
    source blocks must never run (VERDICT weak #5)."""
    marker_dir = str(tmp_path / "ran")
    os.makedirs(marker_dir, exist_ok=True)

    # a FILTER keeps the marker op distributed: LimitPushdown hops a
    # limit over 1:1 maps, and a hopped marker would run driver-side on
    # the already-capped rows — passing even if early-stop regressed
    def touch(row):
        import os as _os
        import uuid as _uuid

        open(_os.path.join(marker_dir, _uuid.uuid4().hex), "w").close()
        return True

    ds = rtd.range(240, num_blocks=24).filter(touch)
    rows = ds.take(10)
    assert [r["id"] for r in rows] == list(range(10))
    # 10 rows fit in the first block; the 2-deep launch lookahead may
    # run a couple more blocks, but never anything close to all 24
    ran = len(os.listdir(marker_dir)) / 10  # 10 rows per block
    assert ran <= 4, f"{ran} block tasks ran for a 10-row take"


def test_trailing_limit_stops_launching(cluster, tmp_path):
    """A satisfied TRAILING limit must also stop the executor — not
    just the first one: limit(100).filter.limit(5) needs ~1 block of
    input, not the 10 blocks the first limit would allow.  The FILTER
    between the limits is load-bearing: without it LimitPushdown merges
    them into one limit(5) and the trailing-limit path never runs."""
    marker_dir = str(tmp_path / "ran")
    os.makedirs(marker_dir, exist_ok=True)

    def touch(row):
        import os as _os
        import uuid as _uuid

        open(_os.path.join(marker_dir, _uuid.uuid4().hex), "w").close()
        return True

    ds = (rtd.range(240, num_blocks=24).filter(touch)
          .limit(100).filter(lambda r: True).limit(5))
    assert [r["id"] for r in ds.take_all()] == list(range(5))
    ran = len(os.listdir(marker_dir)) / 10  # 10 rows per block
    assert ran <= 4, f"{ran} block tasks ran for a trailing take(5)"


def test_limit_semantics_across_ops(cluster):
    """Row results match eager semantics whatever side of the limit the
    ops land on."""
    ds = rtd.range(100, num_blocks=10)
    assert [r["id"] for r in ds.limit(7).take_all()] == list(range(7))
    # map after limit (pushdown hops it): first 5 doubled
    out = ds.limit(5).map(lambda r: {"id": r["id"] * 2}).take_all()
    assert [r["id"] for r in out] == [0, 2, 4, 6, 8]
    # filter before limit: first 4 even ids
    out = ds.filter(lambda r: r["id"] % 2 == 0).limit(4).take_all()
    assert [r["id"] for r in out] == [0, 2, 4, 6]
    # limit then filter (filter stays after the cap)
    out = ds.limit(10).filter(lambda r: r["id"] % 2 == 0).take_all()
    assert [r["id"] for r in out] == [0, 2, 4, 6, 8]
    # two limits separated by a filter: both caps enforced
    out = (ds.limit(10).filter(lambda r: r["id"] < 8)
           .limit(3).take_all())
    assert [r["id"] for r in out] == [0, 1, 2]
    # downstream exchange ops still execute a limited plan
    assert ds.limit(6).count() == 6
    assert sorted(r["id"] for r in
                  ds.limit(6).random_shuffle(seed=1).take_all()) \
        == list(range(6))
