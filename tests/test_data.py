"""ray_tpu.data tests.

Mirrors the reference's Data test strategy (reference:
python/ray/data/tests/ — local cluster, deterministic block sizes).
"""

import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rtd


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def test_range_count_take(cluster):
    ds = rtd.range(100, num_blocks=5)
    assert ds.count() == 100
    assert ds.num_blocks() == 5
    assert [r["id"] for r in ds.take(3)] == [0, 1, 2]


def test_map_batches_runs_in_tasks(cluster):
    ds = rtd.range(100, num_blocks=4).map_batches(
        lambda b: {"id": b["id"], "sq": b["id"] ** 2})
    rows = ds.take_all()
    assert len(rows) == 100
    assert all(r["sq"] == r["id"] ** 2 for r in rows)


def test_fusion_map_filter_chain(cluster):
    ds = (rtd.range(50, num_blocks=4)
          .map(lambda r: {"v": r["id"] * 2})
          .filter(lambda r: r["v"] % 4 == 0)
          .map(lambda r: {"v": r["v"] + 1}))
    vals = sorted(r["v"] for r in ds.take_all())
    expect = sorted(v * 2 + 1 for v in range(50) if (v * 2) % 4 == 0)
    assert vals == expect


def test_flat_map(cluster):
    ds = rtd.from_items([1, 2, 3], num_blocks=2).flat_map(
        lambda r: [{"x": r["item"]}] * r["item"])
    assert ds.count() == 6


def test_iter_batches_sizes(cluster):
    ds = rtd.range(103, num_blocks=4)
    batches = list(ds.iter_batches(batch_size=25))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 103
    assert all(s == 25 for s in sizes[:-1])


def test_aggregates(cluster):
    ds = rtd.range(10, num_blocks=3)
    assert ds.sum("id") == 45.0
    assert ds.min("id") == 0.0
    assert ds.max("id") == 9.0
    assert ds.mean("id") == 4.5


def test_random_shuffle_preserves_multiset(cluster):
    ds = rtd.range(60, num_blocks=3).random_shuffle(seed=7)
    vals = [r["id"] for r in ds.take_all()]
    assert sorted(vals) == list(range(60))
    assert vals != list(range(60))  # actually shuffled


def test_repartition(cluster):
    ds = rtd.range(40, num_blocks=2).repartition(8)
    assert ds.num_blocks() == 8
    assert ds.count() == 40


def test_sort(cluster):
    ds = rtd.from_items([{"k": v} for v in [5, 3, 9, 1]]).sort("k")
    assert [r["k"] for r in ds.take_all()] == [1, 3, 5, 9]


def test_split_for_ingest(cluster):
    shards = rtd.range(40, num_blocks=4).split(2)
    assert len(shards) == 2
    assert shards[0].count() + shards[1].count() == 40


def test_parquet_roundtrip(cluster, tmp_path):
    ds = rtd.range(30, num_blocks=3)
    ds.write_parquet(str(tmp_path / "out"))
    back = rtd.read_parquet(str(tmp_path / "out"))
    assert back.count() == 30
    assert sorted(r["id"] for r in back.take_all()) == list(range(30))


def test_tensor_columns(cluster):
    arr = np.random.rand(20, 8).astype(np.float32)
    ds = rtd.from_numpy({"feat": arr, "label": np.arange(20)})
    batch = next(ds.iter_batches(batch_size=20))
    assert batch["feat"].shape == (20, 8)
    np.testing.assert_allclose(batch["feat"], arr)


def test_map_batches_actor_pool(cluster):
    """Class UDFs run on an actor pool; the instance is constructed once
    per actor and reused across batches (reference:
    actor_pool_map_operator.py)."""
    from ray_tpu import data
    from ray_tpu.data import ActorPoolStrategy

    class AddBase:
        def __init__(self, base):
            import os

            self.base = base
            self.pid = os.getpid()
            self.calls = 0

        def __call__(self, batch):
            self.calls += 1
            return {"id": batch["id"] + self.base, "pid":
                    __import__("numpy").full(len(batch["id"]), self.pid)}

    ds = data.range(40, num_blocks=8).map_batches(
        AddBase, compute=ActorPoolStrategy(size=2),
        fn_constructor_args=(100,))
    rows = ds.take_all()
    assert sorted(r["id"] for r in rows) == list(range(100, 140))
    # exactly 2 pool actors served all 8 blocks
    assert len({r["pid"] for r in rows}) == 2


def test_map_batches_class_requires_no_fn_args_for_plain_fn(cluster):
    from ray_tpu import data

    with pytest.raises(ValueError):
        data.range(4).map_batches(lambda b: b, fn_constructor_args=(1,))


def test_stream_window_is_resource_aware(cluster):
    from ray_tpu.data import dataset as ds_mod

    ds_mod._window_cache[0] = 0.0  # drop the TTL cache
    w = ds_mod._stream_window()
    assert ds_mod._WINDOW_MIN <= w <= ds_mod._WINDOW_MAX
    # 4-CPU test cluster: 2 tasks per CPU
    assert w == 8


def test_explain_and_stats(cluster):
    from ray_tpu import data

    ds = data.range(20, num_blocks=4).map(lambda r: r).filter(
        lambda r: r["id"] % 2 == 0)
    plan = ds.explain()
    assert "Source[4 blocks]" in plan and "map" in plan and "filter" in plan
    assert ds.count() == 10
    stats = ds.stats()
    assert stats["blocks"] == 4 and stats["rows"] == 10
    assert stats["wall_s"] > 0


def test_distributed_sort_multiblock(cluster):
    """Sample-based range-partition sort: result blocks are ordered
    ranges — no driver-side row merge (reference:
    _internal/planner/exchange/sort_task_spec.py)."""
    import random as _r

    vals = list(range(200))
    _r.Random(7).shuffle(vals)
    ds = rtd.from_items([{"v": v} for v in vals], num_blocks=6).sort("v")
    assert [r["v"] for r in ds.take_all()] == list(range(200))
    # block count preserved (one block per range, not one driver blob)
    assert ds.num_blocks() == 6

    desc = rtd.from_items([{"v": v} for v in vals],
                          num_blocks=5).sort("v", descending=True)
    assert [r["v"] for r in desc.take_all()] == list(range(199, -1, -1))


def test_sort_with_duplicate_keys(cluster):
    rows = [{"k": i % 4, "p": i} for i in range(40)]
    ds = rtd.from_items(rows, num_blocks=4).sort("k")
    ks = [r["k"] for r in ds.take_all()]
    assert ks == sorted(ks)
    assert len(ks) == 40


def test_groupby_aggregates(cluster):
    """Distributed hash-partitioned groupby (reference:
    grouped_data.py:36)."""
    rows = [{"g": f"k{i % 5}", "x": float(i)} for i in range(100)]
    ds = rtd.from_items(rows, num_blocks=8)

    counts = {r["g"]: r["count()"] for r in ds.groupby("g").count().take_all()}
    assert counts == {f"k{i}": 20 for i in range(5)}

    sums = {r["g"]: r["sum(x)"] for r in ds.groupby("g").sum("x").take_all()}
    assert sums["k0"] == sum(float(i) for i in range(0, 100, 5))

    means = {r["g"]: r["mean(x)"]
             for r in ds.groupby("g").mean("x").take_all()}
    assert abs(means["k1"] - (sum(range(1, 100, 5)) / 20)) < 1e-9

    multi = ds.groupby("g").aggregate(("min", "x"), ("max", "x")).take_all()
    m = {r["g"]: (r["min(x)"], r["max(x)"]) for r in multi}
    assert m["k2"] == (2.0, 97.0)


def test_groupby_map_groups(cluster):
    rows = [{"g": i % 3, "x": i} for i in range(30)]
    ds = rtd.from_items(rows, num_blocks=5)

    def summarize(group_rows):
        g = group_rows[0]["g"]
        return [{"g": g, "n": len(group_rows),
                 "total": sum(r["x"] for r in group_rows)}]

    out = {r["g"]: (r["n"], r["total"])
           for r in ds.groupby("g").map_groups(summarize).take_all()}
    assert out[0] == (10, sum(range(0, 30, 3)))
    assert out[1] == (10, sum(range(1, 30, 3)))


def test_logical_plan_rewrite(cluster):
    """The planner seam: logical ops fuse via the rewrite rule and
    explain() shows both plans (reference: rules/operator_fusion.py)."""
    from ray_tpu.data import logical

    ds = rtd.range(10, num_blocks=2).map(lambda r: r).filter(
        lambda r: True).flat_map(lambda r: [r])
    assert len(ds._logical) == 3
    optimized = logical.optimize(ds._logical)
    assert len(optimized) == 1 and optimized[0].name == "fused_map"
    assert len(optimized[0].payload) == 3  # one task runs all three
    plan = ds.explain()
    assert "logical:" in plan and "Fused[" in plan
