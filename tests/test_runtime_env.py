"""Runtime environment tests (reference: python/ray/tests/test_runtime_env*.py)."""

import os

import pytest

import ray_tpu
from ray_tpu._private.runtime_env import RuntimeEnvError


def test_env_vars_task_and_actor(local_cluster):
    @ray_tpu.remote(runtime_env={"env_vars": {"RT_TEST_FLAVOR": "mango"}})
    def read():
        import os as _os

        return _os.environ.get("RT_TEST_FLAVOR")

    assert ray_tpu.get(read.remote(), timeout=60) == "mango"

    @ray_tpu.remote(runtime_env={"env_vars": {"RT_TEST_FLAVOR": "lime"}})
    class Reader:
        def read(self):
            import os as _os

            return _os.environ.get("RT_TEST_FLAVOR")

    r = Reader.remote()
    assert ray_tpu.get(r.read.remote(), timeout=60) == "lime"


def test_env_workers_are_pooled_separately(local_cluster):
    @ray_tpu.remote
    def plain_pid():
        import os as _os

        return _os.getpid()

    @ray_tpu.remote(runtime_env={"env_vars": {"K": "1"}})
    def env_pid():
        import os as _os

        return _os.getpid()

    plain = ray_tpu.get(plain_pid.remote(), timeout=60)
    env1 = ray_tpu.get(env_pid.remote(), timeout=60)
    env2 = ray_tpu.get(env_pid.remote(), timeout=60)
    assert plain != env1          # env worker is a different process
    assert env1 == env2           # same env reuses the pooled worker
    assert ray_tpu.get(plain_pid.remote(), timeout=60) == plain


def test_working_dir(local_cluster, tmp_path):
    proj = tmp_path / "proj"
    proj.mkdir()
    (proj / "data.txt").write_text("payload-42")
    (proj / "helper.py").write_text("def val():\n    return 'from-helper'\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(proj)})
    def use():
        import helper  # importable: working_dir is on sys.path

        with open("data.txt") as f:  # cwd IS the working_dir
            return f.read(), helper.val()

    data, helper_val = ray_tpu.get(use.remote(), timeout=60)
    assert data == "payload-42"
    assert helper_val == "from-helper"


def test_py_modules(local_cluster, tmp_path):
    mod_dir = tmp_path / "libs"
    pkg = mod_dir / "mylib"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("ANSWER = 99\n")

    @ray_tpu.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use():
        import mylib

        return mylib.ANSWER

    assert ray_tpu.get(use.remote(), timeout=60) == 99


def test_pip_gate(local_cluster):
    @ray_tpu.remote(runtime_env={"pip": ["numpy"]})
    def ok():
        import numpy

        return numpy.__name__

    assert ray_tpu.get(ok.remote(), timeout=60) == "numpy"

    @ray_tpu.remote(runtime_env={"pip": ["surely-not-installed-xyz"]})
    def nope():
        return 1

    with pytest.raises(RuntimeEnvError):
        nope.remote()


def test_unknown_key_rejected(local_cluster):
    @ray_tpu.remote(runtime_env={"container": {"image": "x"}})
    def f():
        return 1

    with pytest.raises(RuntimeEnvError):
        f.remote()


def test_job_level_runtime_env(tmp_path):
    """init(runtime_env=...) applies to every task; task-level overrides
    merge key-wise."""
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024,
                 runtime_env={"env_vars": {"JOB_VAR": "base",
                                           "SHARED": "job"}})
    try:
        @ray_tpu.remote
        def read():
            import os as _os

            return _os.environ.get("JOB_VAR"), _os.environ.get("SHARED")

        @ray_tpu.remote(runtime_env={"env_vars": {"SHARED": "task"}})
        def override():
            import os as _os

            return _os.environ.get("JOB_VAR"), _os.environ.get("SHARED")

        assert ray_tpu.get(read.remote(), timeout=60) == ("base", "job")
        assert ray_tpu.get(override.remote(), timeout=60) == ("base", "task")
    finally:
        ray_tpu.shutdown()


def test_nested_task_inherits_env(local_cluster):
    """A task submitted from inside an env'd task inherits that env
    (reference: parent runtime_env inheritance)."""
    @ray_tpu.remote(runtime_env={"env_vars": {"NEST": "deep"}})
    def outer():
        @ray_tpu.remote
        def inner():
            import os as _os

            return _os.environ.get("NEST")

        return ray_tpu.get(inner.remote(), timeout=60)

    assert ray_tpu.get(outer.remote(), timeout=120) == "deep"
