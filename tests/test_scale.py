"""Scale smoke tests — miniature versions of the reference's
scalability envelope (reference: release/benchmarks/README.md — queued
tasks, many actors, many objects), escalated toward the reference
numbers now that dispatch is batched (PR 8): 50k tasks queued at once,
a single 10k-ref get, 200 concurrent actors (the actor envelope runs
under the `slow` marker; tier-1 keeps a 24-actor version sized for the
870s budget)."""

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024,
                 # one 50k burst ahead of a grant means lease requests
                 # can queue behind ~2 minutes of worker spawns
                 _system_config={"worker_lease_timeout_ms": 240_000})
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


@pytest.mark.slow
def test_many_queued_tasks_drain(cluster):
    """Tens of thousands of tasks queued at once all complete
    (reference: '1M tasks queued on one node' scaled to the box) — the
    batched submit path (one push_tasks frame per lease pass, batched
    lease asks) is what makes this a queueing test instead of a
    frame-count test.  Moved behind `slow` with the 50k envelope (which
    subsumes it) when the LLM serving tests joined tier-1 — the 870s
    budget was at ~796s; tier-1 keeps the 10k-ref single-get and the
    24-actor envelope below as its scale gates."""
    @ray_tpu.remote
    def unit(i):
        return i

    n = 10_000
    refs = [unit.remote(i) for i in range(n)]
    out = ray_tpu.get(refs, timeout=600)
    assert out == list(range(n))


@pytest.mark.slow
def test_many_queued_tasks_envelope(cluster):
    """The 50k-queued-tasks reference point (VERDICT weak #7)."""
    @ray_tpu.remote
    def unit(i):
        return i

    n = 50_000
    refs = [unit.remote(i) for i in range(n)]
    out = ray_tpu.get(refs, timeout=600)
    assert out == list(range(n))


def test_one_get_of_10k_refs(cluster):
    """One ray_tpu.get resolving 10k refs (reference: '10k plasma
    objects in one ray.get'): the vectorized driver get must resolve
    the batch in O(owners) frames, not O(refs)."""
    n = 10_000
    refs = [ray_tpu.put(i) for i in range(n)]
    assert ray_tpu.get(refs, timeout=300) == list(range(n))
    # the owner's reference table tracked every live ref through it
    summary = ray_tpu.api._worker().memory_summary(limit=20_000)
    assert summary["num_owned"] >= n


def test_many_actors(cluster):
    """Dozens of concurrent actors each serving calls — tier-1 sized
    (worker spawn on the CI box is ~0.7s/proc gated at
    worker_startup_parallelism; 24 fits the budget, the 200-actor
    envelope lives in test_many_actors_envelope below)."""
    @ray_tpu.remote
    class Cell:
        def __init__(self, base):
            self.base = base

        def bump(self, x):
            return self.base + x

    n = 24
    actors = [Cell.remote(i) for i in range(n)]
    refs = [a.bump.remote(j) for j in range(5) for a in actors]
    out = ray_tpu.get(refs, timeout=600)
    assert sum(out) == sum(i + j for j in range(5) for i in range(n))
    for a in actors:
        ray_tpu.kill(a)


@pytest.mark.slow
def test_many_actors_envelope(cluster):
    """The 200-actor reference envelope (reference: many_actors).
    Worker spawn dominates (~135s on the 2-CPU box with the spawn gate
    at parallelism 4), so this runs under the slow marker."""
    @ray_tpu.remote
    class Cell:
        def __init__(self, base):
            self.base = base

        def bump(self, x):
            return self.base + x

    n = 200
    actors = [Cell.remote(i) for i in range(n)]
    refs = [a.bump.remote(1) for a in actors]
    out = ray_tpu.get(refs, timeout=600)
    assert sum(out) == sum(i + 1 for i in range(n))
    for a in actors:
        ray_tpu.kill(a)


def test_deep_nested_submission(cluster):
    """Tasks submitting tasks several levels deep (owner chains,
    borrowed refs) complete without deadlock."""
    @ray_tpu.remote
    def descend(depth):
        if depth == 0:
            return 1
        return 1 + ray_tpu.get(descend.remote(depth - 1), timeout=120)

    assert ray_tpu.get(descend.remote(6), timeout=300) == 7


def test_async_task_put_and_nested_get(cluster):
    """An async task body (running on the shared loop thread) can put
    objects (unique IDs via the per-coroutine exec shadow) and block on
    nested tasks (the blocked-worker release still fires)."""
    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    async def parent(i):
        import asyncio as _a

        await _a.sleep(0.01)
        ref = ray_tpu.put({"i": i})            # put from a coroutine
        nested = ray_tpu.get(child.remote(i), timeout=120)
        return ray_tpu.get(ref, timeout=30)["i"], nested

    out = ray_tpu.get([parent.remote(i) for i in range(6)], timeout=300)
    assert out == [(i, i + 1) for i in range(6)]
