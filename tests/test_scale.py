"""Scale smoke tests — miniature versions of the reference's
scalability envelope (reference: release/benchmarks/README.md — queued
tasks, many actors, many objects), sized for a small CI box."""

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def test_many_queued_tasks_drain(cluster):
    """Thousands of tasks queued at once all complete (reference: '1M
    tasks queued on one node' scaled down)."""
    @ray_tpu.remote
    def unit(i):
        return i

    n = 5000
    refs = [unit.remote(i) for i in range(n)]
    out = ray_tpu.get(refs, timeout=300)
    assert out == list(range(n))


def test_many_small_objects(cluster):
    """Thousands of puts resolved in one get (reference: '10k plasma
    objects in one ray.get')."""
    refs = [ray_tpu.put(i) for i in range(3000)]
    assert ray_tpu.get(refs, timeout=120) == list(range(3000))


def test_many_actors(cluster):
    """Dozens of concurrent actors each serving calls (reference:
    'many_actors' scaled down)."""
    @ray_tpu.remote
    class Cell:
        def __init__(self, base):
            self.base = base

        def bump(self, x):
            return self.base + x

    actors = [Cell.remote(i) for i in range(24)]
    refs = [a.bump.remote(j) for j in range(5) for a in actors]
    out = ray_tpu.get(refs, timeout=300)
    assert sum(out) == sum(i + j for j in range(5) for i in range(24))
    for a in actors:
        ray_tpu.kill(a)


def test_deep_nested_submission(cluster):
    """Tasks submitting tasks several levels deep (owner chains,
    borrowed refs) complete without deadlock."""
    @ray_tpu.remote
    def descend(depth):
        if depth == 0:
            return 1
        return 1 + ray_tpu.get(descend.remote(depth - 1), timeout=120)

    assert ray_tpu.get(descend.remote(6), timeout=300) == 7


def test_async_task_put_and_nested_get(cluster):
    """An async task body (running on the shared loop thread) can put
    objects (unique IDs via the per-coroutine exec shadow) and block on
    nested tasks (the blocked-worker release still fires)."""
    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    async def parent(i):
        import asyncio as _a

        await _a.sleep(0.01)
        ref = ray_tpu.put({"i": i})            # put from a coroutine
        nested = ray_tpu.get(child.remote(i), timeout=120)
        return ray_tpu.get(ref, timeout=30)["i"], nested

    out = ray_tpu.get([parent.remote(i) for i in range(6)], timeout=300)
    assert out == [(i, i + 1) for i in range(6)]
