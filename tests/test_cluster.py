"""Multi-node scheduling + fault tolerance tests.

Mirrors the reference's multi-node-without-hardware strategy
(reference: python/ray/cluster_utils.py Cluster + chaos helpers,
SURVEY §4.2): several node agents as processes on one machine, tasks
spread across them, nodes killed mid-run.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def three_node_cluster():
    cluster = Cluster(head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, resources={"nodeA": 1})
    cluster.add_node(num_cpus=2, resources={"nodeB": 1})
    ray_tpu.init(address=cluster.address)
    try:
        yield cluster
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


def test_schedule_on_remote_nodes(three_node_cluster):
    @ray_tpu.remote(resources={"nodeA": 0.1})
    def on_a():
        return os.getpid()

    @ray_tpu.remote(resources={"nodeB": 0.1})
    def on_b():
        return os.getpid()

    pid_a = ray_tpu.get(on_a.remote(), timeout=60)
    pid_b = ray_tpu.get(on_b.remote(), timeout=60)
    assert pid_a != pid_b
    assert ray_tpu.cluster_resources().get("CPU") == 6.0


def test_cross_node_object_transfer(three_node_cluster):
    import numpy as np

    @ray_tpu.remote(resources={"nodeA": 0.1})
    def produce():
        return np.arange(500_000, dtype=np.float64)  # 4MB: plasma path

    @ray_tpu.remote(resources={"nodeB": 0.1})
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    total = ray_tpu.get(consume.remote(ref), timeout=60)
    assert total == float(np.arange(500_000, dtype=np.float64).sum())


def test_survives_node_kill(three_node_cluster):
    cluster = three_node_cluster

    @ray_tpu.remote
    def ping():
        return 1

    assert ray_tpu.get(ping.remote(), timeout=60) == 1
    victim = cluster.nodes[-1]  # nodeB
    cluster.remove_node(victim, graceful=False)
    cluster.wait_for_nodes(2, timeout=30)
    # cluster still schedules work
    assert ray_tpu.get([ping.remote() for _ in range(10)], timeout=60) == [1] * 10
    assert ray_tpu.cluster_resources().get("CPU") == 4.0


def test_task_retry_on_worker_death(tmp_path):
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        pid_file = str(tmp_path / "victim.pid")

        @ray_tpu.remote(max_retries=2)
        def flaky():
            import os as _os
            import time as _time

            # first attempt records its pid and hangs; the retry (different
            # pid after kill) returns
            if not _os.path.exists(pid_file):
                with open(pid_file, "w") as f:
                    f.write(str(_os.getpid()))
                _time.sleep(60)
            return "recovered"

        ref = flaky.remote()
        deadline = time.monotonic() + 30
        while not os.path.exists(pid_file) and time.monotonic() < deadline:
            time.sleep(0.05)
        victim = int(open(pid_file).read())
        os.kill(victim, signal.SIGKILL)
        assert ray_tpu.get(ref, timeout=60) == "recovered"
    finally:
        ray_tpu.shutdown()


def test_actor_restart_on_worker_death():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote(max_restarts=1, max_task_retries=1)
        class Counter:
            def __init__(self):
                self.v = 0

            def inc(self):
                self.v += 1
                return self.v

            def pid(self):
                import os as _os

                return _os.getpid()

        c = Counter.remote()
        assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
        victim = ray_tpu.get(c.pid.remote(), timeout=30)
        os.kill(victim, signal.SIGKILL)
        # restarted instance has fresh state; the retried call lands on it
        out = ray_tpu.get(c.inc.remote(), timeout=60)
        assert out == 1
        assert ray_tpu.get(c.pid.remote(), timeout=30) != victim
    finally:
        ray_tpu.shutdown()


def test_actor_out_of_restarts_dies():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        @ray_tpu.remote(max_restarts=0)
        class Fragile:
            def pid(self):
                import os as _os

                return _os.getpid()

        f = Fragile.remote()
        victim = ray_tpu.get(f.pid.remote(), timeout=60)
        os.kill(victim, signal.SIGKILL)
        with pytest.raises(ray_tpu.ActorDiedError):
            ray_tpu.get(f.pid.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------- lineage reconstruction


def test_lineage_reconstruction_after_node_death(three_node_cluster):
    """A plasma-stored task return survives losing its primary copy:
    the owner resubmits the producing task (reference:
    src/ray/core_worker/object_recovery_manager.cc)."""
    import numpy as np

    cluster = three_node_cluster
    node_b = cluster.nodes[-1]

    @ray_tpu.remote(resources={"nodeB": 0.1}, max_retries=3)
    def produce():
        return np.full(500_000, 7.0)  # 4MB -> plasma, primary on node B

    ref = produce.remote()
    assert float(ray_tpu.get(ref, timeout=60)[0]) == 7.0

    cluster.remove_node(node_b, graceful=False)
    # the lost primary must be recomputed elsewhere; re-add capacity so
    # the resubmitted task has somewhere to run
    cluster.add_node(num_cpus=2, resources={"nodeB": 1})
    value = ray_tpu.get(ref, timeout=120)
    assert float(value[0]) == 7.0 and value.shape == (500_000,)


def test_lineage_reconstruction_for_borrower(three_node_cluster):
    """A downstream task consuming a lost object triggers recovery via
    the owner (borrower reports the dead location)."""
    import numpy as np

    cluster = three_node_cluster
    node_b = cluster.nodes[-1]

    @ray_tpu.remote(resources={"nodeB": 0.1}, max_retries=3)
    def produce():
        return np.ones(500_000)

    @ray_tpu.remote(resources={"nodeA": 0.1})
    def consume(arr):
        return float(arr.sum())

    ref = produce.remote()
    ray_tpu.wait([ref], num_returns=1, timeout=60)
    cluster.remove_node(node_b, graceful=False)
    cluster.add_node(num_cpus=2, resources={"nodeB": 1})
    assert ray_tpu.get(consume.remote(ref), timeout=120) == 500_000.0


def test_put_objects_are_not_reconstructible(three_node_cluster):
    """ray.put data has no lineage (matching the reference): losing the
    primary raises ObjectLostError rather than hanging."""
    import numpy as np

    cluster = three_node_cluster
    node_b = cluster.nodes[-1]

    @ray_tpu.remote(resources={"nodeB": 0.1})
    def put_there(arr):
        import ray_tpu as rt

        return rt.put(arr)  # nested ref owned by the node-B worker

    inner = ray_tpu.get(put_there.remote(np.zeros(500_000)), timeout=60)
    cluster.remove_node(node_b, graceful=False)
    with pytest.raises(ray_tpu.RayError):
        ray_tpu.get(inner, timeout=30)
