"""ray_tpu.cancel tests (reference: python/ray/tests/test_cancel.py;
owner-side path python/ray/_private/worker.py:2942, worker interrupt in
_raylet.pyx / core_worker CancelTask)."""

import time

import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=2, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def test_cancel_running_loop_task(cluster):
    """Non-force cancel interrupts a running Python loop."""
    @ray_tpu.remote
    def spin():
        import time as t
        deadline = t.time() + 60
        while t.time() < deadline:
            t.sleep(0.01)
        return "finished"

    ref = spin.remote()
    time.sleep(1.0)  # let it start spinning
    ray_tpu.cancel(ref)
    t0 = time.time()
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert time.time() - t0 < 10, "cancel should interrupt promptly"


def test_cancel_queued_task(cluster):
    """A task cancelled before it starts never runs."""
    @ray_tpu.remote(num_cpus=2)
    def hog():
        import time as t
        t.sleep(3)
        return "hog"

    @ray_tpu.remote(num_cpus=2)
    def queued():
        return "ran"

    h = hog.remote()  # occupies both CPUs
    time.sleep(0.3)
    q = queued.remote()  # stuck behind the hog
    ray_tpu.cancel(q)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(q, timeout=30)
    assert ray_tpu.get(h, timeout=30) == "hog"  # the hog is untouched


def test_cancel_async_actor_call(cluster):
    """Cancelling an async actor call cancels its coroutine; the actor
    stays alive and serves later calls."""
    @ray_tpu.remote
    class A:
        async def slow(self):
            import asyncio
            await asyncio.sleep(60)
            return "done"

        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"
    ref = a.slow.remote()
    time.sleep(0.5)  # in flight, awaiting
    ray_tpu.cancel(ref)
    t0 = time.time()
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
    assert time.time() - t0 < 10
    assert ray_tpu.get(a.ping.remote(), timeout=30) == "pong"


def test_force_cancel_kills_blocked_worker(cluster):
    """force=True terminates a body stuck in native code (uninterruptible
    without killing the worker)."""
    @ray_tpu.remote
    def stuck():
        import time as t
        t.sleep(600)  # one long native sleep: async-exc can't interrupt

    ref = stuck.remote()
    time.sleep(1.0)
    ray_tpu.cancel(ref, force=True)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)

    # the cluster still runs tasks afterwards
    @ray_tpu.remote
    def ok():
        return 1

    assert ray_tpu.get(ok.remote(), timeout=60) == 1


def test_cancel_finished_task_is_noop(cluster):
    @ray_tpu.remote
    def quick():
        return 7

    ref = quick.remote()
    assert ray_tpu.get(ref, timeout=30) == 7
    ray_tpu.cancel(ref)  # no error
    assert ray_tpu.get(ref, timeout=30) == 7  # result intact


def test_cancel_streaming_generator(cluster):
    """Cancelling by generator stops the producer; consumed items stay."""
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        import time as t
        for i in range(100):
            yield i
            t.sleep(0.05)

    g = slow_gen.remote()
    first = ray_tpu.get(g.next_ref(timeout=30))
    assert first == 0
    ray_tpu.cancel(g)
    with pytest.raises((ray_tpu.TaskCancelledError, ray_tpu.RayTaskError,
                        StopIteration)):
        for _ in range(200):
            next(g)


def test_cancel_while_args_resolving(cluster):
    """A task whose ref args are still being produced is cancellable —
    it must never run (regression: it was in no queue during dep
    resolution and cancel was a silent no-op)."""
    @ray_tpu.remote
    def slow_dep():
        import time as t
        t.sleep(2)
        return 1

    @ray_tpu.remote
    def consumer(x):
        return "ran"

    dep = slow_dep.remote()
    ref = consumer.remote(dep)
    time.sleep(0.2)  # consumer is waiting on dep
    ray_tpu.cancel(ref)
    with pytest.raises(ray_tpu.TaskCancelledError):
        ray_tpu.get(ref, timeout=30)
