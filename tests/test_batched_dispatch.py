"""Batched, owner-partitioned task submission (ISSUE 8).

Covers the four tentpole layers: burst-history-independent async
dispatch, multi-task control frames (push_tasks / request_leases /
ensure_local_batch / fetch_objects / reserve_bundles), the partitioned
owner pump forming real batches, and the sharded head object
directory.  Frame-shape assertions count frames via a counting wrapper
around rpc._pack in THIS process (the driver side of every exchange);
wall-clock assertions follow the slow-box protocol (best-of repeats,
ratio thresholds only).
"""

import asyncio
import os
import time
from contextlib import contextmanager

import pytest

import ray_tpu
from ray_tpu._private import rpc as rpcmod
from ray_tpu._private.object_directory import (DeltaReporter,
                                               DirectoryMirror,
                                               ShardedObjectDirectory)


@contextmanager
def _frame_counter():
    """Count control frames sent by this process, keyed (kind, method)."""
    counts = {}
    orig = rpcmod._pack

    def counting(kind, req_id, method, payload):
        counts[(kind, method)] = counts.get((kind, method), 0) + 1
        return orig(kind, req_id, method, payload)

    rpcmod._pack = counting
    try:
        yield counts
    finally:
        rpcmod._pack = orig


def _frames(counts, method):
    return sum(n for (_k, m), n in counts.items() if m == method)


# ------------------------------------------------- sharded directory units


class TestShardedDirectory:
    def test_shard_index_is_process_independent(self):
        """Head and agents live in different processes: shard assignment
        must not use Python's salted hash() (a mismatch silently sends
        every mirror lookup to the wrong bucket)."""
        from ray_tpu._private.object_directory import _shard_index

        import zlib
        assert _shard_index("deadbeef" * 3, 16) == \
            zlib.crc32(b"deadbeef" * 3) % 16  # crc32: stable across runs

    def test_delta_apply_and_locations(self):
        d = ShardedObjectDirectory(num_shards=4, epoch="e1")
        d.apply_delta("n1", [["a" * 8, 100], ["b" * 8, 200]], [])
        d.apply_delta("n2", [["a" * 8, 100]], [])
        assert d.locations("a" * 8) == {"n1": 100, "n2": 100}
        assert d.locations("b" * 8) == {"n1": 200}
        d.apply_delta("n1", [], ["a" * 8])
        assert d.locations("a" * 8) == {"n2": 100}

    def test_versions_move_only_on_touched_shards(self):
        d = ShardedObjectDirectory(num_shards=8, epoch="e1")
        before = d.versions()
        d.apply_delta("n1", [["x" * 8, 50]], [])
        after = d.versions()
        assert sum(a != b for a, b in zip(before, after)) == 1

    def test_updates_since_is_incremental(self):
        d = ShardedObjectDirectory(num_shards=4, epoch="e1")
        d.apply_delta("n1", [["x" * 8, 50]], [])
        full = d.updates_since(None)
        assert any(u["holders"].get("x" * 8) for u in full.values())
        seen = d.versions()
        assert d.updates_since(seen) == {}
        d.apply_delta("n1", [["y" * 8, 60]], [])
        inc = d.updates_since(seen)
        assert len(inc) == 1
        (payload,) = inc.values()
        assert payload["holders"]["y" * 8] == {"n1": 60}

    def test_drop_node_removes_every_holder_entry(self):
        d = ShardedObjectDirectory(num_shards=4, epoch="e1")
        d.apply_delta("n1", [[f"oid{i}", 10] for i in range(20)], [])
        d.apply_delta("n2", [["oid3", 10]], [])
        d.drop_node("n1")
        assert d.node_entries("n1") == {}
        assert d.locations("oid0") == {}
        assert d.locations("oid3") == {"n2": 10}

    def test_full_resend_drops_stale_entries(self):
        d = ShardedObjectDirectory(num_shards=4, epoch="e1")
        d.apply_delta("n1", [["old", 10], ["keep", 20]], [])
        d.apply_delta("n1", [["keep", 20], ["new", 30]], [], full=True)
        assert d.node_entries("n1") == {"keep": 20, "new": 30}

    def test_mirror_applies_versioned_updates(self):
        d = ShardedObjectDirectory(num_shards=4, epoch="e1")
        m = DirectoryMirror(num_shards=4)
        d.apply_delta("n1", [["obj", 42]], [])
        m.apply_updates(d.updates_since(m.seen_versions()))
        assert m.holders("obj") == {"n1": 42}
        # no churn -> nothing to ship
        assert d.updates_since(m.seen_versions()) == {}
        d.apply_delta("n1", [], ["obj"])
        m.apply_updates(d.updates_since(m.seen_versions()))
        assert m.holders("obj") == {}

    def test_delta_reporter_epoch_handshake(self):
        # delta entries are [oid, size, crc] triples since checksummed
        # transfers (crc None until the store has hashed the object)
        r = DeltaReporter()
        d1 = r.build([["a", 1], ["b", 2]], "epoch1")
        assert d1["full"] and sorted(e[0] for e in d1["add"]) == ["a", "b"]
        r.ack()
        # steady state: no churn -> empty delta
        d2 = r.build([["a", 1], ["b", 2]], "epoch1")
        assert not d2["full"] and d2["add"] == [] and d2["remove"] == []
        r.ack()
        # removal flows as a remove entry
        d3 = r.build([["a", 1]], "epoch1")
        assert d3["remove"] == ["b"]
        r.ack()
        # a checksum turning known is churn: the entry re-ships
        d3b = r.build([["a", 1, 777]], "epoch1")
        assert not d3b["full"] and d3b["add"] == [["a", 1, 777]]
        r.ack()
        # head restarted (new epoch): everything re-sends
        d4 = r.build([["a", 1, 777]], "epoch2")
        assert d4["full"] and d4["add"] == [["a", 1, 777]]

    def test_unacked_delta_is_rebuilt(self):
        """A heartbeat that died in flight must not lose its delta."""
        r = DeltaReporter()
        r.build([["a", 1]], "e")
        r.ack()
        d = r.build([["a", 1], ["b", 2]], "e")  # not acked (call failed)
        assert d["add"] == [["b", 2, None]]
        d = r.build([["a", 1], ["b", 2]], "e")
        assert d["add"] == [["b", 2, None]]  # still pending


# ------------------------------------------------- batched control frames


def test_async_burst_uses_batched_frames(local_cluster):
    """A 300-task async burst must cost O(batches) push frames and O(1)
    lease-request frames — not one frame per task (the round-6 profile
    showed 340 single-task frames per 1000 tasks before batching)."""

    @ray_tpu.remote
    def e():
        return 1

    ray_tpu.get([e.remote() for _ in range(50)], timeout=60)  # warm
    n = 300
    with _frame_counter() as counts:
        out = ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
    assert out == [1] * n
    pushes = _frames(counts, "push_tasks") + _frames(counts, "push_task")
    assert pushes <= n // 3, (
        f"pump fragmented: {pushes} push frames for {n} tasks "
        f"({dict(counts)})")
    # batched request_leases frames cover the whole deficit: each
    # partial grant (workers still spawning) triggers one re-ask, so
    # the count tracks grant cycles — O(node CPUs), never O(tasks)
    assert _frames(counts, "request_lease") == 0
    assert _frames(counts, "request_leases") <= 12


def test_batched_get_localizes_in_one_frame(local_cluster):
    """get() over many plasma-stored objects sends ONE
    ensure_local_batch frame to the agent, not one ensure_local per
    ref (round-5 verdict: vectorized driver get)."""
    import numpy as np

    refs = [ray_tpu.put(np.zeros(50_000)) for _ in range(20)]  # >100KB each
    with _frame_counter() as counts:
        vals = ray_tpu.get(refs, timeout=60)
    assert all(v.shape == (50_000,) for v in vals)
    assert _frames(counts, "ensure_local") == 0
    assert _frames(counts, "ensure_local_batch") == 1, dict(counts)


def test_worker_materializes_many_borrowed_refs(local_cluster):
    """A task taking many driver-owned refs resolves them through the
    batched fetch_objects path (owner side) and still sees every
    value."""

    @ray_tpu.remote
    def total(xs):
        return sum(ray_tpu.get(list(xs), timeout=60))

    refs = [ray_tpu.put(i) for i in range(40)]
    assert ray_tpu.get(total.remote(refs), timeout=60) == sum(range(40))


def test_burst_then_async_is_history_independent(local_cluster):
    """Regression for the round-5 top finding: a blocking sync burst
    must not depress the async rate that follows.  Best-of repeats on
    both sides (slow-box protocol); post-burst retries stop early once
    the bar is met, so a recovered-but-noisy box can't flake this."""

    @ray_tpu.remote
    def e():
        return 1

    n = 300

    def async_rate():
        t0 = time.perf_counter()
        ray_tpu.get([e.remote() for _ in range(n)], timeout=120)
        return n / (time.perf_counter() - t0)

    ray_tpu.get([e.remote() for _ in range(50)], timeout=60)  # warm
    fresh = max(async_rate() for _ in range(2))
    for _ in range(200):  # the history pollution
        ray_tpu.get(e.remote(), timeout=60)
    post = 0.0
    for _ in range(3):
        post = max(post, async_rate())
        if post >= 0.75 * fresh:
            break
    assert post >= 0.75 * fresh, (
        f"async collapsed after sync burst: fresh={fresh:.0f}/s "
        f"post={post:.0f}/s")


def test_cancel_inside_batch_frame(local_cluster):
    """A cancelled task travelling inside a multi-task push_tasks frame
    resolves as cancelled WITHOUT poisoning its batch siblings."""

    @ray_tpu.remote(max_retries=0)
    def step(x, delay):
        if delay:
            time.sleep(delay)
        return x

    from ray_tpu._private.errors import TaskCancelledError

    # train the class sub-ms so the pump batches deep
    ray_tpu.get([step.remote(i, 0) for i in range(30)], timeout=60)
    # CPU:4 pins the class to ONE lease -> slow head + queued siblings
    # ride one frame behind it
    opts = step.options(resources={"CPU": 4})
    ray_tpu.get(opts.remote(-1, 0), timeout=60)  # warm the 4-CPU class
    slow = opts.remote(-2, 3.0)
    quick = [opts.remote(i, 0) for i in range(8)]
    victim = quick[3]
    time.sleep(0.3)  # let the frame reach the worker, slow task running
    ray_tpu.cancel(victim)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(victim, timeout=60)
    rest = [r for i, r in enumerate(quick) if i != 3]
    assert ray_tpu.get(rest, timeout=120) == [0, 1, 2, 4, 5, 6, 7]
    assert ray_tpu.get(slow, timeout=60) == -2


# ------------------------------------------------- chaos on the batch RPCs


@pytest.fixture
def chaos_rules():
    """Install driver-process chaos rules; always disarm after."""
    from ray_tpu._private import fault_injection

    installed = []

    def arm(rules):
        installed.extend(rules)
        fault_injection.install(rules, fault_injection.version + 1)

    yield arm
    fault_injection.install([], fault_injection.version + 1)


def test_chaos_sever_on_push_tasks_requeues_batch(local_cluster,
                                                  chaos_rules):
    """rpc.send severing a push_tasks frame mid-burst: the owner maps
    the connection loss to a lease death, requeues the unstarted tasks,
    and the burst still completes on a replacement lease."""

    @ray_tpu.remote
    def e(x):
        return x

    ray_tpu.get([e.remote(i) for i in range(30)], timeout=60)  # warm
    chaos_rules([{"site": "rpc.send", "action": "sever",
                  "target": ":push_tasks", "count": 1, "p": 1.0}])
    out = ray_tpu.get([e.remote(i) for i in range(200)], timeout=120)
    assert out == list(range(200))
    from ray_tpu._private import fault_injection

    assert fault_injection.fired_counts(), "sever rule never fired"


def test_chaos_delay_on_request_leases(local_cluster, chaos_rules):
    """Delaying the batched lease frames must only slow the burst, never
    wedge or shrink it."""

    @ray_tpu.remote
    def e(x):
        return x

    chaos_rules([{"site": "rpc.send", "action": "delay", "delay_s": 0.2,
                  "target": ":request_leases", "count": 3, "p": 1.0}])
    out = ray_tpu.get([e.remote(i) for i in range(150)], timeout=120)
    assert out == list(range(150))


# ------------------------------------------------- PG commit batching


def test_pg_reserve_batches_per_node(tmp_path):
    """A multi-bundle PG commits all of a node's bundles in ONE
    reserve_bundles frame and returns them in ONE return_bundles frame."""
    from ray_tpu._private.head import HeadService
    from ray_tpu._private.node_agent import NodeAgent

    async def main():
        head = HeadService()
        head_port = await head.start()
        agent = NodeAgent(("127.0.0.1", head_port), str(tmp_path),
                          {"CPU": 8}, capacity=1 << 20)
        await agent.start()
        reserve_frames = []
        return_frames = []
        orig_reserve = agent.rpc_reserve_bundles
        orig_return = agent.rpc_return_bundles

        async def counting_reserve(pg_id, items, wait_ms=0, _conn=None):
            reserve_frames.append(len(items))
            return await orig_reserve(pg_id, items, wait_ms=wait_ms,
                                      _conn=_conn)

        async def counting_return(pg_id, indices):
            return_frames.append(len(indices))
            return await orig_return(pg_id, indices)

        agent.rpc_reserve_bundles = counting_reserve
        agent.rpc_return_bundles = counting_return
        try:
            r = await head.rpc_create_placement_group(
                bundles=[{"CPU": 1}] * 4, strategy="PACK", pg_id="aa" * 14)
            assert r["info"]["state"] == "CREATED", r
            assert reserve_frames == [4], reserve_frames
            await head.rpc_remove_placement_group("aa" * 14)
            assert return_frames == [4], return_frames
        finally:
            await agent.stop()
            await head.stop()

    asyncio.run(main())


def test_pg_create_reply_carries_created_info(local_cluster):
    """pg.wait() after an inline-committed create answers from the
    create reply — zero get_placement_group round trips."""
    from ray_tpu.util import placement_group, remove_placement_group

    pg = placement_group([{"CPU": 1}])
    with _frame_counter() as counts:
        assert pg.wait(timeout=30)
    assert _frames(counts, "get_placement_group") == 0, dict(counts)
    remove_placement_group(pg)
