"""RLlib PPO slice tests.

Mirrors the reference's PPO learning tests
(reference: rllib/algorithms/ppo/tests/test_ppo.py — config build,
training_step mechanics, and learning CartPole;
rllib/core/learner/tests for the update path)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    from tests.conftest import force_cpu_jax

    force_cpu_jax()
    ray_tpu.init(num_cpus=6, object_store_memory=256 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def test_learner_update_shapes(cluster):
    """One jitted update on a synthetic batch: finite losses, params move."""
    import jax

    from ray_tpu.rllib.core.learner import PPOLearner
    from ray_tpu.rllib.core.rl_module import ActorCriticModule

    module = ActorCriticModule(obs_dim=4, num_actions=2)
    learner = PPOLearner(module, minibatch_size=64, num_epochs=2, seed=0)
    T, E = 32, 4
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(T, E, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=(T, E)).astype(np.int32),
        "logp": np.full((T, E), -0.69, np.float32),
        "values": np.zeros((T, E), np.float32),
        "rewards": np.ones((T, E), np.float32),
        "nonterminal": np.ones((T, E), np.float32),
        "mask": np.ones((T, E), np.float32),
        "last_value": np.zeros((E,), np.float32),
    }
    before = jax.tree_util.tree_leaves(learner.params)[0].copy()
    stats = learner.update_from_batch(batch)
    after = jax.tree_util.tree_leaves(learner.params)[0]
    assert np.isfinite(stats["total_loss"])
    assert not np.allclose(before, after), "update did not move params"


def test_env_runner_rollout(cluster):
    """EnvRunner actor returns a consistent [T, E] rollout."""
    from ray_tpu.rllib.core.rl_module import ActorCriticModule
    from ray_tpu.rllib.env_runner import EnvRunner

    module_cfg = {"obs_dim": 4, "num_actions": 2}
    runner = ray_tpu.remote(EnvRunner).remote("CartPole-v1", 4, module_cfg,
                                              seed=0)
    module = ActorCriticModule(**module_cfg)
    import jax

    weights = jax.tree_util.tree_map(
        np.asarray, module.init(jax.random.PRNGKey(0)))
    ro = ray_tpu.get(runner.sample.remote(weights, 64), timeout=300)
    assert ro["obs"].shape == (64, 4, 4)
    assert ro["actions"].shape == (64, 4)
    assert ro["last_value"].shape == (4,)
    # masked fraction is small (resets are rare relative to steps)
    assert ro["mask"].mean() > 0.5
    # with a random policy CartPole episodes finish within 64*4 steps
    assert len(ro["episode_returns"]) > 0
    ray_tpu.kill(runner)


def test_ppo_learns_cartpole(cluster):
    """North star: CartPole reward > 450 in CI minutes on CPU
    (reference: rllib PPO CartPole tuned example)."""
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                         rollout_fragment_length=128)
            .training(lr=2.5e-4, minibatch_size=128, num_epochs=4)
            .debugging(seed=3)
            .build())
    try:
        best = 0.0
        for _ in range(150):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best > 450:
                break
        assert best > 450, f"PPO only reached {best} return"
        # the greedy policy holds the pole too
        assert algo.evaluate(num_episodes=5) > 400
    finally:
        algo.stop()


def test_ppo_under_tuner(cluster):
    """PPO as a Tune trainable: metrics reported per iteration
    (reference: Algorithm is a Trainable run through Tuner)."""
    from ray_tpu.rllib import PPOConfig
    from ray_tpu.tune import TuneConfig, Tuner

    base = (PPOConfig()
            .env_runners(num_env_runners=1, num_envs_per_env_runner=4,
                         rollout_fragment_length=64)
            .debugging(seed=0))
    tuner = Tuner(
        base.to_trainable(max_iterations=3),
        param_space={"lr": 1e-3},
        tune_config=TuneConfig(metric="episode_return_mean", mode="max",
                               num_samples=1))
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics.get("training_iteration", 0) >= 3
    assert "episode_return_mean" in best.metrics
