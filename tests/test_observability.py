"""Metrics + state API + task events + timeline tests.

Mirrors the reference's observability suites
(reference: python/ray/tests/test_metrics_agent.py,
test_state_api.py; stats plane src/ray/stats/metric.h, task events
src/ray/core_worker/task_event_buffer.h:206)."""

import time
import urllib.request

import pytest

import ray_tpu


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def _scrape(port: int) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        return r.read().decode()


def _assert_valid_exposition(text: str) -> None:
    """Validate Prometheus text exposition format (the contract every
    scraper relies on): HELP/TYPE headers come at most once per family,
    a family's samples are contiguous, sample lines parse as
    name{labels} value, and histogram buckets are cumulative with a
    +Inf terminal matching _count."""
    import re

    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'               # metric name
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'         # first label
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})?'    # more labels
        r' [-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|nan|inf)$')
    typed: dict = {}
    helped: set = set()
    family_of_sample = {}
    last_family = None
    families_seen_done = set()
    for i, ln in enumerate(text.splitlines()):
        if not ln.strip():
            continue
        if ln.startswith("# HELP "):
            name = ln.split()[2]
            assert name not in helped, f"duplicate HELP for {name}"
            helped.add(name)
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split()
            name, kind = parts[2], parts[3]
            assert name not in typed, f"duplicate TYPE for {name}"
            assert kind in ("counter", "gauge", "histogram", "summary",
                            "untyped"), ln
            typed[name] = kind
            continue
        assert not ln.startswith("#"), f"bad comment line: {ln!r}"
        m = sample_re.match(ln)
        assert m, f"unparsable sample line {i}: {ln!r}"
        name = m.group(1)
        base = name
        for suffix in ("_bucket", "_count", "_sum"):
            if name.endswith(suffix) and name[:-len(suffix)] in typed:
                base = name[:-len(suffix)]
        family_of_sample[name] = base
        # contiguity: once a family ends, it must not reappear
        if base != last_family:
            assert base not in families_seen_done, \
                f"family {base} interleaved (line {i}: {ln!r})"
            if last_family is not None:
                families_seen_done.add(last_family)
            last_family = base
    # histogram buckets cumulative and consistent with _count
    for fam, kind in typed.items():
        if kind != "histogram":
            continue
        buckets: dict = {}
        counts: dict = {}
        for ln in text.splitlines():
            if ln.startswith(fam + "_bucket"):
                labels = ln[len(fam + "_bucket"):].split(" ")[0]
                le = labels.split('le="')[1].split('"')[0]
                key = labels.replace(f'le="{le}"', "").strip("{},")
                buckets.setdefault(key, []).append(float(ln.rsplit(" ", 1)[1]))
            elif ln.startswith(fam + "_count"):
                labels, v = ln[len(fam + "_count"):].rsplit(" ", 1)
                counts[labels.strip("{}")] = float(v)
        for key, vals in buckets.items():
            assert vals == sorted(vals), \
                f"{fam} buckets not cumulative for {{{key}}}: {vals}"
            if key in counts:
                assert vals[-1] == counts[key], \
                    f"{fam} +Inf bucket != _count for {{{key}}}"


def _agent_metrics_port() -> int:
    w = ray_tpu.api._worker()
    return w.agent.call("metrics_port")["port"]


def _head_metrics_port() -> int:
    w = ray_tpu.api._worker()
    return w.head.call("metrics_port")["port"]


def test_agent_prometheus_endpoint(cluster):
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1), timeout=60) == 2
    port = _agent_metrics_port()
    assert port > 0
    text = _scrape(port)
    assert "rt_object_store_capacity_bytes" in text
    assert "rt_worker_pool_size" in text
    # the worker that executed f pushes its counters for re-export
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        text = _scrape(port)
        if "rt_tasks_finished" in text:
            return
        time.sleep(0.5)
    raise AssertionError("worker metrics never re-exported:\n" + text[:800])


def test_head_prometheus_endpoint(cluster):
    port = _head_metrics_port()
    assert port > 0
    text = _scrape(port)
    assert "rt_head_nodes" in text
    assert "rt_head_nodes 1.0" in text or "rt_head_nodes 1 " in text \
        or "rt_head_nodes 1\n" in text


def test_metrics_exposition_format_valid(cluster):
    """Both scrape targets must emit parseable Prometheus exposition
    text — guards the handcrafted renderer (and the merge of worker
    pushes) against format drift as metrics are added."""
    @ray_tpu.remote
    def f(x):
        return x

    ray_tpu.get([f.remote(i) for i in range(20)], timeout=60)
    head_port, agent_port = _head_metrics_port(), _agent_metrics_port()
    deadline = time.monotonic() + 60
    head_text = agent_text = ""
    while time.monotonic() < deadline:
        head_text, agent_text = _scrape(head_port), _scrape(agent_port)
        # wait until the interesting families are present so the
        # validation actually covers them (worker push + head ingest +
        # the introspection loop-lag probes on both daemons)
        if "ray_tpu_task_sched_latency_seconds_bucket" in head_text \
                and "rt_tasks_finished" in agent_text \
                and "ray_tpu_event_loop_lag_seconds" in head_text \
                and "ray_tpu_event_loop_lag_seconds" in agent_text:
            break
        time.sleep(0.5)
    _assert_valid_exposition(head_text)
    _assert_valid_exposition(agent_text)
    # the new head-side families are exposed
    assert "ray_tpu_task_sched_latency_seconds" in head_text
    for phase in ("queued", "leased", "running"):
        assert f'phase="{phase}"' in head_text, phase
    assert "rt_head_traces" in head_text
    # always-on introspection gauges: the loop-lag probe on each daemon
    # and the owner-side dispatch-pump depth riding the worker push
    assert 'ray_tpu_event_loop_lag_seconds{role="head"}' in head_text
    assert 'role="agent"' in agent_text
    deadline = time.monotonic() + 45
    while time.monotonic() < deadline:
        if "ray_tpu_dispatch_pump_depth" in agent_text:
            break
        time.sleep(0.5)
        agent_text = _scrape(agent_port)
    assert "ray_tpu_dispatch_pump_depth" in agent_text
    _assert_valid_exposition(agent_text)
    # tracing self-metrics ride the worker push to the agent endpoint
    deadline = time.monotonic() + 45
    while time.monotonic() < deadline:
        agent_text = _scrape(agent_port)
        if "rt_trace_spans_sampled" in agent_text:
            break
        time.sleep(0.5)
    assert "rt_trace_spans_sampled" in agent_text
    _assert_valid_exposition(agent_text)


def test_user_metrics_exported(cluster):
    from ray_tpu.util.metrics import Counter

    @ray_tpu.remote
    def instrumented():
        c = Counter("my_app_events", "app-level counter")
        c.inc(3)
        return "ok"

    assert ray_tpu.get(instrumented.remote(), timeout=60) == "ok"
    port = _agent_metrics_port()
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if "my_app_events" in _scrape(port):
            return
        time.sleep(0.5)
    raise AssertionError("user metric never appeared on the node endpoint")


def test_list_tasks_and_summary(cluster):
    from ray_tpu.util.state import list_tasks, summarize_tasks

    @ray_tpu.remote
    def traced(x):
        return x

    ray_tpu.get([traced.remote(i) for i in range(5)], timeout=60)
    # NB: tasks defined inside a test function carry their qualname
    # ("test_x.<locals>.traced") — filter by suffix
    deadline = time.monotonic() + 15
    finished = []
    while time.monotonic() < deadline:
        finished = [t for t in list_tasks()
                    if t.get("name", "").endswith("traced")
                    and t.get("state") == "FINISHED"]
        if len(finished) >= 5:
            break
        time.sleep(0.3)
    assert len(finished) >= 5, finished
    t = finished[0]
    assert t["worker_id"] and t["node_id"]
    assert t.get("running_ts") and t.get("finished_ts")
    summary = summarize_tasks()
    traced_rows = [v for k, v in summary.items() if k.endswith("traced")]
    assert traced_rows and traced_rows[0]["states"].get("FINISHED", 0) >= 5
    # grown to percentiles: the running-phase stats cover the 5 runs
    running = traced_rows[0]["running"]
    assert running and running["count"] >= 5
    assert running["p50_ms"] <= running["p99_ms"] <= running["max_ms"]


def test_failed_task_recorded(cluster):
    from ray_tpu.util.state import list_tasks

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise ValueError("kaput")

    with pytest.raises(ray_tpu.RayError):
        ray_tpu.get(boom.remote(), timeout=60)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        failed = [t for t in list_tasks(state="FAILED")
                  if t.get("name", "").endswith("boom")]
        if failed:
            assert "kaput" in failed[0].get("error", "")
            return
        time.sleep(0.3)
    raise AssertionError("failed task never recorded")


def test_timeline_chrome_trace(cluster, tmp_path):
    import json

    from ray_tpu.util.state import timeline

    @ray_tpu.remote
    def span():
        time.sleep(0.05)
        return 1

    ray_tpu.get([span.remote() for _ in range(3)], timeout=60)
    path = str(tmp_path / "trace.json")
    deadline = time.monotonic() + 15
    events = []
    while time.monotonic() < deadline:
        events = [e for e in timeline(path)
                  if e["name"].endswith("span")]
        if len(events) >= 3:
            break
        time.sleep(0.3)
    assert len(events) >= 3
    ev = events[0]
    assert ev["ph"] == "X" and ev["dur"] >= 50_000  # >=50ms in usecs
    assert json.load(open(path))  # valid JSON on disk


def test_list_objects(cluster):
    import numpy as np

    from ray_tpu.util.state import list_objects

    ref = ray_tpu.put(np.zeros(300_000))  # ~2.4MB -> plasma
    objs = list_objects()
    assert any(o["object_id"] == ref.oid for o in objs), objs
    assert all("size" in o and "node_id" in o for o in objs)
    del ref


def test_metric_names_documented_in_readme(cluster):
    """Every framework metric family registered at runtime must appear
    in README.md's Observability metrics table — undocumented metrics
    fail CI (VERDICT/ISSUE 6 satellite).  Covers both what the live
    endpoints expose and every process-singleton family the codebase
    can register lazily (dag/serve/xfer/introspection helpers)."""
    import os

    @ray_tpu.remote
    def f(x):
        return x

    ray_tpu.get([f.remote(i) for i in range(10)], timeout=60)
    head_port, agent_port = _head_metrics_port(), _agent_metrics_port()
    deadline = time.monotonic() + 30
    names = set()
    while time.monotonic() < deadline:
        text = _scrape(head_port) + _scrape(agent_port)
        names = {ln.split()[2] for ln in text.splitlines()
                 if ln.startswith("# TYPE ")}
        if "rt_tasks_finished" in names:
            break
        time.sleep(0.5)
    # force-register every lazy singleton family so the diff also
    # covers code paths this test didn't exercise (dag, serve, xfer)
    from ray_tpu._private import metrics as m

    for fn in (m.object_transfer_metrics, m.dag_metrics,
               m.serve_request_latency_histogram, m.loop_lag_gauge,
               m.dispatch_pump_depth_gauge, m.dag_channel_occupancy_gauge,
               m.serve_proxy_inflight_gauge, m.fault_tolerance_metrics,
               m.task_events_dropped_counter,
               m.dispatch_batch_size_histogram,
               m.object_leaked_bytes_gauge,
               m.memory_scan_partial_gauge,
               m.object_store_breakdown_gauge,
               m.pipeline_metrics,
               m.llm_metrics,
               m.llm_prefix_metrics,
               m.autoscaler_metrics,
               m.serve_sheds_counter,
               m.deadline_metrics,
               m.serve_tail_metrics,
               m.memory_pressure_metrics,
               m.object_checksum_failures_counter,
               m.head_inbox_depth_gauge):
        fn()
    with m.default_registry._lock:
        names |= set(m.default_registry._metrics)
    framework = sorted(n for n in names
                       if n.startswith(("rt_", "ray_tpu_")))
    assert framework, "no framework metrics scraped at all?"
    readme = open(os.path.join(os.path.dirname(__file__), "..",
                               "README.md")).read()
    undocumented = [n for n in framework if n not in readme]
    assert not undocumented, (
        f"metrics registered at runtime but missing from the README "
        f"metrics table: {undocumented}")


def test_head_dashboard_spa(local_cluster):
    """The head serves the single-page dashboard app and its JSON data
    plane, and the snapshot reflects live cluster state (reference:
    dashboard/client/src — the role, not the framework)."""
    import json
    import urllib.request

    import ray_tpu as rt

    port = rt.api._worker().head.call("metrics_port")["port"]
    assert port

    def fetch(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.headers.get("Content-Type", ""), r.read()

    # app shell + the one JS file
    ct, html = fetch("/")
    assert ct.startswith("text/html")
    assert "ray_tpu cluster" in html.decode()
    assert '<script src="/app.js">' in html.decode()
    ct, js = fetch("/app.js")
    assert ct.startswith("application/javascript")
    for needle in ("api/snapshot", "sparkline", "Placement groups",
                   "Traces", "Memory", "api/memory"):
        assert needle in js.decode()

    # live state lands in the snapshot the app renders from
    @rt.remote
    def probe():
        return 1

    assert rt.get(probe.remote(), timeout=60) == 1

    @rt.remote
    class DashActor:
        def ping(self):
            return "pong"

    a = DashActor.remote()
    assert rt.get(a.ping.remote(), timeout=60) == "pong"

    snap = json.loads(fetch("/api/snapshot")[1])
    for key in ("nodes", "actors", "tasks", "placement_groups", "jobs",
                "traces", "series", "summary"):
        assert key in snap, key
    assert len(snap["nodes"]) == 1
    assert any(x["state"] == "ALIVE" for x in snap["actors"])
    assert any(t.get("state") == "FINISHED" for t in snap["tasks"])
    assert snap["summary"]["cpus_total"] > 0

    # timeline download is a Chrome trace event list: duration slices
    # plus flow events ("s"/"f" submit→execute arrows) and optional
    # instant events for queue-time failures.  Poll: the executor's
    # RUNNING/FINISHED events flush within ms but the owner's SUBMITTED
    # half (which the flow start needs) rides the periodic flush tick.
    deadline = time.monotonic() + 45
    while True:
        events = json.loads(fetch("/api/timeline")[1])
        assert isinstance(events, list) and events
        assert all(e["ph"] in ("X", "s", "f", "i") and "ts" in e
                   for e in events)
        slices = [e for e in events if e["ph"] == "X"]
        assert slices and all("dur" in e for e in slices)
        flow_starts = {e["id"] for e in events if e["ph"] == "s"}
        flow_ends = {e["id"] for e in events if e["ph"] == "f"}
        if flow_starts or time.monotonic() >= deadline:
            break
        time.sleep(0.5)
    assert flow_starts and flow_starts == flow_ends

    # legacy summary endpoint unchanged
    state = json.loads(fetch("/api/state")[1])
    assert len(state["nodes"]) == 1 and "actors_by_state" in state
    rt.kill(a)
