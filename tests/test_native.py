"""Native copy-path tests (reference: plasma client.cc write path)."""

import numpy as np
import pytest

from ray_tpu import _native


def test_native_builds_and_loads():
    assert _native.available(), "cc toolchain present: native must load"


@pytest.mark.parametrize("n", [10, 4096, (1 << 21) - 1, (1 << 21) + 7,
                               8 * 1024 * 1024 + 13])
def test_copy_into_matches_python(n):
    src = np.random.randint(0, 256, size=n, dtype=np.uint8)
    dst = np.zeros(n, dtype=np.uint8)
    _native.copy_into(memoryview(dst), memoryview(src))
    assert np.array_equal(dst, src)


def test_copy_into_readonly_source():
    src = bytes(np.random.randint(0, 256, size=3 << 21, dtype=np.uint8))
    dst = bytearray(len(src))
    _native.copy_into(memoryview(dst), src)  # bytes = readonly buffer
    assert bytes(dst) == src


def test_copy_into_length_mismatch():
    with pytest.raises(ValueError):
        _native.copy_into(bytearray(4), b"12345")


def test_copy_into_readonly_dest_rejected():
    with pytest.raises(ValueError):
        _native.copy_into(b"1234", bytearray(4))


def test_fallback_path(monkeypatch):
    monkeypatch.setattr(_native, "_lib", None)
    monkeypatch.setattr(_native, "_load_failed", True)
    src = np.random.randint(0, 256, size=1 << 22, dtype=np.uint8)
    dst = np.zeros(1 << 22, dtype=np.uint8)
    _native.copy_into(memoryview(dst), memoryview(src))
    assert np.array_equal(dst, src)
    _native.touch_pages(memoryview(dst))


def test_touch_pages():
    buf = np.zeros(5 << 22, dtype=np.uint8)
    _native.touch_pages(memoryview(buf))  # must not crash or mutate
    assert not buf.any()


def test_native_allocator_matches_python():
    """The C allocator and the Python FreeListAllocator agree on a long
    random alloc/free sequence (offsets, failures, allocated bytes)."""
    import random

    from ray_tpu._private.object_store import FreeListAllocator

    native = _native.make_allocator(1 << 16, wait_s=60)
    assert native is not None
    py = FreeListAllocator(1 << 16)
    rng = random.Random(42)
    live = []
    for _ in range(600):
        if live and rng.random() < 0.45:
            off, size = live.pop(rng.randrange(len(live)))
            native.free(off, size)
            py.free(off, size)
        else:
            size = rng.randint(1, 3000)
            a, b = native.alloc(size), py.alloc(size)
            assert a == b, f"divergence: native {a} vs python {b}"
            if a is not None:
                live.append((a, size))
        assert native.allocated == py.allocated
    for off, size in live:
        native.free(off, size)
        py.free(off, size)
    assert native.allocated == py.allocated == 0
