"""Cluster memory & object accounting (ISSUE 9): worker reference
summaries with call-sites, per-node store byte breakdowns, the head's
joined /api/memory + /api/summary views, and the leak tripwires
(dead-owner pins, borrowed refs past TTL, orphaned channel slots) with
their ray_tpu_object_leaked_bytes gauge."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import state

MB = 1024 * 1024

# fast tripwires for tests: scan twice a second, flag past 2s
_ACCT_CONFIG = {"memory_scan_interval_s": 0.4, "object_leak_ttl_s": 2.0}


@pytest.fixture(scope="module")
def acct_cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * MB,
                 _system_config=dict(_ACCT_CONFIG))
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def _head_metrics_port():
    return ray_tpu.api._worker().head.call("metrics_port")["port"]


def _scrape_head():
    port = _head_metrics_port()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        return r.read().decode()


def _leaked_bytes(kind: str) -> float:
    needle = f'ray_tpu_object_leaked_bytes{{kind="{kind}"}}'
    for ln in _scrape_head().splitlines():
        if ln.startswith(needle):
            return float(ln.rsplit(" ", 1)[1])
    return -1.0  # gauge series not present yet


def _wait(predicate, timeout=20.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.2)
    raise AssertionError(f"timed out waiting for {what or predicate}")


# ----------------------------------------------------- multi-node e2e
# Runs FIRST: it drives its own 2-node Cluster + driver, which must not
# collide with the module-scoped single-node fixture below.


def test_two_node_attribution_and_reconciliation():
    """Acceptance on a live 2-node cluster: >=95% of arena bytes carry
    function-level call-sites and every node's breakdown sums reconcile
    with its store's occupancy gauge."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 2})
    cluster.add_node(num_cpus=2, object_store_memory=64 * MB)
    try:
        ray_tpu.init(address=cluster.address,
                     _system_config=dict(_ACCT_CONFIG))
        cluster.wait_for_nodes(2)

        @ray_tpu.remote
        def produce(i):
            import numpy as np

            return np.full(2 * MB, i % 251, dtype=np.uint8)

        # pin production to BOTH nodes (SPREAD is best-effort and can
        # pack while the second node's workers are still spawning);
        # returns are driver-owned but stored on the executing node
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)

        nodes = [n["node_id"] for n in state.list_nodes()]
        assert len(nodes) == 2, nodes
        refs = [produce.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                nodes[i % 2], soft=False)).remote(i) for i in range(6)]
        ray_tpu.get(refs, timeout=120)
        local_put = ray_tpu.put(os.urandom(2 * MB))

        def settled():
            v = state.memory_summary(top_n=100)
            return (len(v["nodes"]) == 2
                    and v["store_object_bytes"] >= 10 * MB)

        _wait(settled, timeout=30, what="2-node memory view")
        v = state.memory_summary(top_n=100)
        assert len(v["nodes"]) == 2
        assert v["attributed_bytes"] / v["store_object_bytes"] >= 0.95
        per_node_objects = {nid: 0 for nid in v["nodes"]}
        for o in v["objects"]:
            per_node_objects[o["node_id"]] += 1
            if o["size"] >= 2 * MB:
                assert o.get("owner"), o
                assert o["owner"]["call_site"], o
        # bytes landed on BOTH nodes (SPREAD) and each breakdown
        # reconciles: aligned shm footprint == allocator occupancy
        assert all(n > 0 for n in per_node_objects.values()), \
            per_node_objects
        for nid, b in v["nodes"].items():
            assert b["shm_bytes"] == b["arena_used"], (nid, b)
            assert b["arena_used"] + b["arena_free"] == b["capacity"]
        assert not v["leaks"]["partial"]
        del refs, local_put
    finally:
        try:
            ray_tpu.shutdown()
        finally:
            cluster.shutdown()


# ------------------------------------------------------- worker summaries


def test_worker_summary_owned_refs_with_call_sites(acct_cluster):
    """The owner's reference table records size, pin state and the USER
    call-site for puts and task returns."""
    @ray_tpu.remote
    def produce():
        import numpy as np

        return np.zeros(1 * MB, dtype=np.uint8)

    big = ray_tpu.put(b"z" * (2 * MB))          # plasma put
    small = ray_tpu.put({"k": 1})               # inline put
    ret = produce.remote()
    ray_tpu.get(ret, timeout=60)
    s = ray_tpu.api._worker().memory_summary()
    assert s["kind"] == "driver" and s["num_owned"] >= 3
    by_oid = {r["oid"]: r for r in s["owned"]}
    me = os.path.basename(__file__)
    r_big = by_oid[big.oid]
    assert r_big["size"] >= 2 * MB and r_big["store"] == "plasma"
    assert r_big["name"] == "put"
    assert r_big["call_site"].startswith(me), r_big["call_site"]
    assert r_big["local"] >= 1 and r_big["borrowers"] == 0
    r_small = by_oid[small.oid]
    assert r_small["store"] == "inline" and 0 < r_small["size"] < 1024
    r_ret = by_oid[ret.oid]
    assert r_ret["name"].endswith("produce")
    assert r_ret["call_site"].startswith(me)
    assert r_ret["size"] >= 1 * MB
    del big, small, ret


def test_memory_view_attributes_arena_bytes(acct_cluster):
    """Acceptance: the joined view attributes >=95% of reported arena
    bytes to owned refs with call-sites, and each node's breakdown
    reconciles with the store's own occupancy gauge."""
    refs = [ray_tpu.put(os.urandom(1 * MB)) for _ in range(6)]
    v = state.memory_summary(top_n=100)
    assert v["store_object_bytes"] >= 6 * MB
    assert v["attributed_bytes"] / v["store_object_bytes"] >= 0.95
    for nid, b in v["nodes"].items():
        # aligned shm footprint == allocator occupancy, exactly
        assert b["shm_bytes"] == b["arena_used"], (nid, b)
        usage = ray_tpu.api._worker().agent.call("store_usage")
        assert b["capacity"] == usage["capacity"]
    top = v["objects"][0]
    assert top["owner"] and top["owner"]["call_site"]
    assert not v["leaks"]["partial"]
    del refs


def test_summarize_tasks_percentiles_and_actor_methods(acct_cluster):
    @ray_tpu.remote
    def quick(i):
        return i

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    ray_tpu.get([quick.remote(i) for i in range(8)], timeout=60)
    c = Counter.remote()
    ray_tpu.get([c.incr.remote() for _ in range(3)], timeout=60)

    def summary_ready():
        tasks = state.summarize_tasks()
        rows = [v for k, v in tasks.items() if k.endswith("quick")]
        if not rows or not rows[0]["running"]:
            return False
        # the queued percentile needs the owner-side submitted_ts
        # batch, which races the executor's RUNNING/FINISHED batch —
        # wait for BOTH rows, not just running, before asserting
        if not rows[0]["queued"] or rows[0]["queued"]["count"] < 1:
            return False
        if rows[0]["running"]["count"] < 8:
            return False
        # the actor-method counts ride their own event batches: wait
        # until the store saw all 3 incr calls too, so every assertion
        # below reads settled state instead of racing the flush
        actors = state.summarize_actors()
        return any(k.endswith("incr") and n >= 3
                   for k, n in actors["methods"].items())

    _wait(summary_ready, what="task summary percentiles")
    tasks = state.summarize_tasks()
    row = next(v for k, v in tasks.items() if k.endswith("quick"))
    assert row["kind"] == "task"
    assert row["states"].get("FINISHED", 0) >= 8
    assert 0 <= row["running"]["p50_ms"] <= row["running"]["p99_ms"]
    assert row["queued"] and row["queued"]["count"] >= 1
    actors = state.summarize_actors()
    assert actors["by_state"].get("ALIVE", 0) >= 1
    assert any(k.endswith("incr") and n >= 3
               for k, n in actors["methods"].items())
    objs = state.summarize_objects()
    assert objs["total_arena_used"] >= 0 and "nodes" in objs
    ray_tpu.kill(c)


def test_http_memory_and_summary_endpoints(acct_cluster):
    ref = ray_tpu.put(b"h" * (1 * MB))
    port = _head_metrics_port()

    def fetch(path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            assert r.headers["Content-Type"].startswith("application/json")
            return json.loads(r.read())

    mem = fetch("/api/memory?top=5")
    assert mem["nodes"] and len(mem["objects"]) <= 5
    assert "leaks" in mem and "attributed_bytes" in mem
    summ = fetch("/api/summary")
    assert set(summ) >= {"tasks", "actors", "objects", "last_leak_scan"}
    del ref


def test_cli_memory_and_summary(acct_cluster, capsys):
    from ray_tpu import scripts

    w = ray_tpu.api._worker()
    addr = f"{w.head_addr[0]}:{w.head_addr[1]}"
    ref = ray_tpu.put(os.urandom(3 * MB))
    assert scripts.main(["memory", "--address", addr]) == 0
    out = capsys.readouterr().out
    assert "arena" in out and "attributed to live owners" in out
    assert os.path.basename(__file__) in out  # call-site shown
    assert scripts.main(["summary", "--address", addr]) == 0
    out = capsys.readouterr().out
    assert "tasks (per function):" in out and "objects:" in out
    assert scripts.main(["memory", "--address", addr, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert "leaks" in parsed
    del ref


# --------------------------------------------------------- leak tripwires


def test_borrowed_ref_ttl_leak_flagged_then_cleared(acct_cluster):
    """A deliberately held borrowed ref is flagged within one TTL
    interval, and the gauge returns to 0 after release."""
    @ray_tpu.remote
    class Hoarder:
        def __init__(self):
            self.held = None

        def hold(self, ref):
            self.held = ref  # keeps the BORROWED ref alive forever
            return True

        def release(self):
            self.held = None
            import gc

            gc.collect()
            return True

    h = Hoarder.remote()
    payload = ray_tpu.put(os.urandom(1 * MB))
    # pass the ref INSIDE a container so the actor deserializes and
    # keeps it (a plain arg would be consumed by the call itself)
    assert ray_tpu.get(h.hold.remote([payload]), timeout=60)

    def flagged():
        v = state.memory_summary()
        return any(e["object_id"] == payload.oid
                   for e in v["leaks"]["borrowed_ttl"])

    _wait(flagged, timeout=30, what="borrowed-TTL leak flag")
    _wait(lambda: _leaked_bytes("borrowed_ttl") > 0, timeout=20,
          what="borrowed_ttl gauge > 0")
    assert ray_tpu.get(h.release.remote(), timeout=60)
    _wait(lambda: _leaked_bytes("borrowed_ttl") == 0, timeout=30,
          what="borrowed_ttl gauge back to 0")
    ray_tpu.kill(h)
    del payload


def test_channel_slot_leak_flagged_then_cleared(acct_cluster):
    """A channel slot no live compiled graph claims (as after a skipped
    teardown) is flagged, and destroying it clears the gauge."""
    from ray_tpu.dag import channel as chmod

    spec = chmod.ChannelSpec(oid="dagch-leaktest-slot", max_in_flight=2,
                             slot_size=64 * 1024, n_readers=1,
                             writer_node="n0", reader_nodes=["n0"],
                             nodes={})
    agent = ray_tpu.api._worker().agent
    agent.call("channel_create", oid=spec.oid, size=spec.total_size(),
               header=spec.header_wire())

    def flagged():
        v = state.memory_summary()
        return any(e["object_id"] == spec.oid
                   for e in v["leaks"]["channel_slots"])

    _wait(flagged, timeout=30, what="channel-slot leak flag")
    _wait(lambda: _leaked_bytes("channel_slot") > 0, timeout=20,
          what="channel_slot gauge > 0")
    agent.call("channel_destroy", oid=spec.oid)
    _wait(lambda: _leaked_bytes("channel_slot") == 0, timeout=30,
          what="channel_slot gauge back to 0")


def test_dead_owner_leak_flagged_then_cleared(acct_cluster, tmp_path):
    """A driver that exits without freeing its plasma put leaves
    primary bytes no owner claims: flagged as dead_owner within a TTL,
    gauge back to 0 once the bytes are freed."""
    w = ray_tpu.api._worker()
    addr = f"{w.head_addr[0]}:{w.head_addr[1]}"
    oid_file = tmp_path / "leaked_oid"
    script = f"""
import os
import ray_tpu
ray_tpu.init(address={addr!r})
ref = ray_tpu.put(os.urandom(2 * 1024 * 1024))
open({str(oid_file)!r}, "w").write(ref.oid)
os._exit(0)  # hard exit: no shutdown, no free — the leak
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    subprocess.run([sys.executable, "-c", script], check=True, env=env,
                   timeout=120)
    leaked_oid = oid_file.read_text().strip()

    def flagged():
        v = state.memory_summary()
        return any(e["object_id"] == leaked_oid
                   for e in v["leaks"]["dead_owner"])

    _wait(flagged, timeout=40, what="dead-owner leak flag")
    _wait(lambda: _leaked_bytes("dead_owner") > 0, timeout=20,
          what="dead_owner gauge > 0")
    # cleanup: free the orphaned bytes; the gauge must return to 0
    w.agent.call("store_free", oids=[leaked_oid])
    _wait(lambda: _leaked_bytes("dead_owner") == 0, timeout=30,
          what="dead_owner gauge back to 0")


# (the 2-node acceptance test lives at the TOP of this module so it
# runs before the module-scoped single-node fixture is instantiated)


# ------------------------------------------------- conftest tripwire unit


def test_resource_leak_detector_units():
    """The conftest leak detector trips only when a resource's
    low-water mark rises across windows — transient teardown spikes
    never trip it, compounding growth does."""
    import conftest as cft

    grow = [(f"m{i}", 10 + i * 10, 5) for i in range(10)]
    hit = cft._monotonic_leak(grow, window=5, floor=25)
    assert hit is not None and hit[0] == "threads"
    # spikes over a flat baseline (a module snapshotted mid-teardown):
    # the floor never moves, no trip — the exact false positive the
    # per-module-delta rule had
    spiky = [("a", 10, 19), ("b", 10, 21), ("c", 10, 26), ("d", 10, 28),
             ("e", 10, 51), ("f", 10, 11), ("g", 10, 14), ("h", 10, 46),
             ("i", 10, 12), ("j", 10, 63)]
    assert cft._monotonic_leak(spiky, window=5, floor=25) is None
    # slow creep stays under the floor
    creep = [(f"m{i}", 10 + i, 5) for i in range(12)]
    assert cft._monotonic_leak(creep, window=5, floor=25) is None
    # sockets leak independently of threads
    socks = [(f"m{i}", 10, 5 + i * 10) for i in range(10)]
    assert cft._monotonic_leak(socks, window=5, floor=25)[0] == "sockets"
    # short history never trips
    assert cft._monotonic_leak(grow[:8], window=5, floor=25) is None
