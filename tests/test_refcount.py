"""Ownership + distributed refcounting tests.

Mirrors the reference's reference-counting semantics
(reference: src/ray/core_worker/reference_count.h, tested in
python/ray/tests/test_reference_counting.py): objects are freed when the
owner's last reference drops, pinned while borrowed, and survive while
contained in other objects.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def _store_contains(oid: str, retries: int = 50) -> bool:
    w = ray_tpu.api._worker()
    return w.plasma.contains(oid)


def _wait_freed(oid: str, timeout: float = 5.0) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _store_contains(oid):
            return True
        time.sleep(0.05)
    return False


def test_put_freed_on_ref_drop(cluster):
    arr = np.zeros(300_000)  # plasma-sized
    ref = ray_tpu.put(arr)
    oid = ref.oid
    assert _store_contains(oid)
    del ref
    gc.collect()
    assert _wait_freed(oid), "object not freed after last ref dropped"


def test_object_pinned_while_ref_alive(cluster):
    ref = ray_tpu.put(np.ones(300_000))
    time.sleep(0.5)
    assert _store_contains(ref.oid)
    # still retrievable
    assert float(ray_tpu.get(ref, timeout=30).sum()) == 300_000.0


def test_get_after_free_raises(cluster):
    ref = ray_tpu.put(np.ones(300_000))
    oid = ref.oid
    ref2 = ray_tpu.ObjectRef(oid, ref.owner_addr, ref.node_addr)  # alias
    del ref
    gc.collect()
    # ref2 still holds a local reference: not freed
    assert _store_contains(oid)
    del ref2
    gc.collect()
    assert _wait_freed(oid)


def test_task_return_freed_after_drop(cluster):
    @ray_tpu.remote
    def big():
        return np.zeros(400_000)

    ref = big.remote()
    val = ray_tpu.get(ref, timeout=60)
    oid = ref.oid
    assert _store_contains(oid)
    del val
    del ref
    gc.collect()
    assert _wait_freed(oid)


def test_arg_ref_pinned_during_task(cluster):
    @ray_tpu.remote
    def slow_sum(arr):
        import time as _t

        _t.sleep(1.0)
        return float(arr.sum())

    data_ref = ray_tpu.put(np.ones(300_000))
    oid = data_ref.oid
    result = slow_sum.remote(data_ref)
    del data_ref  # only the in-flight submission pins it now
    gc.collect()
    time.sleep(0.3)
    assert _store_contains(oid), "arg freed while task in flight"
    assert ray_tpu.get(result, timeout=60) == 300_000.0


def test_borrowed_ref_keeps_object_alive(cluster):
    @ray_tpu.remote
    class Keeper:
        def __init__(self):
            self.ref = None

        def hold(self, refs):
            self.ref = refs[0]  # keeps a borrowed reference alive
            return True

        def read(self):
            return float(ray_tpu.get(self.ref, timeout=30).sum())

    k = Keeper.remote()
    data = ray_tpu.put(np.ones(300_000))
    oid = data.oid
    # pass the REF itself (inside a container so it is serialized, not
    # resolved to a value)
    assert ray_tpu.get(k.hold.remote([data]), timeout=60) is True
    del data
    gc.collect()
    time.sleep(0.5)
    assert _store_contains(oid), "object freed while actor still borrows it"
    assert ray_tpu.get(k.read.remote(), timeout=30) == 300_000.0
    ray_tpu.kill(k)


def test_contained_ref_pinned_by_outer(cluster):
    inner = ray_tpu.put(np.ones(300_000))
    oid = inner.oid
    outer = ray_tpu.put({"inner": inner})
    del inner
    gc.collect()
    time.sleep(0.3)
    assert _store_contains(oid), "inner freed while outer object exists"
    back = ray_tpu.get(outer, timeout=30)
    assert float(ray_tpu.get(back["inner"], timeout=30).sum()) == 300_000.0


def test_borrowed_inline_nested_ref_stays_alive(cluster):
    """A nested ref deserialized out of an inline (small-put) container
    registers a borrow — the owner must not free it while the borrower
    holds the inner ref (reference: reference_count.h nested borrows)."""
    import gc
    import time

    import ray_tpu as rt

    inner = rt.put({"payload": 123})
    outer = rt.put([inner])  # small: memory-store path

    @rt.remote
    class Holder:
        def take(self, refs):
            self.inner = rt.get(refs[0], timeout=30)[0]  # keep inner ref
            return True

        def read(self):
            return rt.get(self.inner, timeout=30)["payload"]

    h = Holder.remote()
    assert rt.get(h.take.remote([outer]), timeout=60)
    del inner, outer  # driver drops BOTH; borrower still holds inner
    gc.collect()
    time.sleep(1.0)  # let remove_borrow/free propagation settle
    assert rt.get(h.read.remote(), timeout=60) == 123
