"""Memory monitor / OOM protection tests
(reference: src/ray/raylet/worker_killing_policy.cc +
python/ray/tests/test_memory_pressure.py — via the test-usage-file hook
so no real memory is exhausted)."""

import os
import time

import pytest

import ray_tpu


@pytest.fixture
def oom_cluster(tmp_path):
    usage_file = str(tmp_path / "usage")
    with open(usage_file, "w") as f:
        f.write("0.10")
    ray_tpu.init(
        num_cpus=2, object_store_memory=64 * 1024 * 1024,
        _system_config={
            "memory_monitor_test_usage_file": usage_file,
            "memory_usage_threshold": 0.9,
            "memory_monitor_refresh_ms": 100,
            "memory_monitor_min_kill_interval_ms": 200,
        })
    try:
        yield usage_file
    finally:
        ray_tpu.shutdown()


def test_oom_kill_retries_task(oom_cluster, tmp_path):
    usage_file = oom_cluster
    attempts = str(tmp_path / "attempts")

    @ray_tpu.remote(max_retries=2)
    def hog():
        with open(attempts, "a") as f:
            f.write("x\n")
        n = len(open(attempts).readlines())
        if n == 1:
            time.sleep(120)  # parked until the monitor kills this worker
        return n

    ref = hog.remote()
    deadline = time.time() + 30
    while not os.path.exists(attempts) and time.time() < deadline:
        time.sleep(0.1)
    assert os.path.exists(attempts), "task never started"
    with open(usage_file, "w") as f:
        f.write("0.99")  # cross the threshold: newest lease is killed
    # give the monitor time to kill, then clear the pressure
    deadline = time.time() + 30
    while len(open(attempts).readlines()) < 2 and time.time() < deadline:
        time.sleep(0.2)
    with open(usage_file, "w") as f:
        f.write("0.10")
    assert ray_tpu.get(ref, timeout=60) == 2  # retried after the OOM kill


def test_oom_kill_exhausts_retries(oom_cluster, tmp_path):
    usage_file = oom_cluster
    started = str(tmp_path / "started")

    @ray_tpu.remote(max_retries=0)
    def hog():
        open(started, "w").close()
        time.sleep(120)
        return 1

    ref = hog.remote()
    deadline = time.time() + 30
    while not os.path.exists(started) and time.time() < deadline:
        time.sleep(0.1)
    with open(usage_file, "w") as f:
        f.write("0.99")
    with pytest.raises(ray_tpu.RayError):
        ray_tpu.get(ref, timeout=60)
    with open(usage_file, "w") as f:
        f.write("0.10")
