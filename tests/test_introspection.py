"""Live-introspection tests: stack dumps, sampling profiler, driver log
streaming, and the head time-series ring.

Mirrors the reference's `ray stack` / py-spy / log-monitor surfaces
(reference: dashboard/modules/reporter/profile_manager.py:79,
scripts.py:1830 `ray stack`, _private/log_monitor.py:103) — here served
in-process over the control RPC plane (see _private/profiling.py +
_private/log_monitor.py)."""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import scripts


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=64 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


def _head():
    return ray_tpu.api._worker().head


def _head_http(path: str) -> bytes:
    port = _head().call("metrics_port")["port"]
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=30) as r:
        return r.read()


def _cluster_address() -> str:
    return "%s:%d" % tuple(ray_tpu.api._worker().head_addr)


# ------------------------------------------------------------- stack dumps


def test_stack_dump_names_spinning_task(cluster, capsys):
    """`rtpu stack <node>` must print a live traceback naming the user
    function a worker is currently spinning in — the "what is this
    worker doing right now" contract, with no py-spy/ptrace."""

    @ray_tpu.remote
    def spin_marker_fn():
        t0 = time.time()
        while time.time() - t0 < 60:
            sum(range(256))
        return 1

    ref = spin_marker_fn.remote()
    try:
        # wait until the live frame is observable at the head
        deadline = time.monotonic() + 30
        blob = ""
        while time.monotonic() < deadline:
            out = _head().call("cluster_stack", timeout=30)
            blob = json.dumps(out)
            if "spin_marker_fn" in blob:
                break
            time.sleep(0.3)
        assert "spin_marker_fn" in blob, "live frame never appeared"
        assert out.get("head", {}).get("pid")  # head dumped itself too

        # the CLI path: target the node explicitly
        node_id = next(iter(out["nodes"]))
        rc = scripts.main(["stack", node_id[:12],
                           "--address", _cluster_address()])
        printed = capsys.readouterr().out
        assert rc == 0
        assert "spin_marker_fn" in printed
        assert "worker" in printed and "agent" in printed

        # worker-id target: only the spinning worker's dump is printed
        wid = next(w for w, data in out["nodes"][node_id]["workers"].items()
                   if "spin_marker_fn" in json.dumps(data))
        rc = scripts.main(["stack", wid[:12],
                           "--address", _cluster_address()])
        printed = capsys.readouterr().out
        assert rc == 0 and "spin_marker_fn" in printed
        assert "agent (pid" not in printed

        # HTTP surface serves the same aggregation
        http_blob = json.loads(_head_http("/api/stack?target=head"))
        assert http_blob.get("head", {}).get("threads")
    finally:
        ray_tpu.cancel(ref, force=True)


def test_stack_unknown_target_fails(cluster, capsys):
    rc = scripts.main(["stack", "ffffffffffff",
                       "--address", _cluster_address()])
    capsys.readouterr()
    assert rc == 1


# ------------------------------------------------------ sampling profiler


def test_profiler_round_trip_on_busy_actor(cluster):
    """start → sample → stop on a busy actor's worker process: the
    collapsed output must attribute samples to the actor method."""

    @ray_tpu.remote
    class Busy:
        def burn(self, seconds):
            t0 = time.time()
            while time.time() - t0 < seconds:
                sum(range(512))
            return 1

    from ray_tpu.util.state import list_actors

    a = Busy.remote()
    assert ray_tpu.get(a.burn.remote(0.01), timeout=60) == 1
    info = next(x for x in list_actors() if x["state"] == "ALIVE")
    wid = info["worker_id"]

    ref = a.burn.remote(8.0)  # keep the main thread busy while sampling
    reply = _head().call("profile_target", target=wid[:12],
                         duration_s=1.0, hz=200, fmt="collapsed",
                         timeout=60)
    assert reply.get("ok"), reply
    assert reply["found"] and reply["worker_id"] == wid
    assert reply["samples"] > 10, reply
    assert "burn" in reply["profile"], reply["profile"][:2000]
    # collapsed line format: frame;frame;... <count>
    line = next(ln for ln in reply["profile"].splitlines() if "burn" in ln)
    assert line.rsplit(" ", 1)[1].isdigit()

    # speedscope output parses and carries weighted samples
    reply2 = _head().call("profile_target", target=wid[:12],
                          duration_s=0.4, hz=200, fmt="speedscope",
                          timeout=60)
    assert reply2.get("ok"), reply2
    prof = json.loads(reply2["profile"])
    assert prof["profiles"][0]["samples"]
    assert len(prof["profiles"][0]["samples"]) == \
        len(prof["profiles"][0]["weights"])
    assert ray_tpu.get(ref, timeout=60) == 1


def test_profiler_head_and_http(cluster):
    reply = _head().call("profile_target", target="head",
                         duration_s=0.3, fmt="collapsed", timeout=30)
    assert reply.get("ok") and reply["samples"] > 0
    # the head's own event loop shows up in its profile
    assert "rt-profiler" not in reply["profile"]  # sampler excludes itself
    prof = json.loads(_head_http(
        "/api/profile?target=head&duration=0.3&format=speedscope"))
    assert prof.get("$schema", "").endswith("file-format-schema.json")


def test_profiler_single_flight():
    from ray_tpu._private import profiling

    assert profiling.start_sampler(hz=50)["ok"]
    try:
        again = profiling.start_sampler(hz=50)
        assert not again["ok"] and "already" in again["error"]
        assert profiling.sampler_status()["running"]
    finally:
        out = profiling.stop_sampler()
    assert out["ok"]
    assert not profiling.sampler_status()["running"]
    assert not profiling.stop_sampler()["ok"]  # no run in flight


# ------------------------------------------------------- driver log stream


def test_worker_print_reaches_driver_within_1s(cluster, capsys):
    """The acceptance bound: a worker print() lands on the subscribed
    driver's console, (pid=, node=)-prefixed, in under a second."""
    marker = f"log-stream-marker-{os.getpid()}-{int(time.time())}"

    @ray_tpu.remote
    def quiet():
        return 1

    # warm: worker pooled, driver's init-time subscription long settled
    assert ray_tpu.get(quiet.remote(), timeout=60) == 1

    @ray_tpu.remote
    def shouty():
        print(marker)
        return 1

    assert ray_tpu.get(shouty.remote(), timeout=60) == 1
    t0 = time.monotonic()
    acc = ""
    while time.monotonic() - t0 < 1.0:
        acc += capsys.readouterr().out
        if marker in acc:
            break
        time.sleep(0.05)
    assert marker in acc, "worker stdout never reached the driver"
    assert time.monotonic() - t0 < 1.0
    line = next(ln for ln in acc.splitlines() if marker in ln)
    assert line.startswith("(pid=") and "node=" in line


def test_rtpu_logs_tail_cli(cluster, capsys):
    marker = f"cli-tail-marker-{os.getpid()}"

    @ray_tpu.remote
    def shouty():
        print(marker)
        return 1

    assert ray_tpu.get(shouty.remote(), timeout=60) == 1
    time.sleep(0.6)  # let the line hit the log file
    capsys.readouterr()
    rc = scripts.main(["logs", "--tail", "50",
                       "--address", _cluster_address()])
    out = capsys.readouterr().out
    assert rc == 0
    assert marker in out
    assert "(pid=" in out and "node=" in out


# ------------------------------------------------------- head time-series


def test_head_timeseries_ring(cluster):
    """Per-agent heartbeat gauge summaries and the head's own sampler
    both land in the bounded ring behind /api/timeseries."""
    deadline = time.monotonic() + 30
    have = set()
    while time.monotonic() < deadline:
        ts = _head().call("timeseries")
        have = {(s["node"], s["name"]) for s in ts["series"]}
        agent_lag = any(name == "loop_lag_seconds" and node != "head"
                        for node, name in have)
        if agent_lag and ("head", "loop_lag_seconds") in have:
            break
        time.sleep(0.5)
    assert agent_lag, have
    assert ("head", "loop_lag_seconds") in have, have
    assert any(name == "workers" for _, name in have), have
    for s in ts["series"]:
        for point in s["points"]:
            assert len(point) == 2 and point[0] > 0

    # HTTP surface + status --watch share the same payload
    http_ts = json.loads(_head_http("/api/timeseries"))
    assert {(s["node"], s["name"]) for s in http_ts["series"]} >= have


def test_status_watch_rpc_surfaces(cluster, capsys):
    """`rtpu status` (non-watch) still works and the watch pane's data
    dependencies (timeseries RPC) are served."""
    rc = scripts.main(["status", "--address", _cluster_address()])
    out = capsys.readouterr().out
    assert rc == 0 and "node(s)" in out
