"""End-to-end runtime tests: tasks, objects, actors across real processes.

Mirrors the reference's core API tests
(reference: python/ray/tests/test_basic.py, test_actor.py — same
behavioral surface, pytest-fixture driven per SURVEY §4).
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(scope="module")
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=96 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------------------------- tasks


def test_simple_task(cluster):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    assert ray_tpu.get(add.remote(1, 2), timeout=60) == 3


def test_many_tasks_parallel(cluster):
    @ray_tpu.remote
    def sq(x):
        return x * x

    refs = [sq.remote(i) for i in range(100)]
    assert ray_tpu.get(refs, timeout=60) == [i * i for i in range(100)]


def test_task_runs_in_separate_process(cluster):
    import os

    @ray_tpu.remote
    def pid():
        time.sleep(0.5)  # slow enough that one worker cannot drain the
        # queue even while fresh workers are still booting on a loaded host
        return os.getpid()

    pids = set(ray_tpu.get([pid.remote() for _ in range(8)], timeout=60))
    assert os.getpid() not in pids
    assert len(pids) >= 2  # multiple worker processes participated


def test_kwargs_and_ordering(cluster):
    @ray_tpu.remote
    def f(a, b, c=0, d=0):
        return (a, b, c, d)

    assert ray_tpu.get(f.remote(1, 2, d=4), timeout=30) == (1, 2, 0, 4)


def test_task_chaining(cluster):
    @ray_tpu.remote
    def sq(x):
        return x * x

    a = sq.remote(3)
    b = sq.remote(a)  # dependency resolved owner-side
    assert ray_tpu.get(b, timeout=30) == 81


def test_num_returns(cluster):
    @ray_tpu.remote(num_returns=3)
    def three():
        return 1, 2, 3

    r1, r2, r3 = three.remote()
    assert ray_tpu.get([r1, r2, r3], timeout=30) == [1, 2, 3]


def test_error_propagation(cluster):
    @ray_tpu.remote
    def boom():
        raise ValueError("bad input")

    with pytest.raises(ray_tpu.RayTaskError) as exc_info:
        ray_tpu.get(boom.remote(), timeout=30)
    assert isinstance(exc_info.value.cause, ValueError)
    assert "bad input" in exc_info.value.traceback_str


def test_error_through_dependency(cluster):
    @ray_tpu.remote
    def boom():
        raise RuntimeError("upstream")

    @ray_tpu.remote
    def consume(x):
        return x

    with pytest.raises(ray_tpu.RayError):
        ray_tpu.get(consume.remote(boom.remote()), timeout=30)


def test_nested_task_submission(cluster):
    @ray_tpu.remote
    def inner(x):
        return x + 1

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x), timeout=30) * 10

    assert ray_tpu.get(outer.remote(4), timeout=60) == 50


# ------------------------------------------------------------------- objects


def test_put_get_roundtrip(cluster):
    ref = ray_tpu.put({"a": [1, 2, 3], "b": "text"})
    assert ray_tpu.get(ref, timeout=30) == {"a": [1, 2, 3], "b": "text"}


def test_large_array_zero_copy_path(cluster):
    arr = np.arange(2_000_000, dtype=np.float32)
    ref = ray_tpu.put(arr)
    out = ray_tpu.get(ref, timeout=30)
    assert np.array_equal(out, arr)


def test_large_arg_and_return(cluster):
    @ray_tpu.remote
    def double(a):
        return a * 2

    arr = np.arange(500_000, dtype=np.float64)
    out = ray_tpu.get(double.remote(ray_tpu.put(arr)), timeout=60)
    assert np.array_equal(out, arr * 2)


def test_get_timeout(cluster):
    @ray_tpu.remote
    def slow():
        time.sleep(5)
        return 1

    with pytest.raises(ray_tpu.GetTimeoutError):
        ray_tpu.get(slow.remote(), timeout=0.2)


def test_wait(cluster):
    @ray_tpu.remote
    def delay(t):
        time.sleep(t)
        return t

    fast = [delay.remote(0.0) for _ in range(3)]
    slow = delay.remote(5.0)
    ready, pending = ray_tpu.wait(fast + [slow], num_returns=3, timeout=30)
    assert len(ready) >= 3
    assert slow in pending or len(ready) == 4


# -------------------------------------------------------------------- actors


def test_actor_basic_and_ordering(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def inc(self, n=1):
            self.v += n
            return self.v

    c = Counter.remote(10)
    out = ray_tpu.get([c.inc.remote() for _ in range(10)], timeout=60)
    assert out == list(range(11, 21))  # ordered delivery


def test_actor_state_isolated(cluster):
    @ray_tpu.remote
    class Holder:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)
            return len(self.items)

    a, b = Holder.remote(), Holder.remote()
    ray_tpu.get([a.add.remote(1), a.add.remote(2)], timeout=60)
    assert ray_tpu.get(b.add.remote(9), timeout=30) == 1


def test_named_actor(cluster):
    @ray_tpu.remote
    class Reg:
        def ping(self):
            return "pong"

    owner_handle = Reg.options(name="the-registry").remote()
    h = ray_tpu.get_actor("the-registry")
    assert ray_tpu.get(h.ping.remote(), timeout=60) == "pong"
    del owner_handle  # handle GC terminates the actor


def test_actor_handle_passed_to_task(cluster):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.v = 0

        def inc(self, n):
            self.v += n
            return self.v

    @ray_tpu.remote
    def bump(h, n):
        return ray_tpu.get(h.inc.remote(n), timeout=30)

    c = Counter.remote()
    assert ray_tpu.get(bump.remote(c, 5), timeout=60) == 5


def test_actor_constructor_error(cluster):
    @ray_tpu.remote
    class Broken:
        def __init__(self):
            raise RuntimeError("ctor failed")

        def m(self):
            return 1

    h = Broken.remote()
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(h.m.remote(), timeout=60)


def test_kill_actor(cluster):
    @ray_tpu.remote
    class Idle:
        def ping(self):
            return 1

    h = Idle.remote()
    assert ray_tpu.get(h.ping.remote(), timeout=60) == 1
    ray_tpu.kill(h)
    time.sleep(0.3)
    with pytest.raises(ray_tpu.ActorDiedError):
        ray_tpu.get(h.ping.remote(), timeout=30)


def test_actor_method_error(cluster):
    @ray_tpu.remote
    class Faulty:
        def bad(self):
            raise KeyError("nope")

        def good(self):
            return "fine"

    h = Faulty.remote()
    with pytest.raises(ray_tpu.RayTaskError):
        ray_tpu.get(h.bad.remote(), timeout=60)
    # actor survives a method error
    assert ray_tpu.get(h.good.remote(), timeout=30) == "fine"


# ------------------------------------------------------------------ cluster


def test_cluster_resources(cluster):
    total = ray_tpu.cluster_resources()
    assert total.get("CPU") == 4.0


def test_infeasible_task_errors(cluster):
    @ray_tpu.remote(num_cpus=64)
    def heavy():
        return 1

    with pytest.raises(ray_tpu.SchedulingError):
        ray_tpu.get(heavy.remote(), timeout=60)


def test_small_put_stays_in_memory_store(cluster):
    """Small puts skip plasma (reference: memory_store.cc direct-call
    threshold); borrowers receiving the ref inside a container resolve
    the value inline from the owner."""
    import ray_tpu as rt

    r = rt.put({"k": list(range(40))})
    w = rt.api._worker()
    assert w.memory.known(r.oid)          # owner-side in-process value
    assert r.oid not in w._locations      # never touched plasma
    assert rt.get(r, timeout=30)["k"][5] == 5

    @rt.remote
    def direct(d):                        # inlined as a task arg
        return sum(d["k"])

    assert rt.get(direct.remote(r), timeout=60) == sum(range(40))

    @rt.remote
    class Borrower:                       # ref inside a container
        def read(self, refs):
            return rt.get(refs[0], timeout=30)["k"][-1]

    b = Borrower.remote()
    assert rt.get(b.read.remote([r]), timeout=60) == 39

    big = rt.put(b"x" * (1024 * 1024))    # large: plasma as before
    assert big.oid in w._locations
    assert rt.get(big, timeout=30) == b"x" * (1024 * 1024)


def test_async_tasks_and_actor_methods(cluster):
    """async def tasks and actor methods run to completion; an actor
    with max_concurrency overlaps async waits across calls, and
    loop-bound state created in one call works in later calls
    (reference: async actors — one shared event loop)."""
    @ray_tpu.remote
    async def atask(x):
        import asyncio as _a

        await _a.sleep(0.05)
        return x * 3

    assert ray_tpu.get(atask.remote(14), timeout=60) == 42

    @ray_tpu.remote(max_concurrency=4)
    class AsyncActor:
        async def setup(self):
            import asyncio as _a

            self.lock = _a.Lock()  # loop-bound resource
            return True

        async def slow_echo(self, v):
            import asyncio as _a

            async with self.lock:  # must be usable from ANY later call
                pass
            await _a.sleep(0.4)
            return v

    a = AsyncActor.remote()
    assert ray_tpu.get(a.setup.remote(), timeout=60)
    t0 = time.time()
    out = ray_tpu.get([a.slow_echo.remote(i) for i in range(4)], timeout=60)
    wall = time.time() - t0
    assert sorted(out) == [0, 1, 2, 3]
    assert wall < 1.3, f"async calls did not overlap: {wall:.2f}s"
