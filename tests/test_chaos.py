"""Chaos fault-injection plane + recovery-under-churn tests.

Covers the deterministic injection engine (seeded schedules reproduce,
injected clocks keep unit tests sleep-free), the RPC/bulk-transfer
injection sites, head→agent rule gossip, stateful actor restarts
(``__rt_save__``/``__rt_restore__`` resume a killed actor's state),
Serve graceful degradation (dead-replica retry, bounded replica health
checks), workflow durability across a chaos-killed step, and the typed
compiled-graph death error.

Multi-second churn scenarios are marked ``slow`` so the tier-1 budget
holds; everything else is fast and deterministic.
"""

import asyncio
import os
import time

import pytest

import ray_tpu
from ray_tpu._private import fault_injection as fi


@pytest.fixture(autouse=True)
def _clean_chaos():
    fi.clear()
    fi.set_timers()
    yield
    fi.clear()
    fi.set_timers()


@pytest.fixture
def cluster():
    ray_tpu.init(num_cpus=4, object_store_memory=128 * 1024 * 1024)
    try:
        yield ray_tpu
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------------------- unit: engine


def test_seeded_schedule_reproducible():
    """The same seed must compile to the SAME failure schedule — and a
    different seed to a different one — so a chaos run is replayable."""
    s1 = fi.make_schedule(42, ["rpc.send", "xfer.send"], events_per_site=4)
    s2 = fi.make_schedule(42, ["rpc.send", "xfer.send"], events_per_site=4)
    strip = lambda rules: [dict(r, rule_id="") for r in rules]  # noqa: E731
    assert strip(s1) == strip(s2)
    s3 = fi.make_schedule(43, ["rpc.send", "xfer.send"], events_per_site=4)
    assert strip(s1) != strip(s3)
    # installing the schedule reproduces the same DECISION sequence too
    def decisions():
        fi.install(fi.make_schedule(7, ["rpc.send"], events_per_site=3,
                                    span=20))
        seq = [fi.decide("rpc.send") is not None for _ in range(20)]
        fi.clear()
        return seq

    first = decisions()
    assert first == decisions()
    assert sum(first) == 3  # exactly events_per_site firings in the span


def test_probabilistic_rule_deterministic_and_bounded():
    fi.inject("rpc.send", "drop", p=0.5, seed=11)
    seq1 = [fi.decide("rpc.send") is not None for _ in range(30)]
    fi.clear()
    fi.inject("rpc.send", "drop", p=0.5, seed=11)
    seq2 = [fi.decide("rpc.send") is not None for _ in range(30)]
    assert seq1 == seq2
    fi.clear()
    # count caps total firings; target filters by site key
    fi.inject("rpc.send", "sever", count=2, target="head")
    assert fi.decide("rpc.send", "agent:push") is None
    assert fi.decide("rpc.send", "head:heartbeat") is not None
    assert fi.decide("rpc.send", "head:heartbeat") is not None
    assert fi.decide("rpc.send", "head:heartbeat") is None  # exhausted


def test_unknown_site_and_action_rejected():
    with pytest.raises(ValueError):
        fi.inject("rpc.bogus", "drop")
    with pytest.raises(ValueError):
        fi.inject("rpc.send", "explode")


def test_gray_failure_sites_parse_and_schedule():
    """The gray-failure sites (worker.stall busy-hang, head.kill self-
    SIGKILL) parse, round-trip the wire form, and ride make_schedule
    with their default actions."""
    r = fi.ChaosRule(site="worker.stall", action="stall", delay_s=2.0,
                     target="w-abc")
    assert fi.ChaosRule.from_wire(r.to_wire()).to_wire() == r.to_wire()
    k = fi.ChaosRule(site="head.kill", action="kill")
    assert k.matches("head.kill", "head")
    assert not k.matches("worker.kill", "head")
    sched = fi.make_schedule(5, ["worker.stall", "head.kill"],
                             events_per_site=2)
    actions = {d["site"]: d["action"] for d in sched}
    assert actions == {"worker.stall": "stall", "head.kill": "kill"}


def test_head_kill_rule_gossips_without_firing(cluster):
    """head.kill installs through the head chaos RPC and gossips to
    agents like any rule; a non-matching target must never fire (the
    head stays alive) while status still lists it."""
    w = ray_tpu.api._worker()
    w.head.call("chaos", op="inject",
                rule={"site": "head.kill", "action": "kill",
                      "target": "no-such-head", "count": 1},
                timeout=30)
    st = w.head.call("chaos", op="status", timeout=30)
    assert any(r["site"] == "head.kill" and r["fired"] == 0
               for r in st["rules"]), st
    # the head is demonstrably still alive and serving
    assert w.head.call("ping", timeout=10) is not None
    # gossip: the agent acked the rule-set version via heartbeat (the
    # version is echoed back in chaos status after a beat)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if fi.status()["version"]:  # driver side untouched; check agent
            pass
        hb = w.head.call("chaos", op="status", timeout=10)
        if hb["version"] == st["version"]:
            break
        time.sleep(0.2)
    w.head.call("chaos", op="clear", timeout=30)


def test_injected_clock_no_real_sleep():
    """Delay decisions route through the injected clock — churn unit
    tests never really sleep."""
    slept = []
    fi.set_timers(sleep=slept.append)
    fi.inject("lease.grant", "delay", delay_s=123.0)
    d = fi.decide("lease.grant")
    t0 = time.monotonic()
    fi.sleep_sync(d.delay_s)
    asyncio.run(fi.sleep_async(d.delay_s))
    assert time.monotonic() - t0 < 1.0
    assert slept == [123.0, 123.0]


# ------------------------------------------------------------ site: rpc plane


def test_rpc_sites_drop_delay_sever():
    """Drive a live RpcServer/RpcClient pair through drop (request times
    out), delay (succeeds, after the injected clock saw the delay), and
    sever (typed ConnectionLost) on both the send and recv sites."""
    from ray_tpu._private.rpc import (ConnectionLost, RpcClient, RpcHost,
                                      RpcServer)

    class Host(RpcHost):
        async def rpc_echo(self, x):
            return {"x": x}

    async def drive():
        server = RpcServer(Host())
        port = await server.start()
        client = RpcClient("127.0.0.1", port, label="t")
        try:
            assert (await client.call("echo", x=1))["x"] == 1
            # drop on send: the frame never leaves the client
            fi.inject("rpc.send", "drop", count=1)
            with pytest.raises(asyncio.TimeoutError):
                await client.call("echo", x=2, timeout=0.3)
            assert (await client.call("echo", x=3))["x"] == 3
            # drop on recv: the server reads the frame, never dispatches
            fi.clear()
            fi.inject("rpc.recv", "drop", count=1, target="echo")
            with pytest.raises(asyncio.TimeoutError):
                await client.call("echo", x=4, timeout=0.3)
            assert (await client.call("echo", x=5))["x"] == 5
            # delay via the injected clock: no real wait, call succeeds
            fi.clear()
            slept = []
            fi.set_timers(sleep=slept.append)
            fi.inject("rpc.send", "delay", delay_s=9.0, count=1)
            assert (await client.call("echo", x=6, timeout=5))["x"] == 6
            assert slept == [9.0]
            fi.set_timers()
            # sever: typed connection loss; reconnect-on-demand recovers
            fi.clear()
            fi.inject("rpc.send", "sever", count=1)
            with pytest.raises(ConnectionLost):
                await client.call("echo", x=7)
            assert (await client.call("echo", x=8))["x"] == 8
        finally:
            await client.close()
            await server.stop()

    asyncio.run(drive())


# ----------------------------------------------------- site: bulk object plane


class _FakeEntry:
    def __init__(self, offset, size):
        self.sealed = True
        self.size = size
        self.offset = offset
        self.location = "shm"
        self.last_used = 0.0
        self.channel = False


class _FakeArena:
    def __init__(self, buf):
        self.view = memoryview(buf)


class _FakeStore:
    def __init__(self, payload):
        self.arena = _FakeArena(bytearray(payload))
        self.objects = {"oid1": _FakeEntry(0, len(payload))}


def test_xfer_truncate_and_corrupt():
    """Holder-side chaos: a truncated range dies mid-payload exactly
    like a holder crash (TransferError → the alt-source/fallback retry
    machinery sees the same signal), and corrupt flips payload bytes
    without touching the holder's arena."""
    from ray_tpu._private.object_transfer import (ObjectTransferClient,
                                                  ObjectTransferServer,
                                                  TransferError)

    payload = bytes(range(256)) * 64  # 16 KB
    store = _FakeStore(payload)
    server = ObjectTransferServer(store)

    async def drive():
        port = await server.start()
        client = ObjectTransferClient("127.0.0.1", port)
        try:
            dest = bytearray(len(payload))
            await client.fetch_into("oid1", memoryview(dest))
            assert bytes(dest) == payload
            # count=2: the puller's stale-pool retry gets a second
            # attempt on a fresh stream — a single truncation is healed
            # by that machinery, so verify it first, then exhaust it
            fi.inject("xfer.send", "truncate", count=1)
            healed = bytearray(len(payload))
            await client.fetch_into("oid1", memoryview(healed))
            assert bytes(healed) == payload
            fi.clear()
            fi.inject("xfer.send", "truncate", count=2)
            with pytest.raises(TransferError):
                await client.fetch_into("oid1", memoryview(dest))
            fi.clear()
            fi.inject("xfer.send", "corrupt", count=1)
            dest2 = bytearray(len(payload))
            await client.fetch_into("oid1", memoryview(dest2))
            assert bytes(dest2) != payload       # corrupted on the wire
            assert bytes(store.arena.view) == payload  # source untouched
            fi.clear()
            dest3 = bytearray(len(payload))
            await client.fetch_into("oid1", memoryview(dest3))
            assert bytes(dest3) == payload
        finally:
            client.close()
            await server.stop()

    asyncio.run(drive())


# ------------------------------------------------- cluster: gossip + restarts


def _head(rt):
    return rt.api._worker().head


def test_chaos_rpc_status_and_clear(cluster):
    head = _head(ray_tpu)
    r = head.call("chaos", op="inject",
                  rule={"site": "lease.grant", "action": "delay",
                        "delay_s": 0.0, "count": 0})
    assert r["version"] >= 1 and len(r["rules"]) == 1
    r = head.call("chaos", op="schedule", seed=5, sites=["rpc.send"],
                  events_per_site=2, span=10)
    assert len(r["rules"]) == 2
    assert r["rules"][1]["at"] is not None
    r = head.call("chaos", op="clear")
    assert r["rules"] == []
    assert head.call("chaos", op="status")["rules"] == []


def test_stateful_actor_restart_restores_state(cluster):
    """Acceptance: a stateful actor with __rt_save__/__rt_restore__
    provably resumes its pre-kill state after max_restarts recovery —
    the kill delivered through the chaos plane (head RPC → agent
    SIGKILLs the worker)."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

        def __rt_save__(self):
            return {"n": self.n}

        def __rt_restore__(self, state):
            self.n = state["n"]

    a = Counter.options(max_restarts=1, max_task_retries=2).remote()
    for expect in (1, 2, 3):
        assert ray_tpu.get(a.incr.remote(), timeout=60) == expect
    head = _head(ray_tpu)
    info = head.call("get_actor_info", actor_id=a._actor_id)
    assert info["state"] == "ALIVE"
    instance, worker_id = info["instance"], info["worker_id"]
    head.call("chaos", op="inject",
              rule={"site": "worker.kill", "action": "kill",
                    "target": worker_id, "count": 1})
    # wait for the restart to land (RESTARTING → ALIVE, instance bumped)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        info = head.call("get_actor_info", actor_id=a._actor_id)
        if info["state"] == "ALIVE" and info["instance"] > instance:
            break
        time.sleep(0.1)
    assert info["instance"] > instance, info
    # NOT 1: the restarted instance restored n=3 before serving again
    assert ray_tpu.get(a.incr.remote(), timeout=60) == 4
    # restart budget consumed exactly once
    assert head.call("list_actors")["actors"], "actor table empty?"


def test_actor_without_hooks_restarts_fresh(cluster):
    """Opt-in means opt-in: no hooks → a restarted actor starts from
    __init__ exactly as before this feature."""

    @ray_tpu.remote
    class Plain:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    a = Plain.options(max_restarts=1, max_task_retries=2).remote()
    assert ray_tpu.get(a.incr.remote(), timeout=60) == 1
    head = _head(ray_tpu)
    info = head.call("get_actor_info", actor_id=a._actor_id)
    head.call("chaos", op="inject",
              rule={"site": "worker.kill", "action": "kill",
                    "target": info["worker_id"], "count": 1})
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        cur = head.call("get_actor_info", actor_id=a._actor_id)
        if cur["state"] == "ALIVE" and cur["instance"] > info["instance"]:
            break
        time.sleep(0.1)
    assert ray_tpu.get(a.incr.remote(), timeout=60) == 1  # fresh state


# ------------------------------------------------------------ serve satellites


def test_serve_replica_health_timeout_typed_error(cluster, monkeypatch):
    """A wedged replica constructor fails the deploy with a typed
    DeploymentFailedError after serve_replica_health_timeout_s — not a
    10-minute stall (the old hardcoded 600)."""
    from ray_tpu import serve

    monkeypatch.setenv("RT_SERVE_REPLICA_HEALTH_TIMEOUT_S", "3")

    @serve.deployment(name="wedged")
    class Wedged:
        def __init__(self):
            time.sleep(600)

        def __call__(self, x):
            return x

    t0 = time.monotonic()
    with pytest.raises(ray_tpu.DeploymentFailedError):
        serve.run(Wedged.bind())
    assert time.monotonic() - t0 < 60
    serve.shutdown()


def test_serve_handle_retries_dead_replica(cluster):
    """Graceful degradation: with two replicas, chaos-killing one's
    worker mid-service leaves call_async answering from the survivor —
    no ActorDiedError escapes to the client."""
    from ray_tpu import serve

    @serve.deployment(name="pair", num_replicas=2)
    def pair(x):
        return {"pid": os.getpid()}

    handle = serve.run(pair.bind())
    head = _head(ray_tpu)

    async def call():
        return await handle.call_async({"q": 1}, _timeout=60)

    assert asyncio.run(call())["pid"] > 0
    replicas = [a for a in head.call("list_actors")["actors"]
                if a.get("name", "").startswith("serve:pair")
                and a["state"] == "ALIVE"]
    assert len(replicas) == 2
    head.call("chaos", op="inject",
              rule={"site": "worker.kill", "action": "kill",
                    "target": replicas[0]["worker_id"], "count": 1})
    # every call during the outage window must still succeed
    deadline = time.monotonic() + 4
    while time.monotonic() < deadline:
        assert asyncio.run(call())["pid"] > 0
        time.sleep(0.05)
    serve.shutdown()


# ------------------------------------------------------- compiled-graph poison


def test_dag_chaos_kill_raises_actor_died(cluster):
    """Killing a compiled-graph actor's worker through the chaos plane
    surfaces a typed ActorDiedError from in-flight gets — never a
    hang."""
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class Stage:
        def step(self, x):
            return x + 1

    with InputNode() as inp:
        out = Stage.bind().step.bind(inp)
    graph = out.experimental_compile(use_channels=True)
    try:
        assert graph.execute(1).get(timeout=60) == 2
        head = _head(ray_tpu)
        stage = next(a for a in head.call("list_actors")["actors"]
                     if a["state"] == "ALIVE" and not a.get("name"))
        head.call("chaos", op="inject",
                  rule={"site": "worker.kill", "action": "kill",
                        "target": stage["worker_id"], "count": 1})
        with pytest.raises(ray_tpu.ActorDiedError):
            for _ in range(200):  # the kill lands within the monitor tick
                graph.execute(1).get(timeout=10)
                time.sleep(0.05)
    finally:
        graph.teardown()


# ------------------------------------------------------- workflow durability


def test_workflow_resumes_after_chaos_kill(cluster, tmp_path):
    """A workflow whose executing worker is chaos-killed mid-step
    resumes and replays ONLY unpersisted steps (ROADMAP item 5)."""
    from ray_tpu import workflow

    workflow.init(str(tmp_path / "wf"))
    runs = tmp_path / "runs"
    runs.mkdir()

    @ray_tpu.remote
    def first(x):
        with open(runs / "first", "a") as f:
            f.write("x")
        return x + 1

    @ray_tpu.remote(max_retries=0)
    def flaky(x):
        with open(runs / "flaky", "a") as f:
            f.write("x")
        marker = runs / "killed"
        if not marker.exists():
            marker.write_text("1")
            # chaos-kill THIS worker mid-step, then wait for the axe
            import ray_tpu as rt

            rt.api._worker().head.call(
                "chaos", op="inject",
                rule={"site": "worker.kill", "action": "kill",
                      "target": os.environ["RT_WORKER_ID"], "count": 1})
            time.sleep(60)
        return x * 10

    dag = flaky.bind(first.bind(1))
    with pytest.raises(ray_tpu.RayError):
        workflow.run(dag, workflow_id="churn")
    assert workflow.get_status("churn") == "FAILED"
    # resume: first's persisted value is replayed, flaky re-executes
    assert workflow.resume("churn") == 20
    assert (runs / "first").read_text() == "x"    # never re-ran
    assert (runs / "flaky").read_text() == "xx"   # killed once + clean run


# ----------------------------------------------- reconstruction give-up detail


@pytest.mark.slow
def test_reconstruction_giveup_names_lost_objects():
    """When lineage reconstruction is out of budget, the error names the
    unrecoverable object AND its producing task so operators can tell
    what was lost."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 2})
    doomed = cluster.add_node(num_cpus=2, resources={"doomed": 1})
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(2)
        import numpy as np

        @ray_tpu.remote(max_retries=0, resources={"doomed": 0.01})
        def produce():
            return np.ones(300_000)  # plasma-sized

        ref = produce.remote()
        ray_tpu.wait([ref], num_returns=1, timeout=60)
        cluster.remove_node(doomed)  # SIGKILL: the only copy dies
        with pytest.raises(ray_tpu.ObjectLostError) as ei:
            ray_tpu.get(ref, timeout=60)
        msg = str(ei.value)
        assert ref.oid[:16] in msg
        assert "produced by task" in msg
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()


# -------------------------------------------------- slow: serve under churn


@pytest.mark.slow
def test_serve_availability_agent_sigkill_under_load():
    """E2E churn: one of two agents SIGKILLed under steady HTTP load;
    availability stays >= 99% and the controller re-heals the replica
    set (the bench chaos_recovery phase, as a regression test)."""
    import json
    import urllib.request

    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(head_node_args={"num_cpus": 4})
    workers = [cluster.add_node(num_cpus=0, resources={"chaos": 2})
               for _ in range(2)]
    ray_tpu.init(address=cluster.address)
    try:
        cluster.wait_for_nodes(3)

        @serve.deployment(name="churn_echo", num_replicas=2,
                          ray_actor_options={
                              "num_cpus": 0, "resources": {"chaos": 1},
                              "scheduling_strategy": "SPREAD"})
        def churn_echo(x):
            return {"ok": 1}

        serve.run(churn_echo.bind())
        host, port = serve.start_http()
        actors = _head(ray_tpu).call("list_actors")["actors"]
        replica_nodes = {a["node_id"] for a in actors
                         if a.get("name", "").startswith("serve:churn_echo")}
        victim = next(w for w in workers if w.node_id in replica_nodes)
        ok = total = 0
        t0 = time.monotonic()
        killed = False
        while time.monotonic() - t0 < 6.0:
            if not killed and time.monotonic() - t0 > 1.5:
                cluster.remove_node(victim)
                killed = True
            total += 1
            try:
                with urllib.request.urlopen(
                        f"http://{host}:{port}/churn_echo?x=1",
                        timeout=30) as r:
                    ok += json.loads(r.read()).get("ok", 0)
            except Exception:
                pass
        assert killed
        assert 100.0 * ok / total >= 99.0, (ok, total)
        # controller re-heals the second replica on the surviving node
        from ray_tpu.serve import api as serve_api

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            counts = ray_tpu.get(
                serve_api._controller().list_deployments.remote(),
                timeout=30)
            if counts.get("churn_echo", 0) >= 2:
                break
            time.sleep(0.2)
        assert counts.get("churn_echo", 0) >= 2
        serve.shutdown_http()
        serve.shutdown()
    finally:
        ray_tpu.shutdown()
        cluster.shutdown()
