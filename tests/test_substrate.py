"""Tests for IDs, config, serialization, RPC (layer L1)."""

import asyncio

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.config import config
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID
from ray_tpu._private.rpc import (
    EventLoopThread,
    RpcClient,
    RpcError,
    RpcHost,
    RpcServer,
    SyncRpcClient,
)


class TestIDs:
    def test_lineage_embedding(self):
        job = JobID.from_int(7)
        actor = ActorID.of(job)
        assert actor.job_id() == job
        task = TaskID.for_actor_task(actor)
        assert task.actor_id() == actor
        assert task.job_id() == job
        obj = ObjectID.from_index(task, 1)
        assert obj.task_id() == task
        assert obj.index() == 1
        assert obj.job_id() == job

    def test_normal_task_has_nil_actor(self):
        task = TaskID.for_normal_task(JobID.from_int(3))
        assert task.actor_id().binary()[:12] == b"\x00" * 12
        assert task.job_id() == JobID.from_int(3)

    def test_hex_roundtrip_and_hash(self):
        n = NodeID.from_random()
        assert NodeID.from_hex(n.hex()) == n
        assert len({n, NodeID.from_hex(n.hex())}) == 1
        assert not n.is_nil()
        assert NodeID.nil().is_nil()


class TestConfig:
    def test_defaults_and_env_override(self, monkeypatch):
        assert config.max_direct_call_object_size == 100 * 1024
        monkeypatch.setenv("RT_MAX_DIRECT_CALL_OBJECT_SIZE", "5")
        assert config.max_direct_call_object_size == 5

    def test_unknown_key_rejected(self):
        with pytest.raises(AttributeError):
            config.not_a_real_key


class TestSerialization:
    def test_roundtrip_python(self):
        val = {"a": [1, 2, (3, "x")], "b": None}
        data = serialization.serialize_to_bytes(val)
        assert serialization.deserialize(data) == val

    def test_numpy_out_of_band_zero_copy(self):
        arr = np.arange(1 << 16, dtype=np.float32)
        frames, size = serialization.serialize(arr)
        # array payload must be out-of-band, not inside the pickle frame
        assert len(frames) >= 2
        assert frames[0].nbytes < 4096
        buf = bytearray(size)
        serialization.pack_into(frames, memoryview(buf))
        out = serialization.deserialize(memoryview(buf))
        np.testing.assert_array_equal(out, arr)
        # zero-copy: deserialized array views into the packed buffer
        assert out.base is not None

    def test_alignment(self):
        # Frame offsets are 64-aligned relative to the buffer start; absolute
        # alignment additionally requires an aligned base (the shm store
        # allocates 64-aligned, heap bytes do not guarantee it).
        arr = np.ones(1000, dtype=np.float64)
        data = serialization.serialize_to_bytes(("pre", arr))
        mv = memoryview(data)
        frames = serialization.unpack_frames(mv)
        base = np.frombuffer(data, dtype=np.uint8).ctypes.data
        for f in frames[1:]:
            off = np.frombuffer(f, dtype=np.uint8).ctypes.data - base
            assert off % 64 == 0

    def test_closure(self):
        x = 41

        def f(y):
            return x + y

        g = serialization.deserialize(serialization.serialize_to_bytes(f))
        assert g(1) == 42


class _EchoHost(RpcHost):
    def __init__(self):
        self.pushes = []

    async def rpc_echo(self, value=None):
        return {"value": value}

    async def rpc_fail(self):
        raise ValueError("boom")

    async def rpc_note(self, value=None, _conn=None):
        self.pushes.append(value)

    async def rpc_push_back(self, _conn=None):
        await _conn.push("server_event", {"n": 1})
        return {}


class TestRpc:
    def test_request_reply_and_error(self):
        async def main():
            host = _EchoHost()
            server = RpcServer(host)
            port = await server.start()
            client = RpcClient("127.0.0.1", port)
            out = await client.call("echo", value={"k": [1, 2, b"raw"]})
            assert out == {"value": {"k": [1, 2, b"raw"]}}
            with pytest.raises(RpcError, match="boom"):
                await client.call("fail")
            # concurrency: many in-flight requests on one connection
            outs = await asyncio.gather(
                *[client.call("echo", value=i) for i in range(50)]
            )
            assert [o["value"] for o in outs] == list(range(50))
            await client.close()
            await server.stop()

        asyncio.run(main())

    def test_oneway_and_server_push(self):
        async def main():
            host = _EchoHost()
            server = RpcServer(host)
            port = await server.start()
            got = asyncio.Event()
            events = []

            def on_push(method, payload):
                events.append((method, payload))
                got.set()

            client = RpcClient("127.0.0.1", port, on_push=on_push)
            await client.oneway("note", value="hello")
            await client.call("push_back")
            await asyncio.wait_for(got.wait(), 5)
            assert host.pushes == ["hello"]
            assert events == [("server_event", {"n": 1})]
            await client.close()
            await server.stop()

        asyncio.run(main())

    def test_sync_client_from_main_thread(self):
        io = EventLoopThread()
        host = _EchoHost()
        server = RpcServer(host)
        port = io.run(server.start())
        client = SyncRpcClient("127.0.0.1", port, io)
        assert client.call("echo", value=9) == {"value": 9}
        client.close()
        io.run(server.stop())
        io.stop()
