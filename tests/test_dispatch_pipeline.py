"""Burst-independent dispatch pipeline regression tests.

Round-5 verdict top finding: a 2000-task sync burst trained the owner's
per-function round-trip EWMA into permanently serializing async
dispatch (~5k/s -> ~1.5k/s).  Depth now derives from worker-reported
EXECUTION time with time-windowed decay, so throughput is
history-independent — asserted here structurally (estimator state and
pipeline depth), never via wall-clock throughput.
"""

import os
import time

import ray_tpu
from ray_tpu._private.worker import (_PIPELINE_BUDGET_S, _PIPELINE_DEPTH_MAX,
                                     _SERVICE_WINDOW_S, _WARM_LEASE_TTL_S,
                                     _ServiceStats)


class TestServiceStats:
    def test_depth_curve_is_continuous(self):
        """depth = budget / measured execution time, clamped — not the
        old 1-or-24 cliff."""
        cases = [
            (0.0005, _PIPELINE_DEPTH_MAX),   # sub-ms: full pipeline
            (0.004, 6),                      # 24ms budget / 4ms tasks
            (0.012, 2),
            (0.048, 1),                      # slower than the budget
        ]
        for exec_s, want in cases:
            s = _ServiceStats()
            t = s.rotated_at
            s.observe(exec_s, now=t)  # one sample: no float accumulation
            assert s.depth(now=t) == want, (exec_s, want)

    def test_unmeasured_class_probes_at_depth_one(self):
        s = _ServiceStats()
        assert s.mean(now=s.rotated_at) is None
        assert s.depth(now=s.rotated_at) == 1

    def test_history_ages_out_on_the_window_horizon(self):
        """The estimator can never be stuck by history: with no fresh
        samples for two windows, everything measured is stale and the
        next samples fully determine depth."""
        s = _ServiceStats()
        t = s.rotated_at
        for _ in range(500):
            s.observe(0.5, now=t)  # a slow (burst-shaped) regime
        assert s.depth(now=t) == 1
        t2 = t + 2 * _SERVICE_WINDOW_S + 0.01
        assert s.mean(now=t2) is None  # fully decayed, no sample needed
        for _ in range(16):
            s.observe(0.0005, now=t2)
        assert s.depth(now=t2) == _PIPELINE_DEPTH_MAX

    def test_previous_window_weight_is_capped(self):
        """A window stuffed with thousands of samples weighs at most as
        much as a window's worth of fresh ones — a huge burst cannot
        outvote the current regime forever."""
        s = _ServiceStats()
        t = s.rotated_at
        for _ in range(5000):
            s.observe(0.1, now=t)
        t2 = t + _SERVICE_WINDOW_S + 0.01
        for _ in range(32):
            s.observe(0.001, now=t2)
        # prev contributes min(5000, 32) samples of weight: mean is the
        # midpoint-ish blend, NOT ~0.1 as an unweighted pool would give
        assert s.mean(now=t2) < 0.06


def test_dispatch_depth_recovers_after_sync_burst(local_cluster):
    """After a pure sync burst (every call a blocking round trip), the
    pipeline depth for the class must reflect sub-ms EXECUTION time —
    the old round-trip EWMA left it serialized at depth 1."""

    @ray_tpu.remote
    def quick():
        return 1

    for _ in range(60):
        assert ray_tpu.get(quick.remote(), timeout=60) == 1
    w = ray_tpu.api._worker()
    states = [s for s in w._sched.values() if s.stats.samples()]
    assert states, "no execution-time samples reached the owner"
    depth = max(s.stats.depth() for s in states)
    assert depth >= 4, (
        f"dispatch still serialized after sync burst: depth={depth}, "
        f"mean={[s.stats.mean() for s in states]}")
    # and the async batch right after the burst completes normally
    out = ray_tpu.get([quick.remote() for _ in range(200)], timeout=120)
    assert out == [1] * 200


def test_result_frames_carry_execution_time(local_cluster):
    """Owner-side service stats are fed from the exec_s field workers
    stamp on every result frame (never the owner round trip)."""

    @ray_tpu.remote
    def sleepy():
        time.sleep(0.05)
        return 1

    ray_tpu.get([sleepy.remote() for _ in range(4)], timeout=60)
    w = ray_tpu.api._worker()
    means = [s.stats.mean() for s in w._sched.values()
             if s.stats.samples()]
    assert means
    # measured execution time includes the sleep
    assert max(means) >= 0.05


def test_warm_lease_pool_adopts_across_functions(local_cluster):
    """An idle lease parks in the warm pool keyed by resource shape —
    a DIFFERENT function of the same shape adopts it without an agent
    round trip (the old per-class linger kept it invisible)."""

    @ray_tpu.remote
    def first():
        return os.getpid()

    @ray_tpu.remote
    def second():
        return os.getpid()

    pid1 = ray_tpu.get(first.remote(), timeout=60)
    w = ray_tpu.api._worker()
    before = w._warm_adopted
    time.sleep(0.05)  # well inside _WARM_LEASE_TTL_S
    pid2 = ray_tpu.get(second.remote(), timeout=60)
    assert w._warm_adopted > before, \
        "second function did not adopt the parked warm lease"
    assert pid2 == pid1  # same leased worker process


def test_warm_lease_pool_returns_on_ttl(local_cluster):
    """Leases nobody re-adopts go back to their agent after the TTL —
    the pool cannot pin cluster resources indefinitely."""

    @ray_tpu.remote
    def job():
        return 1

    assert ray_tpu.get(job.remote(), timeout=60) == 1
    w = ray_tpu.api._worker()
    deadline = time.monotonic() + 2.0
    parked = False
    while time.monotonic() < deadline:
        if any(w._warm_leases.values()):
            parked = True
            break
        time.sleep(0.01)
    assert parked, "idle lease never reached the warm pool"
    deadline = time.monotonic() + 4 * _WARM_LEASE_TTL_S + 3.0
    while time.monotonic() < deadline and any(w._warm_leases.values()):
        time.sleep(0.05)
    assert not any(w._warm_leases.values()), "warm lease outlived its TTL"
    assert w._warm_returned >= 1
