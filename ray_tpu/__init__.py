"""ray_tpu: a TPU-native distributed framework with Ray's capabilities.

Core surface (reference: python/ray/__init__.py):
    init, shutdown, remote, get, put, wait, kill, get_actor,
    cluster_resources, available_resources, nodes, is_initialized,
    ObjectRef, ActorHandle, exceptions.
"""

from ray_tpu._private.errors import (ActorDiedError, ActorUnavailableError,
                                     DeadlineExceededError,
                                     DeploymentFailedError, GetTimeoutError,
                                     ObjectFreedError, ObjectLostError,
                                     OutOfMemoryError, PoisonedTaskError,
                                     RayError, RayTaskError, RayWorkerError,
                                     RuntimeEnvSetupError, SchedulingError,
                                     TaskCancelledError)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.streaming import ObjectRefGenerator
from ray_tpu.api import (ActorClass, ActorHandle, RemoteFunction,
                         available_resources, cancel, cluster_resources, get,
                         get_actor, get_async, init, is_initialized, kill,
                         method, nodes, put, remote, shutdown, wait)

__version__ = "0.2.0"

__all__ = [
    "init", "shutdown", "remote", "get", "get_async", "put", "wait",
    "kill", "cancel",
    "get_actor", "method", "cluster_resources", "available_resources",
    "nodes", "is_initialized", "ObjectRef", "ObjectRefGenerator",
    "ActorHandle", "ActorClass", "RemoteFunction",
    "RayError", "RayTaskError", "RayWorkerError", "ActorDiedError",
    "ActorUnavailableError", "ObjectLostError", "ObjectFreedError",
    "GetTimeoutError", "SchedulingError", "RuntimeEnvSetupError",
    "TaskCancelledError", "DeploymentFailedError", "DeadlineExceededError",
    "OutOfMemoryError", "PoisonedTaskError",
    "__version__",
]
