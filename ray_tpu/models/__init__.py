"""Model zoo for the TPU-native stack (flagship: Llama-family decoder)."""

from ray_tpu.models.llama import LlamaConfig, LlamaModel, llama_param_rules

__all__ = ["LlamaConfig", "LlamaModel", "llama_param_rules"]
