"""Llama-family decoder-only transformer, TPU-first.

This is the flagship model for the framework's north-star path
(BASELINE.json config #2: Llama-3-8B FSDP/GSPMD on a v5e pod slice).
The reference has no model code of its own — Train wraps user torch
models (reference: python/ray/train/torch/train_loop_utils.py) — so this
is green-field, designed for the MXU and GSPMD from the start:

  - bfloat16 activations/compute, fp32 params + optimizer state
  - GQA attention with rotary embeddings; attention runs through a
    pluggable kernel hook so the Pallas flash/ring kernels (ray_tpu/ops)
    swap in without touching the model
  - static shapes everywhere; no data-dependent Python control flow, so
    one jit trace covers the whole step
  - `llama_param_rules` gives PartitionSpecs for tp (heads / mlp hidden)
    and fsdp (everything else) so the same module runs 1-chip or pod

Incremental decoding (the LLM serving tier, serve/llm.py): the same
modules accept an optional paged KV-cache pytree (``make_kv_cache`` /
``decode_cache_args``).  The cache is PAGING-AGNOSTIC here — the model
sees flat per-layer slot pools plus precomputed write-slot and
context-gather index arrays; the serving engine owns the block tables
that map sequence positions to physical page slots.  New keys/values
are written post-rope at their absolute positions.  Chunked prefill
gathers context dense per sequence with a position mask
(``ctx_pos <= q_pos``) for causality; single-token decode can instead
carry page-granular block tables + context lengths and route through
the Pallas paged-attention kernel (ray_tpu/ops/paged_attention.py),
which reads used pages only — no dense gather.  Both ride static
shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = False  # rematerialize each block (activation checkpointing)

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        """Test-size config: compiles in seconds on CPU."""
        return cls(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                   n_kv_heads=2, hidden_dim=128, max_seq_len=128)

    @classmethod
    def small(cls) -> "LlamaConfig":
        """~110M params: single-chip bench size."""
        return cls(vocab_size=32000, dim=768, n_layers=12, n_heads=12,
                   n_kv_heads=4, hidden_dim=2048, max_seq_len=2048)

    @classmethod
    def bench_1b(cls) -> "LlamaConfig":
        """~600M params sized for one v5e chip's HBM with adamw fp32
        state: big enough to load the MXU (all matmul dims are multiples
        of 128), small enough that params+moments+grads fit in 16 GB."""
        return cls(vocab_size=32000, dim=1536, n_layers=20, n_heads=12,
                   n_kv_heads=4, hidden_dim=4096, max_seq_len=2048)

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, hidden_dim=14336, max_seq_len=8192)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def num_params(self) -> int:
        embed = self.vocab_size * self.dim
        per_layer = (
            self.dim * self.n_heads * self.head_dim          # wq
            + 2 * self.dim * self.n_kv_heads * self.head_dim  # wk, wv
            + self.n_heads * self.head_dim * self.dim         # wo
            + 3 * self.dim * self.hidden_dim                  # w1, w2, w3
            + 2 * self.dim                                    # norms
        )
        return embed * 2 + per_layer * self.n_layers + self.dim


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding over the last dim. x: [B, S, H, D]."""
    half = x.shape[-1] // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


# Self-attention prefills at or above this length route through the
# Pallas flash kernel instead of materializing the [S, S] score matrix.
# Module-level so tests/benches can lower it; sequences must also be a
# multiple of the flash block (128) to qualify.
FLASH_PREFILL_MIN_SEQ = 512


def default_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      causal: bool = True) -> jax.Array:
    """Reference attention path: XLA fuses this well on its own; the
    Pallas flash kernel (ray_tpu/ops/flash_attention.py) replaces it for
    long sequences (>= FLASH_PREFILL_MIN_SEQ, multiple of 128).
    q: [B,S,H,D], k/v: [B,S,Hkv,D]."""
    s, t = q.shape[1], k.shape[1]
    if (causal and s == t and s >= FLASH_PREFILL_MIN_SEQ
            and s % 128 == 0):
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, True)
    return dense_attention(q, k, v, causal)


def dense_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True) -> jax.Array:
    """The dense softmax-attention math itself — kept separate from
    :func:`default_attention` so the flash kernel's recompute backward
    can target it without re-entering the length-based routing."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    q = q.reshape(b, s, hkv, group, d)
    logits = jnp.einsum("bshgd,bthd->bhgst", q, k).astype(jnp.float32)
    logits = logits / jnp.sqrt(d).astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        logits = jnp.where(mask[None, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhgst,bthd->bshgd", probs, v)
    return out.reshape(b, s, h, d)


def cached_attention(q: jax.Array, pool_k: jax.Array, pool_v: jax.Array,
                     ctx: jax.Array, ctx_pos: jax.Array,
                     ctx_mask: jax.Array, q_pos: jax.Array) -> jax.Array:
    """Attention over a slot-pool KV cache.

    q: [B,S,H,D] (post-rope); pool_k/pool_v: [T,Hkv,D] flat slot pools
    (already containing this call's keys/values); ctx: [B,L] physical
    slot index of each context entry (garbage entries point at slot 0);
    ctx_pos: [B,L] the token position each entry holds; ctx_mask: [B,L]
    validity; q_pos: [B,S] query positions.  Causality = position mask,
    so one kernel serves chunked prefill (S>1) and decode (S=1)."""
    b, s, h, d = q.shape
    hkv = pool_k.shape[1]
    group = h // hkv
    ck = pool_k[ctx.reshape(-1)].reshape(b, ctx.shape[1], hkv, d)
    cv = pool_v[ctx.reshape(-1)].reshape(b, ctx.shape[1], hkv, d)
    q5 = q.reshape(b, s, hkv, group, d)
    logits = jnp.einsum("bshgd,blhd->bhgsl", q5, ck).astype(jnp.float32)
    logits = logits / jnp.sqrt(d).astype(jnp.float32)
    mask = (ctx_pos[:, None, :] <= q_pos[:, :, None]) \
        & ctx_mask[:, None, :]                      # [B,S,L]
    logits = jnp.where(mask[:, None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(cv.dtype)
    out = jnp.einsum("bhgsl,blhd->bshgd", probs, cv)
    return out.reshape(b, s, h, d)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        out = x.astype(jnp.float32) * jax.lax.rsqrt(var + self.eps)
        return (out * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: LlamaConfig
    kernel: Optional[Callable] = None  # pluggable (flash/ring) attention
    page_size: int = 0  # > 0 enables the paged decode kernel

    @nn.compact
    def __call__(self, x, positions, cache=None):
        cfg = self.cfg
        dense = partial(nn.DenseGeneral, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        q = dense(features=(cfg.n_heads, cfg.head_dim), name="wq")(x)
        k = dense(features=(cfg.n_kv_heads, cfg.head_dim), name="wk")(x)
        v = dense(features=(cfg.n_kv_heads, cfg.head_dim), name="wv")(x)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        wo = nn.DenseGeneral(features=cfg.dim, axis=(-2, -1), use_bias=False,
                             dtype=cfg.dtype, param_dtype=jnp.float32,
                             name="wo")
        if cache is not None:
            # incremental path: write post-rope k/v into this layer's
            # flat slot pools, attend over the gathered context.  Slot 0
            # is the engine's designated garbage slot — inactive batch
            # lanes write there and mask it out of their context.
            b, s = k.shape[0], k.shape[1]
            flat = cache["slots"].reshape(-1)
            pool_k = cache["k"].at[flat].set(
                k.reshape(b * s, *k.shape[2:]))
            pool_v = cache["v"].at[flat].set(
                v.reshape(b * s, *v.shape[2:]))
            if cache.get("block_tables") is not None and s == 1 \
                    and self.page_size > 0:
                # decode via the Pallas paged kernel: page-granular
                # block tables + context lengths, no dense gather
                from ray_tpu.ops.paged_attention import paged_attention

                out = paged_attention(q, pool_k, pool_v,
                                      cache["block_tables"],
                                      cache["context_lens"],
                                      page_size=self.page_size)
            else:
                out = cached_attention(q, pool_k, pool_v, cache["ctx"],
                                       cache["ctx_pos"],
                                       cache["ctx_mask"], positions)
            return wo(out), pool_k, pool_v
        attend = self.kernel or default_attention
        return wo(attend(q, k, v))


class Mlp(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = partial(nn.Dense, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32)
        gate = dense(cfg.hidden_dim, name="w1")(x)
        up = dense(cfg.hidden_dim, name="w3")(x)
        return dense(cfg.dim, name="w2")(nn.silu(gate) * up)


class Block(nn.Module):
    cfg: LlamaConfig
    kernel: Optional[Callable] = None
    page_size: int = 0

    @nn.compact
    def __call__(self, x, positions, cache=None):
        attn_in = RMSNorm(self.cfg.norm_eps, name="attn_norm")(x)
        attn = Attention(self.cfg, self.kernel, self.page_size,
                         name="attn")
        if cache is not None:
            a, pool_k, pool_v = attn(attn_in, positions, cache)
            x = x + a
            x = x + Mlp(self.cfg, name="mlp")(
                RMSNorm(self.cfg.norm_eps, name="mlp_norm")(x))
            return x, pool_k, pool_v
        x = x + attn(attn_in, positions)
        x = x + Mlp(self.cfg, name="mlp")(
            RMSNorm(self.cfg.norm_eps, name="mlp_norm")(x))
        return x


class LlamaModel(nn.Module):
    cfg: LlamaConfig
    kernel: Optional[Callable] = None
    page_size: int = 0

    @nn.compact
    def __call__(self, tokens, cache=None):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="embed")(tokens)
        if cache is not None:
            # incremental decode/prefill over the paged KV cache: query
            # positions come from the engine, per-layer pools are
            # threaded through and returned updated.  The cache carries
            # EITHER dense gather arrays (ctx/ctx_pos/ctx_mask — chunked
            # prefill, or dense decode) OR page-granular block tables +
            # context lengths (paged decode kernel).
            positions = cache["q_pos"]
            paged = cache.get("block_tables") is not None
            new_k, new_v = [], []
            for i in range(cfg.n_layers):
                layer_cache = {"k": cache["k"][i], "v": cache["v"][i],
                               "slots": cache["slots"]}
                if paged:
                    layer_cache["block_tables"] = cache["block_tables"]
                    layer_cache["context_lens"] = cache["context_lens"]
                else:
                    layer_cache.update(
                        ctx=cache["ctx"], ctx_pos=cache["ctx_pos"],
                        ctx_mask=cache["ctx_mask"])
                x, pk, pv = Block(cfg, self.kernel, self.page_size,
                                  name=f"layer_{i}")(
                    x, positions, layer_cache)
                new_k.append(pk)
                new_v.append(pv)
            x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=cfg.dtype, param_dtype=jnp.float32,
                              name="lm_head")(x)
            return logits, {"k": new_k, "v": new_v}
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1]), tokens.shape)
        block_cls = Block
        if cfg.remat:
            # trade FLOPs for HBM: recompute block internals in the bwd
            # pass, keeping only block boundaries resident
            block_cls = nn.remat(Block, prevent_cse=False)
        for i in range(cfg.n_layers):
            x = block_cls(cfg, self.kernel, name=f"layer_{i}")(x, positions)
        x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
        # bf16 matmul with fp32 accumulation: the biggest single matmul of
        # the model must ride the MXU fast path (loss math upcasts after)
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                          param_dtype=jnp.float32, name="lm_head")(x)
        return logits


class LlamaStage(nn.Module):
    """A contiguous layer range of :class:`LlamaModel` for MPMD pipeline
    parallelism: stage 0 owns the embedding, the last stage owns the
    final norm + lm_head, and every stage owns ``layers[start:end)``.

    Submodule names match LlamaModel exactly (``embed``, ``layer_i``,
    ``final_norm``, ``lm_head``), so a full-model checkpoint slices into
    per-stage trees (see train/pipeline.py slice_params_for_stage) and
    ``llama_param_rules`` applies unchanged.  Input is tokens [B, S] for
    the first stage and activations [B, S, D] otherwise; output is
    activations for non-last stages and logits for the last.
    """

    cfg: LlamaConfig
    start: int
    end: int            # exclusive layer bound
    first: bool = False  # embed tokens
    last: bool = False   # final_norm + lm_head
    kernel: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        if self.first:
            x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="embed")(x)
            seq_len = x.shape[1]
            positions = jnp.broadcast_to(jnp.arange(seq_len),
                                         x.shape[:2])
        else:
            positions = jnp.broadcast_to(jnp.arange(x.shape[1]),
                                         x.shape[:2])
        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(Block, prevent_cse=False)
        for i in range(self.start, self.end):
            x = block_cls(cfg, self.kernel, name=f"layer_{i}")(x, positions)
        if self.last:
            x = RMSNorm(cfg.norm_eps, name="final_norm")(x)
            x = nn.Dense(cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                         param_dtype=jnp.float32, name="lm_head")(x)
        return x


def make_kv_pools(cfg: LlamaConfig, num_slots: int,
                  dtype: Any = None) -> Dict[str, Any]:
    """Allocate flat per-layer KV slot pools for incremental decoding.

    ``num_slots`` = pages x page_size; slot 0 is reserved as the
    garbage slot for inactive batch lanes (serve/llm.py never hands it
    to a sequence).  Sized from ``n_kv_heads``/``head_dim`` — the GQA
    shrink is exactly what makes a resident cache affordable."""
    dtype = dtype or cfg.dtype
    shape = (num_slots, cfg.n_kv_heads, cfg.head_dim)
    return {"k": [jnp.zeros(shape, dtype) for _ in range(cfg.n_layers)],
            "v": [jnp.zeros(shape, dtype) for _ in range(cfg.n_layers)]}


def gather_kv_slots(pools: Dict[str, Any], slots: Any) -> Dict[str, Any]:
    """Read the KV rows at ``slots`` out of every layer's pool as host
    numpy arrays — the export half of KV-page shipping (serve/llm.py
    disaggregated prefill).  Paging-agnostic: ``slots`` is whatever flat
    slot indices the caller's block tables resolve to."""
    import numpy as np

    idx = np.asarray(slots, np.int32)
    return {"k": [np.asarray(p[idx]) for p in pools["k"]],
            "v": [np.asarray(p[idx]) for p in pools["v"]]}


def scatter_kv_slots(pools: Dict[str, Any], slots: Any,
                     rows: Dict[str, Any]) -> Dict[str, Any]:
    """Write previously-gathered KV rows into ``slots`` of every
    layer's pool (the import half of KV-page shipping).  Returns the
    updated pools — jax arrays are immutable, so callers must adopt the
    result."""
    idx = jnp.asarray(slots, jnp.int32)
    return {"k": [p.at[idx].set(jnp.asarray(r, p.dtype))
                  for p, r in zip(pools["k"], rows["k"])],
            "v": [p.at[idx].set(jnp.asarray(r, p.dtype))
                  for p, r in zip(pools["v"], rows["v"])]}


def copy_kv_slots(pools: Dict[str, Any], src_slots: Any,
                  dst_slots: Any) -> Dict[str, Any]:
    """Copy KV rows ``src_slots`` -> ``dst_slots`` within every layer's
    pool — the copy-on-write split when a sequence diverges mid-page
    from a shared prefix page.  Returns the updated pools."""
    src = jnp.asarray(src_slots, jnp.int32)
    dst = jnp.asarray(dst_slots, jnp.int32)
    return {"k": [p.at[dst].set(p[src]) for p in pools["k"]],
            "v": [p.at[dst].set(p[src]) for p in pools["v"]]}


def kv_pool_bytes(cfg: LlamaConfig, num_slots: int) -> int:
    """Resident bytes of one replica's KV pools (both k and v)."""
    itemsize = jnp.dtype(cfg.dtype).itemsize
    return (2 * cfg.n_layers * num_slots * cfg.n_kv_heads
            * cfg.head_dim * itemsize)


def llama_param_rules() -> Dict[str, Any]:
    """PartitionSpec rules by parameter-path substring.

    tp shards head and mlp-hidden dims; fsdp shards the other big dim.
    Same layout family as the scaling-book Llama recipe.
    """
    from jax.sharding import PartitionSpec as P

    return {
        "embed": P("tp", "fsdp"),
        "wq/kernel": P("fsdp", "tp", None),
        "wk/kernel": P("fsdp", "tp", None),
        "wv/kernel": P("fsdp", "tp", None),
        "wo/kernel": P("tp", None, "fsdp"),
        "w1/kernel": P("fsdp", "tp"),
        "w3/kernel": P("fsdp", "tp"),
        "w2/kernel": P("tp", "fsdp"),
        "lm_head": P("fsdp", "tp"),
        "norm": P(None),
    }


def causal_lm_loss(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Next-token cross entropy with shifted targets.

    Upcasts to fp32 only here — the lm_head matmul stays bf16 — and uses
    the one-hot-free formulation so no [B,S,V] one-hot materializes.
    """
    targets = tokens[:, 1:]
    logits = logits[:, :-1].astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(lse - picked)
