"""ray_tpu.serve: model serving on actor replicas.

Equivalent of Ray Serve (reference: python/ray/serve/ — api.py
@serve.deployment :248 / serve.run :543, controller _private/controller.py,
router _private/router.py + pow-2 replica scheduler, batching
batching.py).  TPU slant: @serve.batch coalesces concurrent requests
into one jitted forward, the TPU-efficient serving shape.
"""

from ray_tpu._private.errors import DeploymentFailedError
from ray_tpu.serve.api import (Application, Deployment, DeploymentHandle,
                               batch, delete, deployment, get_handle, run,
                               shutdown)
from ray_tpu.serve.http import (proxy_addresses, shutdown_http,
                                start_http, start_per_node_http)
from ray_tpu.serve.llm import (LLMEngine, LLMOverloadedError,
                               llm_deployment)
from ray_tpu.serve.rpc_ingress import (RpcIngressClient, start_rpc_ingress,
                                       stop_rpc_ingress)

__all__ = ["deployment", "run", "get_handle", "delete", "shutdown",
           "batch", "Deployment", "DeploymentHandle", "Application",
           "start_http", "start_per_node_http", "proxy_addresses",
           "shutdown_http", "start_rpc_ingress", "stop_rpc_ingress",
           "RpcIngressClient", "DeploymentFailedError",
           "llm_deployment", "LLMEngine", "LLMOverloadedError"]
