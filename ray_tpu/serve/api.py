"""Serve API: deployments, controller, handles, batching.

Reference mapping:
  - @deployment / .options / .bind  -> python/ray/serve/api.py:248
  - serve.run / delete / get_handle -> api.py:543, _private/api.py
  - ServeController (named actor)   -> _private/controller.py
  - DeploymentHandle + router       -> handle.py, _private/router.py
    (least-outstanding-requests among replicas = the pow-2 intent with
    exact local counts)
  - @serve.batch                    -> batching.py (replica-side dynamic
    batching; replicas run with max_concurrency > 1 so concurrent calls
    coalesce into one forward — the TPU-efficient shape)
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

CONTROLLER_NAME = "_serve_controller"

# ----------------------------------------------------------------- batching

# Batch state lives ONLY in this per-process registry, never on the user's
# class or instance: a _BatchState holds threading locks, and anything
# reachable from the decorated class must stay cloudpickle-able (the
# deployment ships the class to replicas by value).  Keyed by
# (id(owner), key); a weakref finalizer evicts the entry when the owner is
# collected, so short-lived instances don't leak state and a recycled id()
# can't adopt a dead owner's batches.
_batch_states: Dict[Any, "_BatchState"] = {}
_batch_states_lock = threading.Lock()


def _batch_state_for(owner, key: str, max_batch_size: int,
                     wait_s: float) -> "_BatchState":
    import weakref

    regkey = (id(owner), key)
    with _batch_states_lock:
        state = _batch_states.get(regkey)
        if state is None:
            state = _BatchState(max_batch_size, wait_s)
            _batch_states[regkey] = state
            try:
                weakref.finalize(owner, _batch_states.pop, regkey, None)
            except TypeError:
                # owner not weakref-able (__slots__ without __weakref__):
                # pin it so its id() can't be recycled into this entry —
                # a process-lifetime leak is better than another
                # instance silently adopting this owner's queued batches
                state.owner_pin = owner
        return state


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Dynamic request batching for replica methods.

    Concurrent callers (replica threads) enqueue items; one caller
    becomes the flusher, invokes the wrapped function ONCE with the list
    of items, and distributes the per-item results.
    """

    def wrap(fn: Callable) -> Callable:
        cfg = (max_batch_size, batch_wait_timeout_s)
        state_key = f"_serve_batch_{getattr(fn, '__name__', 'fn')}"

        def wrapped(self_or_item, *maybe_item):
            # support methods (self, item) and free functions (item)
            if maybe_item:
                owner, item = self_or_item, maybe_item[0]
                call = lambda items: fn(owner, items)
            else:
                # free function: the wrapper itself anchors the state
                owner, item = wrapped, self_or_item
                call = fn
            state = _batch_state_for(owner, state_key, *cfg)
            return state.submit(item, call)

        wrapped.__name__ = getattr(fn, "__name__", "batched")
        wrapped._is_serve_batch = True
        return wrapped

    if _func is not None:
        return wrap(_func)
    return wrap


class _BatchState:
    def __init__(self, max_batch_size: int, wait_s: float):
        self.max = max_batch_size
        self.wait = wait_s
        self.owner_pin = None  # set for non-weakref-able owners
        self.lock = threading.Lock()
        self.items: List[Any] = []
        self.futures: List[Any] = []
        self.flusher_here = False

    def submit(self, item: Any, call: Callable[[List[Any]], List[Any]]):
        import concurrent.futures as cf

        fut: cf.Future = cf.Future()
        with self.lock:
            self.items.append(item)
            self.futures.append(fut)
            i_flush = not self.flusher_here
            if i_flush:
                self.flusher_here = True
        if not i_flush:
            return fut.result(timeout=120)
        # this caller is the flusher: drain every batch, then hand back
        try:
            while True:
                deadline = time.monotonic() + self.wait
                while time.monotonic() < deadline:
                    with self.lock:
                        if len(self.items) >= self.max:
                            break
                    time.sleep(min(0.001, self.wait / 4 or 0.001))
                with self.lock:
                    items = self.items[:self.max]
                    futures = self.futures[:self.max]
                    del self.items[:self.max]
                    del self.futures[:self.max]
                self._run_batch(call, items, futures)
                with self.lock:
                    if not self.items:
                        self.flusher_here = False
                        break
        except BaseException:
            with self.lock:
                self.flusher_here = False
            raise
        return fut.result(timeout=120)

    @staticmethod
    def _run_batch(call, items, futures):
        try:
            results = call(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for {len(items)} inputs")
            for f, r in zip(futures, results):
                f.set_result(r)
        except BaseException as e:
            for f in futures:
                if not f.done():
                    f.set_exception(e)


# -------------------------------------------------------------- deployment


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)

    def options(self, **opts) -> "Deployment":
        d = Deployment(self.func_or_class, self.name, self.num_replicas,
                       self.max_ongoing_requests,
                       dict(self.ray_actor_options),
                       self.init_args, dict(self.init_kwargs))
        for k, v in opts.items():
            setattr(d, k, v)
        return d

    def bind(self, *args, **kwargs) -> "Application":
        d = self.options()
        d.init_args = args
        d.init_kwargs = kwargs
        return Application(d)


@dataclass
class Application:
    deployment: Deployment


def deployment(_cls: Any = None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_ongoing_requests: int = 8,
               ray_actor_options: Optional[Dict[str, Any]] = None):
    def make(target):
        return Deployment(target, name or getattr(target, "__name__", "app"),
                          num_replicas, max_ongoing_requests,
                          ray_actor_options or {})

    if _cls is not None:
        return make(_cls)
    return make


class _Replica:
    """Actor wrapping the user callable (reference: _private/replica.py)."""

    def __init__(self, target_blob: bytes, init_args, init_kwargs):
        import cloudpickle

        target = cloudpickle.loads(target_blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        else:
            self._callable = target

    def handle_request(self, method: str, args, kwargs):
        if method == "__call__":
            return self._callable(*args, **kwargs)
        return getattr(self._callable, method)(*args, **kwargs)

    def health(self):
        return True


class ServeController:
    """Named actor owning deployment state
    (reference: _private/controller.py reconciliation)."""

    def __init__(self):
        self.apps: Dict[str, Dict[str, Any]] = {}

    def deploy(self, name: str, target_blob: bytes, num_replicas: int,
               max_ongoing: int, init_args, init_kwargs,
               actor_options: Dict[str, Any]):
        import ray_tpu

        existing = self.apps.get(name)
        if existing:
            for h in existing["replicas"]:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
        cls = ray_tpu.remote(_Replica).options(
            max_concurrency=max(2, max_ongoing), **actor_options)
        replicas = [cls.remote(target_blob, init_args, init_kwargs)
                    for _ in range(num_replicas)]
        # block until every replica's constructor finished (model loaded)
        ray_tpu.get([r.health.remote() for r in replicas], timeout=600)
        self.apps[name] = {"replicas": replicas,
                           "max_ongoing": max_ongoing}
        return True

    def get_replicas(self, name: str):
        app = self.apps.get(name)
        if app is None:
            return None
        return [r._actor_id for r in app["replicas"]]

    def delete(self, name: str):
        import ray_tpu

        app = self.apps.pop(name, None)
        if app:
            for h in app["replicas"]:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
        return True

    def list_deployments(self):
        return {name: len(app["replicas"]) for name, app in self.apps.items()}


# ------------------------------------------------------------------ handle


class DeploymentHandle:
    """Client-side router: least-outstanding-requests replica choice
    (reference: router.py assign_request + pow_2_scheduler.py)."""

    def __init__(self, name: str, replica_ids: List[str]):
        self._name = name
        from ray_tpu.api import ActorHandle

        self._replicas = [ActorHandle(rid) for rid in replica_ids]
        self._inflight = [0] * len(self._replicas)
        self._lock = threading.Lock()

    def remote(self, *args, _method: str = "__call__", **kwargs):
        import ray_tpu

        with self._lock:
            idx = min(range(len(self._replicas)),
                      key=lambda i: self._inflight[i])
            self._inflight[idx] += 1
        ref = self._replicas[idx].handle_request.remote(_method, args, kwargs)

        def _done_cb():
            with self._lock:
                self._inflight[idx] -= 1

        _watch_ref(ref, _done_cb)
        return ref

    def method(self, name: str):
        def call(*args, **kwargs):
            return self.remote(*args, _method=name, **kwargs)

        return call


def _watch_ref(ref, cb):
    def watcher():
        import ray_tpu

        try:
            ray_tpu.wait([ref], num_returns=1, timeout=600)
        except Exception:
            pass
        cb()

    threading.Thread(target=watcher, daemon=True).start()


# ---------------------------------------------------------------- serve API


def _controller():
    import ray_tpu

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    import ray_tpu.api as api

    try:
        return api.ActorClass(ServeController, name=CONTROLLER_NAME,
                              lifetime="detached").remote()
    except ray_tpu.RayError:
        # lost the creation race to another caller
        return ray_tpu.get_actor(CONTROLLER_NAME)


def run(app: Application, name: Optional[str] = None) -> DeploymentHandle:
    import cloudpickle

    import ray_tpu

    d = app.deployment
    dep_name = name or d.name
    ctrl = _controller()
    ray_tpu.get(ctrl.deploy.remote(
        dep_name, cloudpickle.dumps(d.func_or_class), d.num_replicas,
        d.max_ongoing_requests, d.init_args, d.init_kwargs,
        d.ray_actor_options), timeout=600)
    return get_handle(dep_name)


def get_handle(name: str) -> DeploymentHandle:
    import ray_tpu

    ctrl = _controller()
    replica_ids = ray_tpu.get(ctrl.get_replicas.remote(name), timeout=60)
    if replica_ids is None:
        raise ValueError(f"no deployment named {name!r}")
    return DeploymentHandle(name, replica_ids)


def delete(name: str):
    import ray_tpu

    ray_tpu.get(_controller().delete.remote(name), timeout=120)


def shutdown():
    import ray_tpu

    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    for name in list(ray_tpu.get(ctrl.list_deployments.remote(), timeout=60)):
        ray_tpu.get(ctrl.delete.remote(name), timeout=120)
    ray_tpu.kill(ctrl)
