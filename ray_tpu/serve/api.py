"""Serve API: deployments, controller, handles, batching.

Reference mapping:
  - @deployment / .options / .bind  -> python/ray/serve/api.py:248
  - serve.run / delete / get_handle -> api.py:543, _private/api.py
  - ServeController (named actor)   -> _private/controller.py
  - DeploymentHandle + router       -> handle.py, _private/router.py
    (least-outstanding-requests among replicas = the pow-2 intent with
    exact local counts)
  - @serve.batch                    -> batching.py (replica-side dynamic
    batching; replicas run with max_concurrency > 1 so concurrent calls
    coalesce into one forward — the TPU-efficient shape)
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

CONTROLLER_NAME = "_serve_controller"

# ----------------------------------------------------------------- batching

# Batch state lives ONLY in this per-process registry, never on the user's
# class or instance: a _BatchState holds threading locks, and anything
# reachable from the decorated class must stay cloudpickle-able (the
# deployment ships the class to replicas by value).  Keyed by
# (id(owner), key); a weakref finalizer evicts the entry when the owner is
# collected, so short-lived instances don't leak state and a recycled id()
# can't adopt a dead owner's batches.
_batch_states: Dict[Any, "_BatchState"] = {}
_batch_states_lock = threading.Lock()


def _batch_state_for(owner, key: str, max_batch_size: int,
                     wait_s: float) -> "_BatchState":
    import weakref

    regkey = (id(owner), key)
    with _batch_states_lock:
        state = _batch_states.get(regkey)
        if state is None:
            state = _BatchState(max_batch_size, wait_s)
            _batch_states[regkey] = state
            try:
                weakref.finalize(owner, _batch_states.pop, regkey, None)
            except TypeError:
                # owner not weakref-able (__slots__ without __weakref__):
                # pin it so its id() can't be recycled into this entry —
                # a process-lifetime leak is better than another
                # instance silently adopting this owner's queued batches
                state.owner_pin = owner
        return state


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Dynamic request batching for replica methods.

    Concurrent callers (replica threads) enqueue items; one caller
    becomes the flusher, invokes the wrapped function ONCE with the list
    of items, and distributes the per-item results.
    """

    def wrap(fn: Callable) -> Callable:
        cfg = (max_batch_size, batch_wait_timeout_s)
        state_key = f"_serve_batch_{getattr(fn, '__name__', 'fn')}"

        def wrapped(self_or_item, *maybe_item):
            # support methods (self, item) and free functions (item)
            if maybe_item:
                owner, item = self_or_item, maybe_item[0]
                call = lambda items: fn(owner, items)
            else:
                # free function: the wrapper itself anchors the state
                owner, item = wrapped, self_or_item
                call = fn
            state = _batch_state_for(owner, state_key, *cfg)
            return state.submit(item, call)

        wrapped.__name__ = getattr(fn, "__name__", "batched")
        wrapped._is_serve_batch = True
        return wrapped

    if _func is not None:
        return wrap(_func)
    return wrap


class _BatchState:
    def __init__(self, max_batch_size: int, wait_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.max = max_batch_size
        self.wait = wait_s
        self.clock = clock  # injectable for deterministic timer tests
        self.owner_pin = None  # set for non-weakref-able owners
        self.lock = threading.Lock()
        # submitters notify the parked flusher the moment the batch
        # fills — a full batch flushes immediately instead of being
        # rediscovered by a poll tick (was a 1ms sleep-poll loop: up to
        # 1ms added latency per batch and a busy core at high rates)
        self.full = threading.Condition(self.lock)
        self.items: List[Any] = []
        self.futures: List[Any] = []
        self.flusher_here = False

    def submit(self, item: Any, call: Callable[[List[Any]], List[Any]]):
        import concurrent.futures as cf

        fut: cf.Future = cf.Future()
        with self.lock:
            self.items.append(item)
            self.futures.append(fut)
            i_flush = not self.flusher_here
            if i_flush:
                self.flusher_here = True
            elif len(self.items) >= self.max:
                self.full.notify()  # wake the flusher: batch is full
        if not i_flush:
            return fut.result(timeout=120)
        # this caller is the flusher: drain every batch, then hand back
        try:
            while True:
                deadline = self.clock() + self.wait
                with self.lock:
                    while len(self.items) < self.max:
                        remaining = deadline - self.clock()
                        if remaining <= 0:
                            break
                        self.full.wait(remaining)
                    items = self.items[:self.max]
                    futures = self.futures[:self.max]
                    del self.items[:self.max]
                    del self.futures[:self.max]
                self._run_batch(call, items, futures)
                with self.lock:
                    if not self.items:
                        self.flusher_here = False
                        break
        except BaseException:
            with self.lock:
                self.flusher_here = False
            raise
        return fut.result(timeout=120)

    @staticmethod
    def _run_batch(call, items, futures):
        try:
            results = call(items)
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for {len(items)} inputs")
            for f, r in zip(futures, results):
                f.set_result(r)
        except BaseException as e:
            for f in futures:
                if not f.done():
                    f.set_exception(e)


# -------------------------------------------------------------- deployment


@dataclass
class Deployment:
    func_or_class: Any
    name: str
    # int, or "auto" — replica count then follows load between the
    # autoscaling_config's min/max bounds (reference: serve's
    # num_replicas="auto" + autoscaling_config)
    num_replicas: Any = 1
    max_ongoing_requests: int = 8
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    init_args: tuple = ()
    init_kwargs: Dict[str, Any] = field(default_factory=dict)
    # reference: _private/autoscaling_policy.py — replica count follows
    # reported ongoing requests: {"min_replicas", "max_replicas",
    # "target_ongoing_requests"}
    autoscaling_config: Optional[Dict[str, Any]] = None
    # LLM serving tier (serve/llm.py llm_deployment): replicas host a
    # continuous-batching engine and the controller installs a pinned
    # decode loop on each one
    llm: bool = False
    # ---- tail tolerance (per-deployment policy; see DeploymentHandle) ----
    # end-to-end budget for one request through this deployment: callers
    # (handle.call_async, the HTTP proxy) cap their wait AND stamp the
    # deadline into the replica task so the whole downstream tree
    # inherits it (an X-Request-Deadline-Ms header tightens it further)
    request_timeout_s: Optional[float] = None
    # hedging (IDEMPOTENT deployments only): a request still unanswered
    # after this delay fires a duplicate against a second replica —
    # first response wins, the loser is cancelled.  A float is a fixed
    # delay; "p99" tracks the handle's observed p99 latency.
    hedge_after_s: Any = None
    # the user's promise that duplicate execution is safe; hedging is
    # refused without it (a duplicate non-idempotent request could
    # double-apply side effects)
    idempotent: bool = False
    # LLM deployments only: run chunked prefill on this many dedicated
    # replicas (a sibling "<name>-prefill" pool); decode replicas attach
    # the shipped KV pages by request_id (serve/llm.py).  0 = colocated
    # prefill (the PR-11 behaviour).
    prefill_replicas: int = 0

    def options(self, **opts) -> "Deployment":
        d = Deployment(self.func_or_class, self.name, self.num_replicas,
                       self.max_ongoing_requests,
                       dict(self.ray_actor_options),
                       self.init_args, dict(self.init_kwargs),
                       dict(self.autoscaling_config)
                       if self.autoscaling_config else None,
                       self.llm, self.request_timeout_s,
                       self.hedge_after_s, self.idempotent,
                       self.prefill_replicas)
        for k, v in opts.items():
            setattr(d, k, v)
        return d

    def policy(self) -> Dict[str, Any]:
        """The wire form of the tail-tolerance policy (stored by the
        controller, learned by every handle via get_replicas)."""
        pol = {"request_timeout_s": self.request_timeout_s,
               "hedge_after_s": self.hedge_after_s,
               "idempotent": bool(self.idempotent)}
        if self.llm and self.prefill_replicas:
            pol["prefill_pool"] = f"{self.name}-prefill"
        return pol

    def bind(self, *args, **kwargs) -> "Application":
        d = self.options()
        d.init_args = args
        d.init_kwargs = kwargs
        return Application(d)


@dataclass
class Application:
    deployment: Deployment


def deployment(_cls: Any = None, *, name: Optional[str] = None,
               num_replicas: Any = 1, max_ongoing_requests: int = 8,
               ray_actor_options: Optional[Dict[str, Any]] = None,
               autoscaling_config: Optional[Dict[str, Any]] = None,
               request_timeout_s: Optional[float] = None,
               hedge_after_s: Any = None, idempotent: bool = False):
    def make(target):
        return Deployment(target, name or getattr(target, "__name__", "app"),
                          num_replicas, max_ongoing_requests,
                          ray_actor_options or {},
                          autoscaling_config=autoscaling_config,
                          request_timeout_s=request_timeout_s,
                          hedge_after_s=hedge_after_s,
                          idempotent=idempotent)

    if _cls is not None:
        return make(_cls)
    return make


class _Replica:
    """Actor wrapping the user callable (reference: _private/replica.py)."""

    def __init__(self, target_blob: bytes, init_args, init_kwargs):
        import cloudpickle

        target = cloudpickle.loads(target_blob)
        if isinstance(target, type):
            self._callable = target(*init_args, **init_kwargs)
        else:
            self._callable = target

    def handle_request(self, method: str, args, kwargs):
        if method == "__call__":
            return self._callable(*args, **kwargs)
        return getattr(self._callable, method)(*args, **kwargs)

    def stream_request(self, method: str, args, kwargs):
        """Generator variant: the user callable must return/be a
        generator; each item streams to the caller as its own object
        (reference: _private/replica.py handle_request_streaming).
        Invoked with num_returns="streaming" by DeploymentHandle.stream."""
        out = self.handle_request(method, args, kwargs)
        if not hasattr(out, "__next__") and not hasattr(out, "__anext__"):
            raise TypeError(
                f"stream() requires {method!r} to return a generator; "
                f"got {type(out).__name__}")
        if hasattr(out, "__anext__"):
            raise TypeError("async generators are not supported through "
                            "serve stream(); use a sync generator")
        yield from out

    def health(self):
        return True

    def __getattr__(self, name):
        # stateful-restart hooks (worker.py __rt_save__/__rt_restore__)
        # delegate to the wrapped callable WHEN IT DEFINES THEM — via
        # __getattr__ so plain replicas still fail hasattr() and skip
        # the autosave machinery entirely
        if name in ("__rt_save__", "__rt_restore__") \
                and "_callable" in self.__dict__:
            return getattr(self.__dict__["_callable"], name)
        raise AttributeError(name)


class ServeController:
    """Named actor owning deployment state, with a background
    reconciliation loop that replaces dead replicas and autoscales on
    handle-reported load (reference: _private/controller.py,
    deployment_state.py:1226, autoscaling_policy.py)."""

    RECONCILE_PERIOD_S = 0.5
    CHECKPOINT_KEY = "serve:controller:checkpoint"

    def __init__(self):
        self.apps: Dict[str, Dict[str, Any]] = {}
        self._lock = threading.Lock()
        self._ckpt_lock = threading.Lock()  # serializes checkpoint writes
        self._version_counter = 0  # monotonic across redeploys
        self._stop = threading.Event()
        recovered = False
        for _ in range(5):
            recovered = self._recover_from_checkpoint()
            if recovered:
                break
            time.sleep(1.0)
        if not recovered:
            # proceeding with empty state would let the next
            # _save_checkpoint clobber the intact checkpoint and leak
            # every replica it references — fail the actor instead so
            # creation retries with a fresh controller
            raise RuntimeError(
                "serve controller could not read its checkpoint")
        # only sweep when the checkpoint was read reliably: sweeping
        # after a failed read would kill every live replica the intact
        # checkpoint still references
        self._sweep_orphan_replicas()
        self._loop_thread = threading.Thread(
            target=self._reconcile_loop, name="serve-reconcile", daemon=True)
        self._loop_thread.start()

    # ---- fault tolerance ---------------------------------------------------
    # The controller checkpoints desired state to the internal KV and
    # reattaches its detached, named replicas on restart — killing the
    # controller loses no deployments (reference: _private/controller.py
    # checkpoints to the GCS KV; application_state recovers replica
    # actors by name).

    def _save_checkpoint(self) -> None:
        import cloudpickle

        from ray_tpu.experimental import internal_kv

        # _ckpt_lock spans snapshot-build AND kv_put so concurrent saves
        # cannot write an older snapshot after a newer one
        with self._ckpt_lock:
            with self._lock:
                snap = {"version_counter": self._version_counter, "apps": {}}
                for name, app in self.apps.items():
                    snap["apps"][name] = {
                        "target_blob": app["target_blob"],
                        "init_args": app["init_args"],
                        "init_kwargs": app["init_kwargs"],
                        "actor_options": app["actor_options"],
                        "max_ongoing": app["max_ongoing"],
                        "autoscaling": app["autoscaling"],
                        "llm": app.get("llm", False),
                        "policy": app.get("policy") or {},
                        "desired": app["desired"],
                        "version": app["version"],
                        "replica_names": list(
                            app.get("replica_names", {}).values()),
                    }
            try:
                internal_kv.kv_put(self.CHECKPOINT_KEY, cloudpickle.dumps(snap))
            except Exception:
                pass  # head briefly unreachable: next mutation re-saves

    def _recover_from_checkpoint(self) -> bool:
        """Returns True when the checkpoint state is reliably known
        (loaded, or confirmed absent).  False means the read failed —
        callers must NOT treat live replicas as orphans in that case."""
        import cloudpickle

        import ray_tpu
        from ray_tpu.experimental import internal_kv

        try:
            raw = internal_kv.kv_get(self.CHECKPOINT_KEY)
        except Exception:
            return False  # head unreachable: checkpoint state unknown
        if not raw:
            return True  # confirmed: no checkpoint exists
        try:
            snap = cloudpickle.loads(raw)
        except Exception:
            return False  # corrupt read: do not sweep on this basis
        self._version_counter = snap.get("version_counter", 0)
        for name, spec in snap.get("apps", {}).items():
            replicas = []
            replica_names = {}
            for rname in spec.get("replica_names", []):
                try:
                    h = ray_tpu.get_actor(rname)
                    replicas.append(h)
                    replica_names[h._actor_id] = rname
                except Exception:
                    continue  # replica died with the outage: healed below
            self.apps[name] = {
                "target_blob": spec["target_blob"],
                "init_args": spec["init_args"],
                "init_kwargs": spec["init_kwargs"],
                "actor_options": spec["actor_options"],
                "max_ongoing": spec["max_ongoing"],
                "autoscaling": spec["autoscaling"],
                "llm": spec.get("llm", False),
                "policy": spec.get("policy") or {},
                "desired": spec["desired"],
                "replicas": replicas,
                "replica_names": replica_names,
                "version": spec["version"],
                "ongoing": {},
            }
        return True

    def _sweep_orphan_replicas(self) -> None:
        """Kill live 'serve:*' replica actors no checkpoint references:
        a controller that died mid-deploy (replicas are detached and
        started BEFORE the post-health-check checkpoint) leaves them
        running with no owner record."""
        import ray_tpu

        known = set()
        with self._lock:
            for app in self.apps.values():
                known.update(app.get("replica_names", {}).values())
        try:
            actors = ray_tpu.api._worker().head.call("list_actors",
                                                     timeout=10)["actors"]
        except Exception:
            return
        for a in actors:
            name = a.get("name", "")
            if (name.startswith("serve:") and name not in known
                    and a.get("state") in ("ALIVE", "PENDING", "RESTARTING")):
                try:
                    h = ray_tpu.get_actor(name)
                    ray_tpu.kill(h)
                except Exception:
                    pass

    # ---- desired state -----------------------------------------------------

    def deploy(self, name: str, target_blob: bytes, num_replicas: int,
               max_ongoing: int, init_args, init_kwargs,
               actor_options: Dict[str, Any],
               autoscaling: Optional[Dict[str, Any]] = None,
               health_timeout: Optional[float] = None,
               llm: bool = False,
               policy: Optional[Dict[str, Any]] = None):
        import ray_tpu

        if autoscaling:
            num_replicas = max(num_replicas,
                               int(autoscaling.get("min_replicas", 1)))
        app = {
            "target_blob": target_blob,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "actor_options": actor_options,
            "max_ongoing": max_ongoing,
            "autoscaling": autoscaling,
            "llm": llm,
            "policy": dict(policy or {}),
            "desired": num_replicas,
            "replicas": [],
            "replica_names": {},  # actor_id -> detached actor name
            "version": 0,
            "ongoing": {},   # handle_id -> (reported count, timestamp)
        }
        from ray_tpu._private.config import config
        from ray_tpu._private.errors import (DeploymentFailedError,
                                             GetTimeoutError)

        # blue-green: bring the new replicas up FIRST; a failing redeploy
        # must not take down a working deployment
        replicas = [self._start_replica(app, name)
                    for _ in range(num_replicas)]
        # the caller's (driver's) config wins: the controller process may
        # have been spawned before the driver set the knob
        if health_timeout is None:
            health_timeout = float(config.serve_replica_health_timeout_s)
        try:
            # block until every replica's constructor finished (model
            # loaded); bounded so ONE wedged replica can't stall the
            # deploy indefinitely (was a hardcoded 600s)
            ray_tpu.get([r.health.remote() for r in replicas],
                        timeout=health_timeout)
        except ray_tpu.RayError as e:
            for r in replicas:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
            if isinstance(e, GetTimeoutError):
                raise DeploymentFailedError(
                    f"deployment {name!r}: replicas did not pass the "
                    f"health check within serve_replica_health_timeout_s="
                    f"{health_timeout:g}s") from e
            raise
        app["replicas"] = replicas
        with self._lock:
            self._version_counter += 1
            app["version"] = self._version_counter
            existing = self.apps.get(name)
            self.apps[name] = app
        if existing:
            for h in existing["replicas"]:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
        self._save_checkpoint()
        return True

    def _start_replica(self, app, dep_name: str):
        import uuid

        import ray_tpu

        # detached + named: replicas survive a controller crash and are
        # reattached from the checkpoint by name.  LLM replicas get two
        # extra exec threads: one is permanently pinned by the decode
        # loop, one keeps control methods (stats/health) responsive
        # when every other thread sits in a streaming request
        rname = f"serve:{dep_name}:{uuid.uuid4().hex[:8]}"
        cls = ray_tpu.remote(_Replica).options(
            max_concurrency=max(2, app["max_ongoing"])
            + (2 if app.get("llm") else 0),
            name=rname, lifetime="detached",
            **app["actor_options"])
        h = cls.remote(app["target_blob"], app["init_args"],
                       app["init_kwargs"])
        with self._lock:  # _save_checkpoint iterates this under the lock
            app["replica_names"][h._actor_id] = rname
        self._ensure_llm_loop(app, h)
        return h

    def _ensure_llm_loop(self, app, replica) -> None:
        """Install the pinned continuous-batching decode loop on an LLM
        replica (worker-side dispatch: __rt_dag_llm_loop__, serve/llm.py
        run_llm_loop).  Idempotent — the engine's run_loop is
        single-flight, so re-ensuring after a controller restart (which
        loses the in-memory loop_refs) is safe."""
        if not app.get("llm"):
            return
        try:
            from ray_tpu import api as _rapi
            from ray_tpu._private.worker import LLM_EXEC_METHOD

            w = _rapi._worker()
            ref = w.submit_actor_task(
                replica._actor_id, LLM_EXEC_METHOD, (), {})[0]
            with self._lock:
                # the ref pins the loop task owner-side; reconcile uses
                # the key set to avoid re-submitting every round
                app.setdefault("loop_refs", {})[replica._actor_id] = ref
        except Exception:
            pass  # replica mid-create or unreachable: reconcile retries

    # ---- reconciliation ----------------------------------------------------

    def _reconcile_loop(self):
        import ray_tpu
        from ray_tpu._private import tracing

        # suppressed: health probes + replacement churn would otherwise
        # mint a root trace every period and evict real traces from the
        # head's bounded store
        with tracing.suppressed():
            while not self._stop.wait(self.RECONCILE_PERIOD_S):
                with self._lock:
                    apps = dict(self.apps)
                for name, app in apps.items():
                    try:
                        self._reconcile_one(ray_tpu, name, app)
                    except Exception:
                        pass  # never let one deployment wedge the loop
                try:
                    self._refresh_replica_nodes()
                except Exception:
                    pass

    def _reconcile_one(self, ray_tpu, name: str, app: Dict[str, Any]):
        # 0. llm decode loops: replicas recovered from a checkpoint (the
        # in-memory loop_refs died with the old controller) get their
        # loop re-ensured once — harmless on running loops.  Every few
        # seconds ALSO ask each replica whether its loop is still
        # running: a loop task that died (engine error, install push
        # cancelled by a controller-connection drop) would otherwise
        # leave a black-hole replica that admits sequences nothing
        # steps — re-ensuring is idempotent (engine-side single-flight)
        if app.get("llm"):
            with self._lock:
                missing = [r for r in app["replicas"]
                           if r._actor_id not in app.get("loop_refs", {})]
            for r in missing:
                self._ensure_llm_loop(app, r)
            now0 = time.monotonic()
            if now0 >= app.get("next_loop_check", 0.0):
                app["next_loop_check"] = now0 + 3.0
                # submit all probes first so the 5s timeouts overlap —
                # one wedged replica must not stall the round 5s per
                # replica (same pattern as the health pass below)
                checks = [(r, r.handle_request.remote("stats", (), {}))
                          for r in app["replicas"]]
                for r, ref in checks:
                    try:
                        st = ray_tpu.get(ref, timeout=5)
                        if not st.get("loop_running"):
                            with self._lock:
                                app.get("loop_refs", {}).pop(
                                    r._actor_id, None)
                            self._ensure_llm_loop(app, r)
                        # engine backlog feeds the replica autoscaler:
                        # queued sequences mean token-boundary admission
                        # is falling behind this replica's decode loop
                        app.setdefault("replica_queue", {})[
                            r._actor_id] = int(st.get("queued", 0))
                    except ray_tpu.RayError:
                        app.setdefault("replica_queue", {}).pop(
                            r._actor_id, None)
        # 1. health: drop replicas that fail a health probe.  Definitive
        # death (ActorDied/worker gone) drops immediately; a TIMEOUT
        # alone needs consecutive misses — a replica paying a long jit
        # compile or a GIL-heavy stretch (an LLM replica's first
        # forward) must not be executed for being slow once, which
        # previously aborted it MID-COMPILE and churned replacements
        from ray_tpu._private.errors import GetTimeoutError

        alive = []
        changed = False
        misses = app.setdefault("health_misses", {})
        probes = [(r, r.health.remote()) for r in app["replicas"]]
        for r, probe in probes:
            try:
                ray_tpu.get(probe, timeout=5)
                alive.append(r)
                misses.pop(r._actor_id, None)
                continue
            except GetTimeoutError:
                misses[r._actor_id] = misses.get(r._actor_id, 0) + 1
                if misses[r._actor_id] < 3:
                    alive.append(r)  # grace: still routed, watched
                    continue
            except ray_tpu.RayError:
                pass  # dead for real: replace now
            changed = True
            misses.pop(r._actor_id, None)
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        # 2. autoscaling: replica count follows load signals with
        # hysteresis (see _autoscale_desired)
        desired = self._autoscale_desired(app, len(alive))
        # 3. converge replica count; scale-down victims drain first (they
        # leave the routing table now, die a few seconds later so
        # in-flight requests finish)
        now = time.monotonic()
        while len(alive) > desired:
            victim = alive.pop()
            changed = True
            app.setdefault("draining", []).append((victim, now + 5.0))
        still_draining = []
        for victim, kill_at in app.get("draining", []):
            if now >= kill_at:
                try:
                    ray_tpu.kill(victim)
                except Exception:
                    pass
            else:
                still_draining.append((victim, kill_at))
        app["draining"] = still_draining
        started = []
        while len(alive) + len(started) < desired:
            started.append(self._start_replica(app, name))
            changed = True
        for r in started:
            try:
                # bounded so one stuck constructor can't freeze recovery
                # for every other deployment; retried next round if slow
                ray_tpu.get(r.health.remote(), timeout=30)
                alive.append(r)
            except ray_tpu.RayError:
                try:
                    ray_tpu.kill(r)
                except Exception:
                    pass
        # prune stale handle reports so 'ongoing' doesn't grow unboundedly
        with self._lock:
            app["ongoing"] = {h: (c, ts) for h, (c, ts) in
                              app["ongoing"].items() if now - ts < 10.0}
            app["sheds"] = {h: (c, ts) for h, (c, ts) in
                            app.get("sheds", {}).items() if now - ts < 10.0}
        if changed:
            with self._lock:
                current = self.apps.get(name) is app
                if current:
                    app["replicas"] = alive
                    live_ids = {r._actor_id for r in alive} | {
                        v._actor_id for v, _ in app.get("draining", [])}
                    app["replica_names"] = {
                        aid: rn for aid, rn in app["replica_names"].items()
                        if aid in live_ids}
                    app["loop_refs"] = {
                        aid: ref for aid, ref in
                        app.get("loop_refs", {}).items() if aid in live_ids}
                    app["replica_queue"] = {
                        aid: q for aid, q in
                        app.get("replica_queue", {}).items()
                        if aid in live_ids}
                    app["health_misses"] = {
                        aid: n for aid, n in
                        app.get("health_misses", {}).items()
                        if aid in live_ids}
                    self._version_counter += 1
                    app["version"] = self._version_counter
            if not current:
                # app was redeployed/deleted mid-round: replicas started
                # this round would otherwise leak
                for r in started:
                    try:
                        ray_tpu.kill(r)
                    except Exception:
                        pass
            else:
                self._save_checkpoint()

    def _autoscale_desired(self, app: Dict[str, Any],
                           alive_count: int) -> int:
        """One autoscaling decision for one deployment.

        Signals (reference: autoscaling_policy.py, extended for the LLM
        tier): windowed handle-reported ongoing requests, replica-side
        engine queue depth (the stats probe above — sequences parked at
        token-boundary admission), and handle-reported 503 sheds (a
        shed means capacity is short RIGHT NOW: desired jumps past the
        current count instead of waiting for averages to catch up).

        Hysteresis: an upscale needs the computed desired above the
        current one for ``serve_autoscale_up_consecutive`` consecutive
        reconcile rounds; a downscale needs it below for
        ``serve_autoscale_down_delay_s`` — one burst neither thrashes
        replicas up nor tears warm replicas down the moment it ends."""
        auto = app.get("autoscaling")
        if not auto:
            return app["desired"]
        import math

        from ray_tpu._private.config import config as _cfg

        now = time.monotonic()
        with self._lock:
            reports = list(app["ongoing"].values())
            shed_reports = list(app.get("sheds", {}).values())
        total = sum(c for c, ts in reports if now - ts < 5.0)
        recent_sheds = sum(c for c, ts in shed_reports if now - ts < 5.0)
        queued = sum(app.get("replica_queue", {}).values())
        target = max(1, int(auto.get(
            "target_ongoing_requests",
            _cfg.serve_autoscale_target_ongoing)))
        want = math.ceil((total + queued) / target)
        if recent_sheds:
            want = max(want, alive_count + 1)
        lo = int(auto.get("min_replicas",
                          _cfg.serve_autoscale_min_replicas))
        hi = int(auto.get("max_replicas",
                          _cfg.serve_autoscale_max_replicas))
        want = min(hi, max(lo, want))
        cur = app["desired"]
        up_needed = max(1, int(auto.get(
            "upscale_consecutive", _cfg.serve_autoscale_up_consecutive)))
        down_delay = float(auto.get("downscale_delay_s",
                                    _cfg.serve_autoscale_down_delay_s))
        if want > cur:
            app["up_streak"] = app.get("up_streak", 0) + 1
            app["below_since"] = None
            if app["up_streak"] >= up_needed:
                app["desired"] = want
                app["up_streak"] = 0
        elif want < cur:
            app["up_streak"] = 0
            t0 = app.get("below_since")
            if t0 is None:
                app["below_since"] = now
            elif now - t0 >= down_delay:
                app["desired"] = want
                app["below_since"] = None
        else:
            app["up_streak"] = 0
            app["below_since"] = None
        app["last_autoscale"] = {
            "want": want, "ongoing": total, "queued": queued,
            "sheds": recent_sheds, "desired": app["desired"]}
        return app["desired"]

    def autoscale_status(self, name: str):
        """Debuggability: the last autoscale inputs/decision for one
        deployment (surfaced by tests and `rtpu status`-adjacent
        tooling)."""
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return None
            return {"desired": app["desired"],
                    "replicas": len(app["replicas"]),
                    "autoscaling": dict(app.get("autoscaling") or {}),
                    "last": dict(app.get("last_autoscale") or {})}

    # ---- handle-facing RPCs ------------------------------------------------

    def get_replicas(self, name: str, known_version: int = -1):
        with self._lock:
            app = self.apps.get(name)
            if app is None:
                return None
            if known_version == app["version"]:
                return {"version": app["version"], "unchanged": True}
            ids = [r._actor_id for r in app["replicas"]]
            nodes = app.get("replica_nodes", {})
            return {"version": app["version"],
                    "replica_ids": ids,
                    "replica_nodes": [nodes.get(i, "") for i in ids],
                    "max_ongoing": app["max_ongoing"],
                    "policy": app.get("policy") or {}}

    def _refresh_replica_nodes(self) -> None:
        """Map replica actor ids to their nodes (for locality-aware
        routing; reference: pow_2_scheduler.py prefers same-node
        replicas)."""
        import ray_tpu

        with self._lock:
            if not self.apps:
                return  # idle controller: skip the cluster-wide RPC
        try:
            actors = ray_tpu.api._worker().head.call("list_actors",
                                                     timeout=10)["actors"]
        except Exception:
            return
        node_of = {a["actor_id"]: a.get("node_id", "") for a in actors}
        with self._lock:
            for app in self.apps.values():
                app["replica_nodes"] = {
                    r._actor_id: node_of.get(r._actor_id, "")
                    for r in app["replicas"]}

    def report_metrics(self, name: str, handle_id: str, ongoing: int,
                       sheds: int = 0):
        with self._lock:
            app = self.apps.get(name)
            if app is not None:
                now = time.monotonic()
                app["ongoing"][handle_id] = (ongoing, now)
                if sheds:
                    app.setdefault("sheds", {})[handle_id] = (sheds, now)
        return True

    def delete(self, name: str):
        import ray_tpu

        with self._lock:
            app = self.apps.pop(name, None)
        if app:
            victims = list(app["replicas"]) + [
                v for v, _ in app.get("draining", [])]
            for h in victims:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass
            self._save_checkpoint()
        return True

    def list_deployments(self):
        with self._lock:
            return {name: len(app["replicas"])
                    for name, app in self.apps.items()}


# ------------------------------------------------------------------ handle


class _SharedWaiter:
    """One background thread per process that watches in-flight serve
    refs and fires completion callbacks — replaces the former
    thread-per-request watcher."""

    def __init__(self):
        self._lock = threading.Lock()
        self._items: Dict[str, Callable[[], None]] = {}  # oid -> cb
        self._refs: Dict[str, Any] = {}
        # streaming calls: task_id -> (ObjectRefGenerator, cb); fired
        # when the underlying generator TASK completes/errors, which is
        # what keeps inflight accounting honest for streams the consumer
        # abandons without ever iterating
        self._gens: Dict[str, Any] = {}
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _start_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="serve-waiter", daemon=True)
            self._thread.start()

    def watch(self, ref, cb: Callable[[], None]) -> None:
        with self._lock:
            self._items[ref.oid] = cb
            self._refs[ref.oid] = ref
            self._start_locked()
        self._wake.set()

    def watch_gen(self, gen, cb: Callable[[], None]) -> None:
        """Fire ``cb`` once the streaming generator's replica-side task
        has finished producing (completed OR errored) — independent of
        whether any consumer ever iterates the stream."""
        with self._lock:
            self._gens[gen.task_id] = (gen, cb)
            self._start_locked()
        self._wake.set()

    def _check_gens(self) -> None:
        with self._lock:
            gens = list(self._gens.items())
        for tid, (gen, cb) in gens:
            try:
                done = gen.completed()
            except Exception:
                done = True  # runtime gone: release rather than leak
            if not done:
                continue
            with self._lock:
                if self._gens.pop(tid, None) is None:
                    continue
            try:
                cb()
            except Exception:
                pass

    def _run(self):
        import ray_tpu

        idle_rounds = 0
        err_rounds = 0
        while True:
            self._check_gens()
            with self._lock:
                refs = list(self._refs.values())
                if not refs and not self._gens and idle_rounds >= 100:
                    # retire under the lock so a concurrent watch() either
                    # sees a dead thread (and restarts one) or we see its ref
                    self._thread = None
                    return
                busy = bool(refs or self._gens)
            if busy:
                idle_rounds = 0
            if not refs:
                self._wake.wait(0.1)
                self._wake.clear()
                idle_rounds += 1
                continue
            try:
                ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.2)
                err_rounds = 0
            except Exception:
                # transient runtime trouble must not fire callbacks for
                # still-running requests; drain only if it persists
                # (runtime torn down)
                err_rounds += 1
                if err_rounds < 50:
                    time.sleep(0.1)
                    continue
                ready = refs
            for r in ready:
                with self._lock:
                    cb = self._items.pop(r.oid, None)
                    self._refs.pop(r.oid, None)
                if cb is not None:
                    try:
                        cb()
                    except Exception:
                        pass


_shared_waiter = _SharedWaiter()


def _abandon_stream(gen) -> None:
    """A stream consumer stopped before exhaustion (client disconnect,
    early break, GC of the wrapper): cancel the replica-side generator
    so it stops producing — an abandoned LLM decode must free its KV
    pages, not generate to max_seq_len for nobody.  No-op for streams
    whose producer already finished."""
    try:
        if not gen.completed():
            gen.cancel()
    except Exception:
        pass


def _watch_ref_done(ref, cb) -> None:
    """Fire ``cb`` once `ref` resolves (value OR error), releasing a
    handle's inflight charge.

    Fast path for refs owned by this process (every handle call — the
    submit happens locally): ONE memory-store waiter, fired on the IO
    thread at resolution, O(1) per request.  The closure pins the ref so
    the entry cannot be evicted (and the callback lost) if the caller
    abandons the ref mid-flight.  The shared waiter's wait()-polling
    loop — which re-registers EVERY in-flight ref on each round and eats
    the GIL under high concurrency — is kept only as the fallback for
    refs owned elsewhere."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    if (w is not None and ref.owner_addr is not None
            and tuple(ref.owner_addr) == w.address):
        pin = [ref]

        def _fire():
            pin.clear()
            cb()

        if w.memory.add_waiter(ref.oid, _fire) is None:
            cb()  # already resolved
        return
    _shared_waiter.watch(ref, cb)


class _MetricsPusher:
    """ONE daemon thread pushing windowed-average ongoing requests for
    every live handle (reference: serve/_private/metrics_utils.py
    MetricsPusher).  Sampling on a clock — instead of piggybacking point
    reads on submit — keeps autoscaling correct when request completion
    is phase-aligned with submission bursts.  Handles are held by
    weakref: an abandoned handle (proxy re-creates them on RayError)
    simply drops out, so no thread or GC pin leaks with handle churn."""

    SAMPLE_PERIOD_S = 0.1
    PUSH_PERIOD_S = 0.5
    WINDOW = 20  # samples (~2 s)

    def __init__(self):
        self._lock = threading.Lock()
        self._handles: List[Any] = []  # weakref.ref[DeploymentHandle]
        self._thread: Optional[threading.Thread] = None

    def register(self, handle) -> None:
        import weakref

        with self._lock:
            self._handles.append(weakref.ref(handle))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, name="serve-metrics", daemon=True)
                self._thread.start()

    def _run(self):
        from ray_tpu._private import tracing

        with tracing.suppressed():  # metric pushes are not user traffic
            while True:
                time.sleep(self.SAMPLE_PERIOD_S)
                with self._lock:
                    live = [(r, h) for r in self._handles
                            if (h := r()) is not None]
                    self._handles = [r for r, _ in live]
                    if not live:
                        self._thread = None  # retire; register() restarts
                        return
                now = time.monotonic()
                for _, h in live:
                    try:
                        self._sample_and_push(h, now)
                    except Exception:
                        pass  # runtime down or controller restarting

    def _sample_and_push(self, h, now: float) -> None:
        with h._lock:
            h._samples.append(sum(h._inflight.values()))
            if len(h._samples) > self.WINDOW:
                h._samples = h._samples[-self.WINDOW:]
            avg = sum(h._samples) / len(h._samples)
        if now - h._last_push < self.PUSH_PERIOD_S:
            return
        h._last_push = now
        with h._lock:
            sheds, h._sheds_pending = h._sheds_pending, 0
        ctrl = _controller()
        ctrl.report_metrics.remote(h._name, h._handle_id, int(round(avg)),
                                   sheds)


_metrics_pusher = _MetricsPusher()


class ReplicaCircuit:
    """Per-replica circuit breaker (reference intent: the router's
    replica health gating; the mechanism is the classic three-state
    breaker).  Failures AND hedge-slow events feed one time-decayed
    score; crossing ``fail_threshold`` opens the circuit and the
    replica leaves routing immediately — a gray (slow-not-dead) replica
    is evicted within a few hedge delays instead of waiting out 3
    health-probe periods.  After ``cooldown_s`` the breaker goes
    half-open: exactly ONE probe request is let through; its success
    closes the breaker, its failure re-opens it.

    The clock is injectable so the state machine unit-tests run
    sleep-free."""

    __slots__ = ("fail_threshold", "decay_s", "cooldown_s", "clock",
                 "score", "scored_at", "state", "opened_at", "probing",
                 "probe_since")

    def __init__(self, fail_threshold: Optional[float] = None,
                 decay_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        from ray_tpu._private.config import config

        self.fail_threshold = float(
            fail_threshold if fail_threshold is not None
            else config.serve_circuit_fail_threshold)
        self.decay_s = float(decay_s if decay_s is not None
                             else config.serve_circuit_decay_s)
        self.cooldown_s = float(cooldown_s if cooldown_s is not None
                                else config.serve_circuit_cooldown_s)
        self.clock = clock
        self.score = 0.0
        self.scored_at = self.clock()
        self.state = "closed"
        self.opened_at = 0.0
        self.probing = False
        self.probe_since = 0.0

    def _decayed(self, now: float) -> float:
        # exponential half-life decay: one old burst of failures stops
        # mattering within a few decay windows with no bookkeeping
        age = max(0.0, now - self.scored_at)
        return self.score * (0.5 ** (age / self.decay_s))

    def record_failure(self, weight: float = 1.0) -> bool:
        """An error, timeout, or hedge-slow event against this replica.
        Returns True when this event OPENED the circuit (callers count
        ray_tpu_serve_circuit_open_total on the transition)."""
        now = self.clock()
        self.score = self._decayed(now) + weight
        self.scored_at = now
        if self.state == "half_open":
            # the probe failed: straight back to open, fresh cooldown
            self.state = "open"
            self.opened_at = now
            self.probing = False
            return False
        if self.state == "closed" and self.score >= self.fail_threshold:
            self.state = "open"
            self.opened_at = now
            self.probing = False
            return True
        return False

    def record_success(self) -> None:
        now = self.clock()
        if self.state == "half_open":
            self.state = "closed"
            self.score = 0.0
            self.scored_at = now
            self.probing = False
            return
        # successes actively pay DOWN the score (on top of time decay):
        # a mostly-healthy replica serving real traffic can never
        # accumulate its way to the threshold from the tail-rate slow
        # events p99 hedging produces by construction — only a replica
        # whose failures/slowness OUTPACE its successes opens
        self.score = max(0.0, self._decayed(now) - 0.5)
        self.scored_at = now

    def routable(self) -> bool:
        """May a request be routed to this replica right now?  Open →
        no; past the cooldown the breaker turns half-open and admits
        requests only while no probe is in flight.  Non-consuming: the
        picker calls ``note_picked`` on the replica it actually chose."""
        if self.state == "closed":
            return True
        now = self.clock()
        if self.state == "open":
            if now - self.opened_at < self.cooldown_s:
                return False
            self.state = "half_open"
            self.probing = False
        if self.probing and self.probe_since \
                and now - self.probe_since > max(2 * self.cooldown_s, 5.0):
            # stale probe: its outcome was never recorded (the probe
            # request was a stream, or a cancelled hedge loser) — a
            # lost probe must not wedge the replica out of routing
            # forever
            self.probing = False
        return not self.probing

    def note_picked(self) -> None:
        """The router chose this replica; a half-open breaker marks its
        single probe in flight (cleared by the probe's outcome, or by
        the stale-probe expiry in ``routable``)."""
        if self.state == "half_open":
            self.probing = True
            self.probe_since = self.clock()

    def allow(self) -> bool:
        """Convenience for tests/direct users: routable-and-picked in
        one step (exactly one half-open probe gets True)."""
        if not self.routable():
            return False
        self.note_picked()
        return True


class DeploymentHandle:
    """Client-side router: least-outstanding-requests replica choice
    (reference: router.py assign_request + pow_2_scheduler.py), with
    periodic replica-list refresh from the controller and load reporting
    for autoscaling."""

    REFRESH_PERIOD_S = 1.0

    def __init__(self, name: str, replica_ids: List[str], version: int = 0,
                 replica_nodes: Optional[List[str]] = None,
                 max_ongoing: int = 8,
                 policy: Optional[Dict[str, Any]] = None):
        import uuid
        from collections import deque

        from ray_tpu._private.worker import global_worker_or_none

        self._name = name
        self._handle_id = uuid.uuid4().hex[:12]
        self._lock = threading.Lock()
        self._version = version
        self._max_ongoing = max_ongoing
        # tail-tolerance policy (Deployment.policy(): request_timeout_s,
        # hedge_after_s | "p99", idempotent) — learned from the
        # controller, refreshed with the roster
        self._policy: Dict[str, Any] = dict(policy or {})
        # per-replica circuit breakers + a windowed latency sample ring
        # (the hedge_after="p99" source; the computed p99 is cached and
        # refreshed every ~20 samples — sorting 200 floats under the
        # handle lock per request would be hot-path waste)
        self._circuits: Dict[str, ReplicaCircuit] = {}
        self._latencies: "deque" = deque(maxlen=200)
        self._lat_version = 0
        self._p99_cache: Optional[tuple] = None  # (version, value)
        w = global_worker_or_none()
        self._my_node = w.node_id if w is not None else ""
        self._set_replicas(replica_ids, replica_nodes)
        self._last_refresh = time.monotonic()
        self._samples: List[int] = []  # recent inflight samples (window)
        self._last_push = 0.0
        # 503s observed against this deployment (proxy gate or replica
        # admission), drained to the controller with each metrics push —
        # the replica autoscaler's immediate scale-up trigger
        self._sheds_pending = 0
        # lazily-built handle to the sibling "<name>-prefill" pool
        # (disaggregated prefill; see _maybe_prefill)
        self._prefill_handle: Optional["DeploymentHandle"] = None
        _metrics_pusher.register(self)

    def note_shed(self) -> None:
        with self._lock:
            self._sheds_pending += 1

    # ---- tail tolerance ---------------------------------------------------

    def _circuit(self, rid: str) -> ReplicaCircuit:
        c = self._circuits.get(rid)
        if c is None:
            c = self._circuits.setdefault(rid, ReplicaCircuit())
        return c

    def _record_outcome(self, rid: str, latency_s: Optional[float] = None,
                        error: bool = False, slow: bool = False) -> None:
        """Feed one request outcome into the replica's breaker (and the
        handle's latency window).  A breaker OPEN transition counts in
        ray_tpu_serve_circuit_open_total — the moment a gray replica
        leaves routing."""
        c = self._circuit(rid)
        if error or slow:
            if c.record_failure():
                try:
                    from ray_tpu._private.metrics import serve_tail_metrics

                    serve_tail_metrics()[1].inc(
                        tags={"deployment": self._name})
                except Exception:
                    pass
        else:
            c.record_success()
            if latency_s is not None:
                with self._lock:
                    self._latencies.append(latency_s)
                    self._lat_version += 1

    def _hedge_delay(self) -> Optional[float]:
        """Seconds to wait before firing a duplicate request, or None
        when hedging is off for this deployment.  Hedging requires the
        deployment to be declared idempotent — a duplicate of a
        non-idempotent request could double-apply side effects."""
        pol = self._policy
        h = pol.get("hedge_after_s")
        if h is None or not pol.get("idempotent"):
            return None
        if isinstance(h, (int, float)):
            return max(0.0, float(h))
        # "p99": track the observed distribution; until enough samples
        # exist, hedge at the configured floor
        from ray_tpu._private.config import config

        floor = float(config.serve_hedge_min_delay_s)
        with self._lock:
            cached = self._p99_cache
            if cached is not None and self._lat_version - cached[0] < 20:
                return max(floor, cached[1])
            samples = sorted(self._latencies)
            if len(samples) < 10:
                return floor
            p99 = samples[min(len(samples) - 1,
                              int(0.99 * len(samples)))]
            self._p99_cache = (self._lat_version, p99)
        return max(floor, p99)

    def _set_replicas(self, replica_ids: List[str],
                      replica_nodes: Optional[List[str]] = None):
        from ray_tpu.api import ActorHandle

        self._replicas = [ActorHandle(rid) for rid in replica_ids]
        self._replica_nodes = dict(zip(replica_ids, replica_nodes or []))
        # inflight is keyed by actor id so counts survive replica-list
        # swaps: late completion callbacks decrement the right counter
        # instead of corrupting a rebuilt positional array
        old = getattr(self, "_inflight", {})
        self._inflight = {rid: old.get(rid, 0) for rid in replica_ids}
        # breakers for replicas no longer in the roster are dropped (a
        # replaced replica's id never comes back)
        self._circuits = {rid: c for rid, c in self._circuits.items()
                          if rid in self._inflight}

    def _maybe_refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < self.REFRESH_PERIOD_S:
            return
        import ray_tpu

        self._last_refresh = now
        try:
            ctrl = _controller()
            # an empty local roster (every cached replica was dropped as
            # dead) asks for the FULL roster: sending our version would
            # get "unchanged" back and leave the handle empty forever
            known = self._version if self._replicas else -1
            info = ray_tpu.get(
                ctrl.get_replicas.remote(self._name, known),
                timeout=30)
        except Exception:
            # refresh is best-effort: during a controller restart the
            # cached replica set (detached actors, still alive) keeps
            # serving — a failed refresh must not fail the request
            return
        self._apply_refresh(info)

    async def _refresh_async(self, force: bool = False):
        """Awaitable replica-list refresh for event-loop callers: the
        controller reply is awaited via get_async instead of blocking
        the loop's thread.  (_controller() itself still does one sync
        name-resolution RPC — sub-ms, once per refresh period.)"""
        now = time.monotonic()
        if not force and now - self._last_refresh < self.REFRESH_PERIOD_S:
            return
        import ray_tpu

        self._last_refresh = now
        try:
            ctrl = _controller()
            known = self._version if self._replicas else -1
            info = await ray_tpu.get_async(
                ctrl.get_replicas.remote(self._name, known),
                timeout=30)
        except Exception:
            return  # best-effort, same as the sync path
        self._apply_refresh(info)

    def _apply_refresh(self, info) -> None:
        if info is None or info.get("unchanged"):
            return
        # same-version rosters still apply when the local list is empty:
        # a handle that _drop_replica'd its way to zero (every cached
        # replica looked dead) must be able to re-learn the roster even
        # though the controller's version never moved
        if info["version"] != self._version or not self._replicas:
            with self._lock:
                self._version = info["version"]
                self._max_ongoing = info.get("max_ongoing",
                                             self._max_ongoing)
                if info.get("policy") is not None:
                    self._policy = dict(info["policy"])
                self._set_replicas(info["replica_ids"],
                                   info.get("replica_nodes"))

    def _pick_replica(self, local_pref: bool = True, exclude=None,
                      probe: bool = False):
        """Choose a replica (least-outstanding-requests) and charge it
        +1 inflight; returns (replica, rid).  ``exclude`` filters out
        replicas a retrying caller already saw die — unless that would
        leave nothing, in which case every replica is fair game again
        (the exclusion list may be stale across a re-heal).  ``probe``
        marks a half-open pick as the breaker's single probe — only
        callers that RECORD outcomes (call_async) pass it; a stream
        pick must not consume the probe slot its outcome would never
        release."""
        import random

        with self._lock:
            if not self._replicas:
                raise RuntimeError(
                    f"deployment {self._name!r} has no replicas")
            candidates = self._replicas
            if exclude:
                alive = [r for r in candidates
                         if r._actor_id not in exclude]
                candidates = alive or candidates
            # circuit-broken replicas leave routing (open breaker) until
            # their half-open probe re-admits them; if EVERY candidate
            # is broken, routing falls back to all of them — degraded
            # service beats refusing to route at all
            if self._circuits:
                healthy = [r for r in candidates
                           if (c := self._circuits.get(r._actor_id))
                           is None or c.routable()]
                candidates = healthy or candidates
            # locality-aware power-of-two (reference:
            # pow_2_scheduler.py:717): prefer same-node replicas only
            # while they have queue headroom — a saturated local replica
            # must not absorb all ingress while remote ones sit idle —
            # then sample two candidates, take the fewer-outstanding one
            local = [r for r in candidates
                     if self._replica_nodes.get(r._actor_id)
                     == self._my_node
                     and self._inflight.get(r._actor_id, 0)
                     < self._max_ongoing] \
                if (local_pref and self._my_node) else []
            pool = local or candidates
            if len(pool) > 2:
                pool = random.sample(pool, 2)
            replica = min(pool,
                          key=lambda r: self._inflight.get(r._actor_id, 0))
            rid = replica._actor_id
            self._inflight[rid] = self._inflight.get(rid, 0) + 1
            c = self._circuits.get(rid)
            if probe and c is not None:
                c.note_picked()  # a half-open pick is THE probe
        return replica, rid

    def _submit_call(self, replica, rid: str, _method: str, args, kwargs):
        """Submit one replica call (non-blocking) under a handle-call
        span; registers the completion watcher that releases the
        inflight charge.  Shared by remote() and remote_async()."""
        # handle-call span: ties a Serve request (HTTP ingress span or an
        # in-cluster caller's active trace) to the replica-side actor
        # task — the submit/execute spans chain under it automatically
        from ray_tpu._private import tracing

        span = tracing.start_span(f"serve.handle {self._name}",
                                  kind=tracing.KIND_CLIENT,
                                  attributes={"replica_id": rid,
                                              "method": _method})
        token = tracing.activate(span.context()) if span else None
        try:
            ref = replica.handle_request.remote(_method, args, kwargs)
        finally:
            if span is not None:
                tracing.restore(token)
                span.end()

        def _done_cb(rid=rid):
            with self._lock:
                if rid in self._inflight:
                    self._inflight[rid] -= 1

        _watch_ref_done(ref, _done_cb)
        return ref

    def remote(self, *args, _method: str = "__call__", **kwargs):
        self._maybe_refresh()
        if not self._replicas:
            self._maybe_refresh(force=True)
        replica, rid = self._pick_replica()
        return self._submit_call(replica, rid, _method, args, kwargs)

    async def remote_async(self, *args, _method: str = "__call__", **kwargs):
        """Async-native remote(): same least-outstanding-requests
        replica choice and inflight accounting, but the periodic
        controller refresh is awaited on the calling loop instead of
        blocking a thread.  Returns the ObjectRef — ``await ref`` (or
        ``ray_tpu.get_async``) for the value.  The async Serve ingress
        routes every request through this."""
        await self._refresh_async()
        if not self._replicas:
            await self._refresh_async(force=True)
        replica, rid = self._pick_replica()
        return self._submit_call(replica, rid, _method, args, kwargs)

    def _drop_replica(self, rid: str) -> None:
        """A call to this replica died: stop routing to it NOW, without
        waiting for the next controller refresh — during node churn the
        refresh window would otherwise keep feeding a dead replica."""
        with self._lock:
            self._replicas = [r for r in self._replicas
                              if r._actor_id != rid]
            self._replica_nodes.pop(rid, None)
            self._inflight.pop(rid, None)

    async def call_async(self, *args, _method: str = "__call__",
                         _timeout: float = 120.0, **kwargs):
        """Submit AND await one call, retrying dead replicas: if the
        picked replica died mid-flight (its node was SIGKILLed under
        load), the request is re-sent to a surviving replica instead of
        surfacing ActorDiedError to the client — graceful degradation
        under churn.  User exceptions (RayTaskError) are NEVER retried;
        only replica-death errors are, ``serve_dead_replica_retries``
        times, with a forced controller refresh between attempts.

        Tail tolerance rides here too: the deployment's
        ``request_timeout_s`` caps the budget (combined with any
        ambient deadline — an X-Request-Deadline-Ms ingress header —
        and stamped into the replica task so the downstream tree
        inherits it), IDEMPOTENT deployments hedge a duplicate request
        to a second replica after the hedge delay (first response
        wins, the loser is cancelled), and every outcome feeds the
        per-replica circuit breaker."""
        from ray_tpu._private import deadlines
        from ray_tpu._private.config import config
        from ray_tpu._private.errors import (ActorDiedError,
                                             ActorUnavailableError,
                                             DeadlineExceededError,
                                             RayWorkerError)

        await self._refresh_async()
        if not self._replicas:
            await self._refresh_async(force=True)
        rt = self._policy.get("request_timeout_s")
        policy_bound = rt is not None and float(rt) < _timeout
        if policy_bound:
            _timeout = float(rt)
        ambient = deadlines.current_deadline()
        # only a REAL bound (policy or ambient header deadline) stamps a
        # deadline into the replica task — the transport's 120s default
        # must not arm the deadline sweep for every unbounded request,
        # shorten a client's explicit (longer) header deadline, or
        # convert a long-running request into a 504
        if policy_bound:
            deadline = deadlines.effective_deadline(_timeout)
        else:
            deadline = ambient  # None when truly unbounded
        bounded = policy_bound or ambient is not None
        attempts = 1 + max(0, int(config.serve_dead_replica_retries))
        dead: set = set()
        for attempt in range(attempts):
            if not self._replicas:
                await self._refresh_async(force=True)
            replica, rid = self._pick_replica(exclude=dead, probe=True)
            try:
                return await self._await_call(replica, rid, _method, args,
                                              kwargs, deadline, bounded,
                                              dead, _timeout, policy_bound)
            except DeadlineExceededError:
                raise  # the budget is gone; retrying cannot help
            except (ActorDiedError, ActorUnavailableError,
                    RayWorkerError):
                # includes OutOfMemoryError (a RayWorkerError subclass):
                # a replica OOM-killed by the node memory watchdog reads
                # as replica death here — _one already fed the breaker a
                # failure, so repeated OOMs open the circuit and routing
                # heals away from the starved node while the controller
                # restarts the replica
                dead.add(rid)
                self._drop_replica(rid)
                if attempt == attempts - 1:
                    raise
                # the controller may have re-healed already; otherwise
                # surviving cached replicas keep serving
                await self._refresh_async(force=True)

    async def _await_call(self, replica, rid: str, _method: str, args,
                          kwargs, deadline: Optional[float],
                          bounded: bool, dead: set,
                          _timeout: float = 120.0,
                          policy_bound: bool = False):
        """One submit-and-await attempt, with hedging.  The replica
        task is submitted under the active deadline (so the spec
        carries it); if the primary has not answered after the hedge
        delay, a duplicate fires against a second replica — first
        response wins and the loser is cancelled through the task
        cancel machinery.  Outcomes (latency, errors, hedge-slowness)
        feed the per-replica circuit breakers."""
        import asyncio

        import ray_tpu
        from ray_tpu._private import deadlines
        from ray_tpu._private.errors import (DeadlineExceededError,
                                             GetTimeoutError, RayTaskError)

        def _budget() -> float:
            rem = deadlines.remaining(deadline)
            return _timeout if rem is None else rem

        def _submit(rep, rep_id):
            token = deadlines.activate(deadline) if deadline else None
            try:
                return self._submit_call(rep, rep_id, _method, args, kwargs)
            finally:
                if token is not None:
                    deadlines.restore(token)

        async def _one(ref, rep_id, t_start):
            try:
                out = await ray_tpu.get_async(ref, timeout=_budget())
            except GetTimeoutError:
                # a miss of the DEPLOYMENT's own SLO is a replica-health
                # signal; an expiry of the CLIENT's (possibly
                # impossibly-tight) header budget is not — feeding the
                # latter to the breaker would open circuits on healthy
                # replicas whenever an upstream sends doomed budgets
                if policy_bound:
                    self._record_outcome(rep_id, error=True)
                if bounded:
                    deadlines.count_exceeded("get")
                    raise DeadlineExceededError(
                        f"deployment {self._name!r} request exceeded its "
                        f"deadline", where="get") from None
                raise
            except RayTaskError:
                raise  # application error: not a replica-health signal
            except ray_tpu.RayError:
                self._record_outcome(rep_id, error=True)
                raise
            self._record_outcome(rep_id,
                                 latency_s=time.monotonic() - t_start)
            return out

        hedge_delay = self._hedge_delay()
        t0 = time.monotonic()
        ref = _submit(replica, rid)
        primary = asyncio.ensure_future(_one(ref, rid, t0))
        if hedge_delay is None:
            return await primary
        done, _ = await asyncio.wait({primary}, timeout=hedge_delay)
        if done:
            return primary.result()  # answered before the hedge delay
        try:
            h_replica, h_rid = self._pick_replica(
                exclude={rid} | set(dead), probe=True)
        except RuntimeError:
            return await primary  # nowhere to hedge to
        if h_rid == rid:
            # exclusion exhausted (single live replica): nothing was
            # submitted for this pick — release its inflight charge or
            # every bailed hedge would inflate the count forever
            with self._lock:
                if rid in self._inflight:
                    self._inflight[rid] -= 1
            return await primary
        from ray_tpu._private.metrics import serve_tail_metrics

        hedges = serve_tail_metrics()[0]
        h_ref = _submit(h_replica, h_rid)
        hedge = asyncio.ensure_future(_one(h_ref, h_rid,
                                           time.monotonic()))
        tasks = {primary: (ref, rid), hedge: (h_ref, h_rid)}
        pending = set(tasks)
        first_error = None
        try:
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    if t.exception() is None:
                        if t is hedge:
                            # the duplicate beat the primary: THAT is
                            # the gray-replica breaker signal (a p99
                            # hedge against a healthy primary usually
                            # loses the race, so healthy replicas
                            # don't accumulate slow events at the
                            # hedge-fire rate)
                            self._record_outcome(rid, slow=True)
                            hedges.inc(tags={"outcome": "won"})
                        else:
                            hedges.inc(tags={"outcome": "lost"})
                        return t.result()
                    if first_error is None or t is primary:
                        first_error = t.exception()
            raise first_error
        finally:
            # cancel the loser: its replica must stop working on a
            # request nobody will read (same machinery as client-
            # disconnect generator cancel)
            for t, (loser_ref, _loser_rid) in tasks.items():
                if not t.done():
                    t.cancel()
                    try:
                        from ray_tpu._private.ids import ObjectID
                        from ray_tpu._private.worker import \
                            global_worker_or_none

                        w = global_worker_or_none()
                        if w is not None:
                            tid = ObjectID(bytes.fromhex(
                                loser_ref.oid)).task_id().hex()
                            w._spawn(w._cancel_async(tid, False))
                    except Exception:
                        pass

    def _submit_stream(self, replica, rid: str, _method: str, args, kwargs):
        """Submit one streaming replica call; returns (gen, release)."""
        from ray_tpu._private import tracing

        span = tracing.start_span(f"serve.stream {self._name}",
                                  kind=tracing.KIND_CLIENT,
                                  attributes={"replica_id": rid,
                                              "method": _method})
        token = tracing.activate(span.context()) if span else None
        try:
            gen = replica.stream_request.options(
                num_returns="streaming").remote(_method, args, kwargs)
        finally:
            if span is not None:
                tracing.restore(token)
                span.end()
        released = [False]

        def _release(rid=rid):
            # once-only: both the consumer finally and the waiter fire
            with self._lock:
                if released[0]:
                    return
                released[0] = True
                if rid in self._inflight:
                    self._inflight[rid] -= 1

        # the consumer-side finally alone LEAKS: a generator that is
        # never iterated never enters its try block, so an abandoned
        # stream() call would pin +1 inflight on the replica forever and
        # skew least-inflight selection.  The shared waiter decrements
        # when the replica-side task finishes producing (or errors), no
        # matter what the consumer does.
        _shared_waiter.watch_gen(gen, _release)
        return gen, _release

    def stream(self, *args, _method: str = "__call__", **kwargs):
        """Call a generator endpoint; yields one ObjectRef per item as
        the replica produces them (reference: DeploymentResponseGenerator
        in serve/handle.py).  Token streaming for TPU inference rides
        this: the replica yields tokens, callers consume mid-generation."""
        self._maybe_refresh()
        if not self._replicas:
            self._maybe_refresh(force=True)
        replica, rid = self._pick_replica(local_pref=False)
        gen, _release = self._submit_stream(replica, rid, _method, args,
                                            kwargs)

        def _wrapped():
            try:
                yield from gen
            finally:
                _abandon_stream(gen)
                _release()

        return _wrapped()

    async def _maybe_prefill(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Disaggregated-prefill hop: when the deployment's policy names
        a prefill pool, route the request's prefill phase to a dedicated
        replica there first.  The pool replica runs chunked prefill,
        exports the finished KV pages into the object store (the
        cross-node pull rides the bulk transfer plane, checksummed with
        alternate-holder retry), and returns a ``kv_ref``; the decode
        replica attaches the shipped pages by request_id and starts at
        the first generated token.  Any prefill-pool failure other than
        a deadline falls back to colocated prefill on the decode replica
        — disaggregation is an optimisation, never a new failure mode.
        Deadline errors propagate: the budget is gone either way."""
        pool = self._policy.get("prefill_pool")
        if (not pool or not isinstance(request, dict)
                or request.get("kv_ref") is not None
                or not request.get("tokens")):
            return request
        from ray_tpu._private.config import config
        from ray_tpu._private.errors import DeadlineExceededError
        try:
            if len(request["tokens"]) < int(config.llm_disagg_min_prompt):
                return request
        except TypeError:
            return request
        request = dict(request)
        if not request.get("request_id"):
            import uuid

            request["request_id"] = uuid.uuid4().hex
        handle = self._prefill_handle
        if handle is None or handle._name != pool:
            import asyncio

            loop = asyncio.get_running_loop()
            try:
                handle = await loop.run_in_executor(
                    None, get_handle, pool)
            except Exception:
                return request  # pool missing/unhealthy: prefill locally
            self._prefill_handle = handle
        try:
            meta = await handle.call_async(request, _method="prefill")
        except DeadlineExceededError:
            raise
        except Exception:
            return request  # fall back to colocated prefill
        if isinstance(meta, dict) and meta.get("kv_ref") is not None:
            request["kv_ref"] = meta["kv_ref"]
        return request

    async def stream_async(self, *args, _method: str = "__call__",
                           _exclude=None, _info=None, **kwargs):
        """Async stream(): returns an async iterator of per-item
        ObjectRefs, item arrival awaited on the calling loop (no thread
        parked per stream).  The replica call is submitted EAGERLY in
        the caller's context — an active ingress span parents the
        serve.stream span, and an abandoned (never-iterated) stream
        still releases its inflight charge via the shared waiter.

        ``_exclude``/``_info`` serve the proxy's mid-stream resume
        retry: a retrying caller learns which replica served it (rid
        recorded into ``_info``) and skips replicas it already saw die
        — a freshly-refreshed roster may briefly still list them, and
        a dead replica's zero inflight makes least-outstanding choice
        otherwise gravitate right back to it."""
        await self._refresh_async()
        if not self._replicas:
            await self._refresh_async(force=True)
        if (_method == "__call__" and not _exclude and len(args) == 1
                and isinstance(args[0], dict)):
            args = (await self._maybe_prefill(args[0]),)
        replica, rid = self._pick_replica(local_pref=False,
                                          exclude=_exclude)
        if _info is not None:
            _info["rid"] = rid
        gen, _release = self._submit_stream(replica, rid, _method, args,
                                            kwargs)

        async def _aiter():
            try:
                async for ref in gen:
                    yield ref
            finally:
                _abandon_stream(gen)
                _release()

        return _aiter()

    def method(self, name: str):
        def call(*args, **kwargs):
            return self.remote(*args, _method=name, **kwargs)

        return call


# ---------------------------------------------------------------- serve API


def _controller():
    import ray_tpu

    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        pass
    import ray_tpu.api as api

    try:
        return api.ActorClass(ServeController, name=CONTROLLER_NAME,
                              lifetime="detached").remote()
    except ray_tpu.RayError:
        # lost the creation race to another caller; the winner may not
        # have registered the name yet — wait it out briefly
        import time as _time

        deadline = _time.monotonic() + 30
        while True:
            try:
                return ray_tpu.get_actor(CONTROLLER_NAME)
            except ValueError:
                if _time.monotonic() >= deadline:
                    raise
                _time.sleep(0.2)


def run(app: Application, name: Optional[str] = None) -> DeploymentHandle:
    import cloudpickle

    import ray_tpu
    from ray_tpu._private.config import config
    from ray_tpu._private.errors import DeploymentFailedError

    d = app.deployment
    dep_name = name or d.name
    num_replicas = d.num_replicas
    autoscaling = d.autoscaling_config
    if num_replicas == "auto":
        # declarative elasticity: replica count follows load between
        # the config bounds (the controller's reconcile loop scales on
        # ongoing requests + replica queue depth + shed pressure)
        autoscaling = dict(autoscaling or {})
        autoscaling.setdefault("min_replicas",
                               int(config.serve_autoscale_min_replicas))
        autoscaling.setdefault("max_replicas",
                               int(config.serve_autoscale_max_replicas))
        autoscaling.setdefault(
            "target_ongoing_requests",
            int(config.serve_autoscale_target_ongoing))
        num_replicas = int(autoscaling["min_replicas"])
    ctrl = _controller()
    pol = d.policy()
    blob = cloudpickle.dumps(d.func_or_class)
    health_timeout = float(config.serve_replica_health_timeout_s)
    try:
        if d.llm and int(d.prefill_replicas or 0) > 0:
            # disaggregated prefill: a sibling pool of identical llm
            # replicas handles the prefill phase only; handles learn the
            # pool name via the decode deployment's policy and ship the
            # finished KV pages over the bulk plane
            pool_name = f"{dep_name}-prefill"
            pol["prefill_pool"] = pool_name
            pool_pol = {k: v for k, v in pol.items()
                        if k != "prefill_pool"}
            ray_tpu.get(ctrl.deploy.remote(
                pool_name, blob, int(d.prefill_replicas),
                d.max_ongoing_requests, d.init_args, d.init_kwargs,
                d.ray_actor_options, None, health_timeout, d.llm,
                pool_pol), timeout=health_timeout + 120.0)
        ray_tpu.get(ctrl.deploy.remote(
            dep_name, blob, num_replicas,
            d.max_ongoing_requests, d.init_args, d.init_kwargs,
            d.ray_actor_options, autoscaling,
            health_timeout, d.llm,
            pol),
            timeout=health_timeout + 120.0)
    except ray_tpu.RayTaskError as e:
        if isinstance(e.cause, DeploymentFailedError):
            raise e.cause from None  # typed: callers can catch it
        raise
    return get_handle(dep_name)


def get_handle(name: str, timeout: float = 30.0) -> DeploymentHandle:
    import ray_tpu

    # ride through a controller crash: the name may briefly resolve to
    # the dead actor (or to nothing) until a fresh controller registers
    # and recovers its checkpoint — retry RayErrors within the window
    deadline = time.monotonic() + timeout
    while True:
        try:
            ctrl = _controller()
            info = ray_tpu.get(ctrl.get_replicas.remote(name), timeout=60)
            break
        except (ray_tpu.RayError, ValueError):
            if time.monotonic() >= deadline:
                raise
            time.sleep(0.2)
    if info is None:
        raise ValueError(f"no deployment named {name!r}")
    return DeploymentHandle(name, info["replica_ids"], info["version"],
                            info.get("replica_nodes"),
                            max_ongoing=info.get("max_ongoing", 8),
                            policy=info.get("policy"))


def delete(name: str):
    import ray_tpu

    ray_tpu.get(_controller().delete.remote(name), timeout=120)


def shutdown():
    import ray_tpu

    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except ValueError:
        return
    for name in list(ray_tpu.get(ctrl.list_deployments.remote(), timeout=60)):
        ray_tpu.get(ctrl.delete.remote(name), timeout=120)
    ray_tpu.kill(ctrl)
