"""LLM serving tier: continuous batching over a paged KV cache.

The request/response Serve data plane re-dispatches one forward per
``@serve.batch`` flush — decode-heavy LLM traffic pays a dispatch per
token step and the accelerator idles between batches.  This module is
the resident-program alternative (the Gemma-on-TPU serving shape): a
replica hosts ONE :class:`LLMEngine` whose decode loop is pinned to an
exec thread through the compiled-DAG dispatch branch
(``__rt_dag_llm_loop__`` in worker.py) and never re-dispatches.  New
sequences are admitted into the running batch at token boundaries
(continuous batching), every sequence owns pages in a paged KV cache
(block-table indexed, recycled on EOS/cancel/disconnect), long prompts
prefill in chunks so they cannot stall in-flight decodes, and generated
tokens stream out per sequence through the existing
``stream_async`` -> SSE path.

Request contract (token-level; tokenization is the client's concern):
  {"tokens": [int, ...],        # prompt token ids
   "max_new_tokens": int,       # decode budget (>= 1)
   "eos": int | None,           # optional stop token
   "request_id": str | None,    # idempotency key: a retried request
                                # re-attaches to the live sequence
   "emit_from": int | None,     # first generation index to emit —
                                # the resume cursor for proxy retries
   "deadline_ms": float | None} # absolute epoch-ms deadline; combined
                                # (tighter wins) with the ambient task
                                # deadline / X-Request-Deadline-Ms
Each streamed item is {"i": <first generation index>, "tokens":
[<id>, ...], "done": <bool>} — items COALESCE every token generated
since the consumer last drained (the decode loop outruns the per-item
transport under load), and the integer "i" is what makes the stream
RESUMABLE: after a mid-stream replica death the HTTP proxy re-submits
with ``emit_from`` = last delivered index + 1 and the client sees at
most one duplicated token boundary.

Admission is a bounded head-of-line queue: a full queue (or a prompt
that can never fit the page budget) raises :class:`LLMOverloadedError`,
which the proxy maps to the PR-3 503 shed gate.  Sequences whose
consumer vanished (SSE disconnect -> generator cancel) keep their pages
only for ``llm_detach_grace_s`` — the re-attach window for transparent
resume — then are cancelled and recycled.

Copy-on-write prefix sharing (``llm_prefix_sharing``): page-aligned
token-prefix blocks are hashed into a per-engine refcounted prefix
index as prefill completes them; a new sequence whose prompt prefix
matches attaches to the SAME physical pages (refcount + 1, recycled
only at refcount 0) and prefills from the first unshared token.  A
divergence MID-page copies the shared head of that page into a private
page (copy-on-write) before the diverging tokens are written.  Shared
pages are immutable by construction — a sequence only ever writes at
positions >= its own ``pos``, and a page enters the index only once
every sequence write past it has happened.

Disaggregated prefill (``llm_deployment(prefill_replicas=N)``): a
sibling replica pool runs ONLY chunked prefill (``prefill_request``),
exports the finished KV pages via models.llama.gather_kv_slots +
object_transfer.pack_kv_pages, and ships them to decode replicas as a
sealed store object over the PR-4 bulk transfer plane (seal-time CRC32,
alternate-holder retry on a corrupt pull).  The decode replica attaches
the pages by request_id (``submit(kv_pack=...)``) and starts at its
first decode step — long prompts never occupy decode-lane steps, and
the deadline admission gate prices the two phases separately
(prefill-only: chunk cost; attach: one decode step).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu._private import deadlines
from ray_tpu._private.errors import DeadlineExceededError

__all__ = ["LLMEngine", "LLMOverloadedError", "llm_deployment",
           "run_llm_loop"]


class LLMOverloadedError(RuntimeError):
    """Admission shed: queue full or the prompt cannot be paged in.
    The HTTP proxy maps this to 503 (the serve shed-gate contract)."""


# sequence states
_QUEUED = "queued"
_PREFILL = "prefill"
_DECODE = "decode"
_SHIP = "ship"  # prefill-only sequence whose pages were just exported

_forward_cache: Dict[int, Any] = {}

# prefix-index chain seed: block k's key hashes (parent key || block
# tokens), so one digest equality implies the WHOLE prefix matches
_PREFIX_SEED = b"rtpu-prefix-v1"


def _chain_hash(parent: bytes, block) -> bytes:
    import hashlib

    h = hashlib.blake2b(parent, digest_size=16)
    for t in block:
        h.update(int(t).to_bytes(4, "little", signed=True))
    return h.digest()


def _jit_forward(model, params, k, v, tokens, slots, ctx, ctx_pos,
                 ctx_mask, q_pos, last_idx, temperature=0.0, top_k=0,
                 rng=None, block_tables=None, context_lens=None):
    """One forward over the paged cache -> (next tokens at ``last_idx``,
    updated pools).  Jitted ONCE per (model, shapes, sampling knobs) —
    the flax module AND the sampling knobs are hashable static
    arguments, so every engine instance with the same config shares the
    compiled executable (k/v pools donated: in-place cache updates).

    Context comes in one of two forms, selected by whether
    ``block_tables`` is an array or None — a pytree-structure change,
    so each form is its own trace: dense ``ctx``/``ctx_pos``/
    ``ctx_mask`` gather arrays (chunked prefill, dense decode), or
    page-granular ``block_tables`` + ``context_lens`` routing decode
    through the Pallas paged-attention kernel (pass ctx/ctx_pos/
    ctx_mask as None then).

    Sampling is a pair of jit-STATIC knobs (ISSUE 13 satellite / PR-11
    declared headroom (d)): ``temperature == 0`` compiles the exact
    greedy-argmax program the decode-identity tier-1 gate pins down —
    no mask, no categorical, no rng use in the graph; ``temperature >
    0`` compiles logits/temperature + optional static top-k mask +
    jax.random.categorical.  Each distinct (temperature, top_k) pair is
    its own executable; lanes within one engine always share the knobs
    (per-lane temperatures would force them to be traced values)."""
    import jax

    key = (float(temperature), int(top_k))
    fn = _forward_cache.get(key)
    if fn is None:
        import jax.numpy as jnp

        def _fwd(model, params, k, v, tokens, slots, ctx, ctx_pos,
                 ctx_mask, q_pos, last_idx, rng, block_tables,
                 context_lens, temperature=key[0], top_k=key[1]):
            cache = {"k": k, "v": v, "slots": slots, "q_pos": q_pos}
            if block_tables is not None:
                cache["block_tables"] = block_tables
                cache["context_lens"] = context_lens
            else:
                cache.update(ctx=ctx, ctx_pos=ctx_pos,
                             ctx_mask=ctx_mask)
            logits, pools = model.apply(
                {"params": params}, tokens, cache)
            picked = jnp.take_along_axis(
                logits, last_idx[:, None, None], axis=1)[:, 0]
            if temperature <= 0.0:
                return jnp.argmax(picked, axis=-1), pools
            scaled = picked / temperature
            if top_k > 0:
                kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
                scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
            return jax.random.categorical(rng, scaled, axis=-1), pools

        fn = _forward_cache[key] = jax.jit(
            _fwd, static_argnums=0, donate_argnums=(2, 3))
    if rng is None:
        import jax.numpy as jnp

        rng = jnp.zeros((2,), dtype="uint32")  # unused when greedy
    return fn(model, params, k, v, tokens, slots, ctx, ctx_pos, ctx_mask,
              q_pos, last_idx, rng, block_tables, context_lens)


class _Seq:
    __slots__ = ("request_id", "prompt", "prefill_tokens", "generated",
                 "max_new", "eos", "block_table", "pos", "state", "done",
                 "error", "attach_count", "detached_at", "done_at",
                 "submitted_at", "first_token_at", "cancelled",
                 "slot_cache", "cond", "deadline", "kv_import",
                 "prefill_export", "export_payload")

    def __init__(self, request_id: str, prompt: List[int], max_new: int,
                 eos: Optional[int], preknown: Optional[List[int]] = None):
        self.request_id = request_id
        # physical slot per position, vectorized at admission (the
        # decode hot path slices this instead of re-deriving slots in
        # Python per lane per step); cond is per-sequence so a token
        # emit wakes THIS stream's consumer, not every parked thread
        self.slot_cache = None
        self.cond: Optional[threading.Condition] = None
        self.prompt = list(prompt)
        self.generated: List[int] = list(preknown or [])
        # restored sequences re-prefill prompt + already-known tokens in
        # one pass; fresh sequences prefill just the prompt
        self.prefill_tokens = self.prompt + self.generated
        self.max_new = int(max_new)
        self.eos = eos
        self.block_table: List[int] = []
        self.pos = 0                  # tokens whose KV is in the cache
        self.state = _QUEUED
        self.done = False
        self.error: Optional[BaseException] = None
        self.attach_count = 0
        self.detached_at: Optional[float] = None
        self.done_at: Optional[float] = None
        self.submitted_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.cancelled = False
        # absolute wall-clock deadline (epoch seconds; 0 = unbounded):
        # the sweep cancels expired in-flight sequences and recycles
        # their pages instead of decoding for a caller that moved on
        self.deadline = 0.0
        # disaggregated prefill: shipped KV rows waiting to be scattered
        # into this engine's pools (decode side), or the flag/result of
        # a prefill-only pass whose pages are exported instead of
        # decoded (prefill side)
        self.kv_import: Optional[Dict[str, Any]] = None
        self.prefill_export = False
        self.export_payload: Optional[Dict[str, Any]] = None

    @property
    def total_len(self) -> int:
        return len(self.prompt) + self.max_new


class LLMEngine:
    """Continuous-batching decode engine over a paged KV cache.

    One engine per replica.  The pinned loop (``run_loop``) is the ONLY
    caller of ``step()`` in serving; request threads touch the engine
    only through ``submit``/``iter_tokens``/``release`` under the
    engine lock.  (The static-batching bench baseline instead drives
    ``generate_batch`` inline — an engine is stepped by its loop OR
    inline, never both.)

    Paging: the cache is ``num_pages`` pages of ``page_size`` slots per
    layer; page 0 is reserved as the garbage page for inactive batch
    lanes and prefill padding.  A sequence's pages are allocated
    UP FRONT for prompt + max_new at admission (no mid-decode OOM, at
    the cost of reserving its worst case) and recycled the moment it
    finishes, errors, or is cancelled.
    """

    def __init__(self, cfg=None, *, model: Any = "tiny",
                 params: Any = None, seed: int = 0,
                 page_size: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 detach_grace_s: Optional[float] = None,
                 prefill_lanes: Optional[int] = None,
                 stream_flush_tokens: Optional[int] = None,
                 dtype: Any = None,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 prefix_sharing: Optional[bool] = None,
                 attention_impl: Optional[str] = None):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu._private.config import config
        from ray_tpu.models.llama import LlamaConfig, LlamaModel, \
            make_kv_pools

        self._np = np
        if cfg is None:
            if isinstance(model, LlamaConfig):
                cfg = model
            elif isinstance(model, dict):
                cfg = LlamaConfig(**model)
            else:
                cfg = getattr(LlamaConfig, str(model))()
        if dtype is not None:
            import dataclasses

            cfg = dataclasses.replace(cfg, dtype=dtype)
        self.cfg = cfg
        self.page_size = int(page_size or config.llm_page_size)
        self.max_batch = int(max_batch or config.llm_max_batch_size)
        self.prefill_chunk = int(prefill_chunk or config.llm_prefill_chunk)
        self.max_queue = int(max_queue or config.llm_admission_queue)
        self.detach_grace_s = float(
            detach_grace_s if detach_grace_s is not None
            else config.llm_detach_grace_s)
        self.prefill_lanes = max(1, min(
            int(prefill_lanes or config.llm_prefill_lanes),
            self.max_batch))
        self.stream_flush_tokens = max(1, int(
            stream_flush_tokens or config.llm_stream_flush_tokens))
        self.pages_per_seq = -(-cfg.max_seq_len // self.page_size)
        if num_pages is None:
            num_pages = int(config.llm_kv_pages) or (
                1 + self.max_batch * self.pages_per_seq)
        # +1: page 0 is the garbage page, never allocated
        self.num_pages = max(int(num_pages), 2)
        self.ctx_len = self.pages_per_seq * self.page_size

        # decode attention implementation: "paged" routes decode steps
        # through the Pallas paged-attention kernel (block tables +
        # context lengths, cost tracks used context); "dense" keeps the
        # gather-then-dense reference (cost tracks max context).
        impl = str(attention_impl or config.llm_attention_impl).lower()
        if impl == "auto":
            impl = "paged"
        if impl not in ("paged", "dense"):
            raise ValueError(
                f"llm_attention_impl must be auto|paged|dense, got {impl!r}")
        self.attention_impl = impl
        self._model = LlamaModel(
            cfg, page_size=self.page_size if impl == "paged" else 0)
        if params is None:
            dummy = np.zeros((1, 8), np.int32)
            params = self._model.init(
                jax.random.PRNGKey(int(seed)), dummy)["params"]
        self._params = params
        self._pools = make_kv_pools(cfg, self.num_pages * self.page_size)
        # sampling knobs are jit-STATIC: temperature=0 (the default)
        # compiles the exact greedy program the decode-identity gate
        # covers; >0 adds temperature scaling + optional top-k masking
        # + categorical sampling, seeded per engine so a fixed seed
        # replays the same stream
        self.temperature = float(
            temperature if temperature is not None
            else config.llm_temperature)
        self.top_k = int(top_k if top_k is not None else config.llm_top_k)
        self._sample_rng = (jax.random.PRNGKey(int(seed))
                            if self.temperature > 0 else None)
        # the jitted stepper is shared process-wide (_jit_forward keys
        # on the STATIC model + shapes + sampling knobs): every engine
        # with the same config/pool geometry reuses one executable —
        # two compiles total in steady state (decode [B,1] and
        # prefill [1,C])
        self._step_fn = _jit_forward

        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._free_pages: List[int] = list(range(1, self.num_pages))
        # ---- copy-on-write prefix sharing ----
        # page_refs[p]: sequences whose block table includes page p —
        # pages recycle to _free_pages only at refcount 0.  The prefix
        # index maps a chain hash over page-aligned token blocks to ONE
        # immutable page holding that block's KV; _children groups
        # registered pages under their parent-chain hash so a mid-page
        # divergence can find its copy-on-write source.
        self.prefix_sharing = bool(
            prefix_sharing if prefix_sharing is not None
            else config.llm_prefix_sharing)
        self._page_refs = [0] * self.num_pages
        self._prefix_index: Dict[bytes, int] = {}
        self._children: Dict[bytes, set] = {}
        self._page_tokens: Dict[int, tuple] = {}
        self._page_keys: Dict[int, tuple] = {}
        self._prefix_hits = 0
        self._prefix_tokens_shared = 0
        self._cow_splits = 0
        self._pages_alloc_total = 0
        self._kv_pages_shipped_out = 0
        self._kv_pages_shipped_in = 0
        self._queued: deque = deque()
        self._active: List[_Seq] = []
        self._by_rid: Dict[str, _Seq] = {}
        self._stopped = threading.Event()
        self._loop_running = False
        self._arange = np.arange(self.ctx_len, dtype=np.int32)
        self._steps = 0
        self._cancelled_total = 0
        self._last_batch = 0
        self._last_step_tokens = 0
        self._metrics = None
        self._warm = False
        self._paged_warm = False
        # decode-step accumulators (bench A/B reads mean step cost as
        # a delta between two stats() snapshots)
        self._decode_steps = 0
        self._decode_secs = 0.0
        # EWMA of one engine step's wall time — the deadline-admission
        # estimate of "prefill + one decode step" cost (0 until the
        # first measured step; cold engines only refuse already-expired
        # budgets)
        self._step_ewma = 0.0
        self._deadline_expired_total = 0

    # ------------------------------------------------------------ admission

    def submit(self, request: Dict[str, Any],
               kv_pack: Optional[tuple] = None) -> _Seq:
        """Admit (or re-attach to) one sequence.  Raises
        LLMOverloadedError when the admission queue is full, ValueError
        on requests that can never fit.

        ``kv_pack`` is an unpacked (meta, rows) KV shipment from a
        prefill replica (object_transfer.unpack_kv_pages): the sequence
        skips prefill entirely — the step loop scatters the rows into
        this engine's pools and the sequence enters decode at the
        shipped position.  A pack that does not match the request's
        prompt is discarded (local prefill is always correct, just
        slower).  A request carrying ``_phase == "prefill"`` is
        prefill-ONLY: its pages are exported and recycled at the end of
        prefill instead of decoding (see prefill_request)."""
        import uuid

        if not isinstance(request, dict) or not request.get("tokens"):
            raise ValueError("llm request must be a dict with 'tokens'")
        prompt = [int(t) for t in request["tokens"]]
        max_new = int(request.get("max_new_tokens", 16))
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        eos = request.get("eos")
        eos = int(eos) if eos is not None else None
        rid = str(request.get("request_id") or uuid.uuid4().hex[:16])
        prefill_only = request.get("_phase") == "prefill"
        if kv_pack is not None:
            meta = kv_pack[0]
            # the shipment must describe exactly this prompt: the rows
            # are attached positionally, so any mismatch would decode
            # against another request's KV
            if (list(meta.get("tokens") or []) != prompt
                    or int(meta.get("n", -1)) != len(prompt)):
                kv_pack = None
        # end-to-end deadline: the ambient context (stamped into the
        # replica task by the handle / the X-Request-Deadline-Ms
        # ingress header) combined with an explicit request-dict
        # "deadline_ms" — tighter wins
        dl = deadlines.effective_deadline() or 0.0
        req_dl = deadlines.from_header(request.get("deadline_ms"))
        if req_dl:
            dl = min(dl, req_dl) if dl else req_dl
        if dl:
            rem = dl - time.time()
            # admission refusal: a sequence whose remaining budget
            # cannot cover its prefill + ONE decode step would only
            # burn pages and batch lanes producing tokens its caller
            # will never read.  Cost model: measured step EWMA x
            # (prefill chunks + 1); a cold engine (no measured step
            # yet) only refuses already-expired budgets.  The two
            # disaggregated phases price separately: a prefill-only
            # pass needs its chunks but no decode step, and a sequence
            # arriving WITH shipped KV needs one decode step but no
            # prefill chunks.
            need = 0.0
            if self._step_ewma > 0.0:
                chunks = -(-len(prompt) // self.prefill_chunk)
                if kv_pack is not None:
                    need = self._step_ewma
                elif prefill_only:
                    need = self._step_ewma * chunks
                else:
                    need = self._step_ewma * (chunks + 1)
            if rem <= need:
                self._deadline_expired_total += 1
                deadlines.count_exceeded("admission")
                raise DeadlineExceededError(
                    f"remaining budget {max(rem, 0.0) * 1000:.0f}ms cannot "
                    f"cover prefill + one decode step "
                    f"(~{need * 1000:.0f}ms)", where="admission")
        with self._lock:
            seq = self._by_rid.get(rid)
            if seq is not None and seq.cancelled:
                # a grace-swept/cancelled sequence is TRUNCATED — a
                # retry must re-generate, not replay a partial result
                # presented as done
                del self._by_rid[rid]
                seq = None
            if seq is not None:
                # idempotent re-attach: a proxy retry after replica or
                # connection trouble resumes the SAME sequence (replay
                # of already-generated tokens + live continuation)
                seq.attach_count += 1
                seq.detached_at = None
                return seq
            if len(prompt) + max_new > min(self.cfg.max_seq_len,
                                           self.ctx_len):
                raise ValueError(
                    f"prompt+max_new_tokens = {len(prompt) + max_new} "
                    f"exceeds max_seq_len {self.cfg.max_seq_len}")
            pages_needed = -(-(len(prompt) + max_new) // self.page_size)
            if pages_needed > self.num_pages - 1:
                raise LLMOverloadedError(
                    f"request needs {pages_needed} KV pages; replica "
                    f"has {self.num_pages - 1}")
            if len(self._queued) >= self.max_queue:
                raise LLMOverloadedError(
                    f"admission queue full ({self.max_queue})")
            seq = _Seq(rid, prompt, max_new, eos)
            seq.deadline = dl
            seq.cond = threading.Condition(self._lock)
            seq.attach_count = 1
            seq.prefill_export = prefill_only
            if kv_pack is not None:
                seq.kv_import = {"meta": kv_pack[0], "rows": kv_pack[1]}
            self._by_rid[rid] = seq
            self._queued.append(seq)
            self._cond.notify_all()  # wake the parked decode loop
        return seq

    def iter_tokens(self, seq: _Seq, emit_from: int = 0):
        """Blocking generator of token items for one consumer.

        Items are COALESCED: each carries every token generated since
        the consumer last drained (``{"i": <first index>, "tokens":
        [...], "done": bool}``) — under load the decode loop outruns
        the per-item streaming path (one stream push + one ref
        resolution + one SSE chunk each), so batching tokens into items
        is what lets 64+ concurrent streams ride one engine without the
        transport dominating.  TTFT is unaffected: the first item
        leaves the moment the first token exists.  Parked waits rely on
        per-sequence notifies and re-check every 2s — that bound (not
        the next token) is the worst-case latency for a pending
        cancellation async-exc on an idle consumer; an actively-fed
        consumer sees it within one flush interval."""
        i = max(0, int(emit_from))
        first = True
        while True:
            with self._cond:
                while True:
                    if seq.error is not None:
                        raise seq.error
                    n = len(seq.generated)
                    if seq.done and i >= n:
                        return
                    # the FIRST item flushes on one token (TTFT);
                    # after that, wait for stream_flush_tokens (or the
                    # end) so a fast decode loop doesn't pay the
                    # push+resolve+chunk transport per single token
                    flush = 1 if first else self.stream_flush_tokens
                    if n - i >= flush or (seq.done and n > i):
                        item = {"i": i, "tokens": list(seq.generated[i:n]),
                                "done": bool(seq.done)}
                        break
                    # per-seq notifies (flush boundaries, finish,
                    # cancel) do the real waking; the 2s timeout only
                    # bounds how long a pending cancellation async-exc
                    # can sit on a parked thread.  A short poll here
                    # melts down at scale: 256 parked streams polling
                    # at 10Hz is ~2.5k futex syscalls/s
                    (seq.cond or self._cond).wait(2.0)
            yield item
            first = False
            if item["done"]:
                return
            i = n

    def release(self, seq: _Seq) -> None:
        """One consumer detached (finished, disconnected, cancelled).
        The last detach of an unfinished sequence starts the grace
        clock; past it the loop cancels the sequence and recycles its
        pages instead of decoding to max_seq_len for nobody."""
        with self._lock:
            seq.attach_count = max(0, seq.attach_count - 1)
            if seq.attach_count == 0 and not seq.done:
                seq.detached_at = time.monotonic()

    def cancel(self, request_id: str) -> bool:
        with self._lock:
            seq = self._by_rid.get(request_id)
            if seq is None or seq.done:
                return False
            self._finish_seq(seq, cancelled=True)
            self._cond.notify_all()
            return True

    # ------------------------------------------------------------- stepping

    def _forward(self, tokens, slot_arr, ctx, ctx_pos, ctx_mask, q_pos,
                 last_idx, block_tables=None, context_lens=None):
        """One jitted forward with this engine's static sampling knobs;
        the per-call rng split only happens on the sampling path, so
        greedy engines run the exact pre-sampling program."""
        rng = None
        if self._sample_rng is not None:
            import jax

            self._sample_rng, rng = jax.random.split(self._sample_rng)
        return self._step_fn(
            self._model, self._params, self._pools["k"], self._pools["v"],
            tokens, slot_arr, ctx, ctx_pos, ctx_mask, q_pos, last_idx,
            temperature=self.temperature, top_k=self.top_k, rng=rng,
            block_tables=block_tables, context_lens=context_lens)

    def _paged_width_buckets(self) -> List[int]:
        """Block-table width buckets the paged decode path can emit:
        powers of four from 4 up to (and capped at) pages_per_seq.
        Coarser-than-pow-2 buckets trade at most a 4x width overshoot
        at small contexts (cheap: unused pages are predicated off and
        their copies deduped) for half the per-bucket jit compiles the
        warm-up burst has to pay."""
        widths, w = [], 4
        while True:
            widths.append(min(w, self.pages_per_seq))
            if w >= self.pages_per_seq:
                return widths
            w *= 4

    def _warm_paged_buckets(self) -> None:
        """Compile every paged block-table width bucket up front, at
        the FIRST decode step.  A bucket-crossing jit compile costs
        seconds (interpret mode especially), and a compile stalling a
        DEADLINED in-flight request past deadline_force_cancel_grace_s
        gets the whole worker force-killed — so pay all compiles in one
        burst while nothing is at stake (the deployment warm-up request
        lands here).  The dummy forwards run garbage lanes only (slot
        0, context length 0); the jit cache is process-wide, so engines
        sharing a config/geometry pay once."""
        np = self._np
        b = self.max_batch
        zeros1 = np.zeros((b, 1), np.int32)
        for width in self._paged_width_buckets():
            _tok, self._pools = self._forward(
                zeros1, zeros1, None, None, None, zeros1,
                np.zeros((b,), np.int32),
                block_tables=np.zeros((b, width), np.int32),
                context_lens=np.zeros((b,), np.int32))

    def _alloc_pages(self, n: int) -> List[int]:
        pages = self._free_pages[:n]
        del self._free_pages[:n]
        for p in pages:
            self._page_refs[p] = 1
        self._pages_alloc_total += len(pages)
        return pages

    def _release_pages(self, pages: List[int]) -> None:
        """Lock held.  Drop one reference per page; pages reaching
        refcount 0 return to the free list and leave the prefix index
        (a later lookup must never attach to a recycled page)."""
        freed = []
        for p in pages:
            self._page_refs[p] -= 1
            if self._page_refs[p] <= 0:
                self._page_refs[p] = 0
                freed.append(p)
                keys = self._page_keys.pop(p, None)
                if keys is not None:
                    parent, own = keys
                    if self._prefix_index.get(own) == p:
                        del self._prefix_index[own]
                    kids = self._children.get(parent)
                    if kids is not None:
                        kids.discard(p)
                        if not kids:
                            del self._children[parent]
                self._page_tokens.pop(p, None)
        self._free_pages.extend(freed)

    def _finish_seq(self, seq: _Seq, cancelled: bool = False) -> None:
        """Lock held.  Mark done and release page references
        immediately — physical pages recycle only at refcount 0 (other
        sequences may still be decoding against a shared prefix)."""
        seq.done = True
        seq.cancelled = cancelled
        if cancelled:
            self._cancelled_total += 1
        seq.done_at = time.monotonic()
        if seq.cond is not None:
            seq.cond.notify_all()
        self._release_pages(seq.block_table)
        seq.block_table = []
        seq.kv_import = None
        if seq in self._active:
            self._active.remove(seq)
        try:
            self._queued.remove(seq)
        except ValueError:
            pass

    def _slot(self, seq: _Seq, pos: int) -> int:
        return (seq.block_table[pos // self.page_size] * self.page_size
                + pos % self.page_size)

    def _sweep(self, now: float) -> None:
        """Lock held: expire sequences past their deadline (pages
        recycle NOW; the consumer sees the typed error), cancel
        sequences abandoned past the grace window, and forget finished
        ones past the replay TTL."""
        from ray_tpu._private.config import config

        wall = time.time()
        for seq in list(self._active) + list(self._queued):
            if seq.deadline and wall >= seq.deadline and not seq.done:
                self._deadline_expired_total += 1
                # a sequence still parked at admission expired WAITING,
                # not decoding — the queued/running split is the signal
                # operators act on (shed earlier vs loosen budgets)
                where = "queued" if seq.state == _QUEUED else "running"
                deadlines.count_exceeded(where)
                seq.error = DeadlineExceededError(
                    f"sequence {seq.request_id} exceeded its deadline "
                    f"while {where} ({len(seq.generated)}/{seq.max_new} "
                    f"tokens generated)", where=where)
                self._finish_seq(seq, cancelled=True)
                continue
            if (seq.attach_count == 0 and seq.detached_at is not None
                    and now - seq.detached_at > self.detach_grace_s):
                self._finish_seq(seq, cancelled=True)
        ttl = float(config.llm_done_seq_ttl_s)
        for rid, seq in list(self._by_rid.items()):
            if seq.done and seq.done_at is not None \
                    and now - seq.done_at > ttl:
                del self._by_rid[rid]

    def _match_prefix(self, seq: _Seq):
        """Lock held.  Longest shared-prefix match for ``seq`` against
        the refcounted index: returns (shared_pages, cow) where
        ``shared_pages`` are live physical pages whose KV covers the
        first ``len(shared_pages) * page_size`` prefill tokens
        verbatim, and ``cow`` is an optional (source_page, n_tokens)
        mid-page extension to copy-on-write into a private page.  At
        least ONE token is always left for prefill — the final prompt
        position's logits are what produce the first generated token."""
        toks = seq.prefill_tokens
        ps = self.page_size
        limit = len(toks) - 1
        shared: List[int] = []
        if limit < 1 or not self._children:
            return shared, None
        h = _PREFIX_SEED
        p = 0
        while (p + 1) * ps <= limit:
            block = tuple(toks[p * ps:(p + 1) * ps])
            child = _chain_hash(h, block)
            page = self._prefix_index.get(child)
            # digest equality implies the whole prefix matches; the
            # token compare turns a (cosmically unlikely) hash
            # collision into a miss instead of a wrong-KV decode
            if page is None or self._page_refs[page] <= 0 \
                    or self._page_tokens.get(page) != block:
                break
            shared.append(page)
            h = child
            p += 1
        # mid-page extension: a registered page under the same parent
        # chain whose leading tokens match is a copy-on-write source —
        # its shared head is copied into the diverging sequence's
        # private page so prefill starts at the first unshared token
        cow = None
        rem = min(limit - p * ps, ps)
        if rem > 0:
            best, best_page = 0, None
            want = toks[p * ps:p * ps + rem]
            for cand in self._children.get(h, ()):
                ct = self._page_tokens.get(cand)
                if not ct or self._page_refs[cand] <= 0:
                    continue
                m = 0
                for a, b in zip(ct, want):
                    if a != b:
                        break
                    m += 1
                if m > best:
                    best, best_page = m, cand
            if best > 0:
                cow = (best_page, best)
        return shared, cow

    def _register_prefix_pages(self, seq: _Seq) -> None:
        """Lock held.  Enter ``seq``'s fully-written prefill pages into
        the prefix index.  A page is registered only once the sequence's
        ``pos`` passed its end (all slots written, and no future write
        can touch it — writes only happen at >= pos) and only within
        the prefill region (decode-extended pages are private).
        Idempotent: already-registered pages (including ones attached
        FROM the index) are skipped."""
        if not self.prefix_sharing:
            return
        ps = self.page_size
        toks = seq.prefill_tokens
        max_page = min(seq.pos, len(toks)) // ps
        h = _PREFIX_SEED
        for p in range(max_page):
            block = tuple(toks[p * ps:(p + 1) * ps])
            child = _chain_hash(h, block)
            page = seq.block_table[p]
            if page not in self._page_keys and self._page_refs[page] > 0:
                # first registration wins; an identical-content page
                # from another sequence stays unregistered (it will be
                # recycled at its own refcount 0)
                self._prefix_index.setdefault(child, page)
                self._children.setdefault(h, set()).add(page)
                self._page_tokens[page] = block
                self._page_keys[page] = (h, child)
            h = child

    def _cow_copy(self, src_page: int, dst_page: int, n_tok: int) -> None:
        """Lock held, loop-synchronized (only ever called from within a
        step, never concurrent with a forward): copy the first
        ``n_tok`` KV rows of ``src_page`` into ``dst_page``."""
        from ray_tpu.models.llama import copy_kv_slots

        np = self._np
        ps = self.page_size
        src = np.arange(n_tok, dtype=np.int32) + src_page * ps
        dst = np.arange(n_tok, dtype=np.int32) + dst_page * ps
        self._pools = copy_kv_slots(self._pools, src, dst)

    def _admit_locked(self) -> None:
        while self._queued and len(self._active) < self.max_batch:
            seq = self._queued[0]
            pages = -(-seq.total_len // self.page_size)
            shared: List[int] = []
            cow = None
            if self.prefix_sharing and seq.kv_import is None \
                    and not seq.block_table:
                shared, cow = self._match_prefix(seq)
            if pages - len(shared) > len(self._free_pages):
                break  # head-of-line waits for pages to recycle
            self._queued.popleft()
            for p in shared:
                self._page_refs[p] += 1
            seq.block_table = shared + self._alloc_pages(
                pages - len(shared))
            np = self._np
            bt = np.asarray(seq.block_table, np.int64)
            seq.slot_cache = (np.repeat(bt * self.page_size,
                                        self.page_size)
                              + np.tile(np.arange(self.page_size),
                                        len(bt))).astype(np.int32)
            shared_tok = len(shared) * self.page_size
            if cow is not None:
                src_page, n_tok = cow
                self._cow_copy(src_page, seq.block_table[len(shared)],
                               n_tok)
                self._cow_splits += 1
                shared_tok += n_tok
            if shared_tok:
                # prefill starts at the first unshared token: the
                # attached pages already hold this prefix's KV
                seq.pos = shared_tok
                self._prefix_hits += 1
                self._prefix_tokens_shared += shared_tok
                m = self.metrics()
                if m is not None:
                    m["prefix_hits"].inc(
                        tags={"kind": "cow" if cow else "page"})
            seq.state = _PREFILL
            self._active.append(seq)

    def _emit_token(self, seq: _Seq, token: int) -> None:
        """Lock held: append one generated token, finish on EOS/budget,
        and wake THIS sequence's consumer at flush boundaries only —
        an engine-wide notify_all per step would thundering-herd every
        parked stream thread per token."""
        seq.generated.append(int(token))
        n = len(seq.generated)
        if seq.first_token_at is None:
            seq.first_token_at = time.monotonic()
            m = self.metrics()
            if m is not None:
                m["ttft"].observe(seq.first_token_at - seq.submitted_at)
        if (seq.eos is not None and int(token) == seq.eos) \
                or n >= seq.max_new:
            self._finish_seq(seq)
        elif seq.cond is not None \
                and (n - 1) % self.stream_flush_tokens == 0:
            # aligned with the consumer cursor AFTER the n=1 TTFT item
            # (i=1): wakes land exactly when a full flush quota exists
            # past it (n = 1, F+1, 2F+1, ...), not one window late
            seq.cond.notify_all()

    # ------------------------------------------- disaggregated prefill
    # Export and import both touch the KV pools, so they only ever run
    # INSIDE a step, under the engine lock, never concurrent with a
    # forward (whose donated pool buffers would be invalidated under a
    # concurrent reader/writer).

    def _attach_imports_locked(self) -> bool:
        """Scatter shipped KV rows for freshly-admitted sequences into
        this engine's pools; the sequence enters decode at the shipped
        position with the prefill replica's first generated token
        already emitted.  Returns True when any import happened."""
        imports = [s for s in self._active
                   if s.kv_import is not None and s.state == _PREFILL]
        for seq in imports:
            pack, seq.kv_import = seq.kv_import, None
            n = int(pack["meta"]["n"])
            first_tok = int(pack["meta"]["first_token"])
            from ray_tpu.models.llama import scatter_kv_slots

            self._pools = scatter_kv_slots(self._pools,
                                           seq.slot_cache[:n],
                                           pack["rows"])
            seq.pos = n
            n_pages = -(-n // self.page_size)
            self._kv_pages_shipped_in += n_pages
            m = self.metrics()
            if m is not None:
                m["shipped"].inc(n_pages, tags={"direction": "in"})
            # imported pages carry a complete prompt prefix: register
            # them so later same-prefix admissions share instead of
            # re-importing or re-prefilling
            self._register_prefix_pages(seq)
            seq.state = _DECODE
            self._emit_token(seq, first_tok)
        return bool(imports)

    def _export_seq_locked(self, seq: _Seq, first_token: int) -> None:
        """Prefill-only sequence finished its last chunk: gather its KV
        rows to host memory, stash them as the export payload, and
        finish the sequence (pages recycle NOW — the payload is a host
        copy).  ``prefill_request`` wakes on the finish notify."""
        from ray_tpu.models.llama import gather_kv_slots

        if seq.first_token_at is None:
            seq.first_token_at = time.monotonic()
            m = self.metrics()
            if m is not None:
                m["ttft"].observe(seq.first_token_at - seq.submitted_at)
        seq.generated.append(int(first_token))
        n = seq.pos
        n_pages = -(-n // self.page_size)
        seq.export_payload = {
            "meta": {"request_id": seq.request_id,
                     "tokens": list(seq.prompt),
                     "first_token": int(first_token),
                     "n": n, "pages": n_pages,
                     "page_size": self.page_size},
            "rows": gather_kv_slots(self._pools, seq.slot_cache[:n]),
        }
        self._kv_pages_shipped_out += n_pages
        m = self.metrics()
        if m is not None:
            m["shipped"].inc(n_pages, tags={"direction": "out"})
        seq.state = _SHIP
        self._finish_seq(seq)

    def prefill_request(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Run ONLY the prefill phase for ``request`` and return the
        export payload ({"meta", "rows"}) for shipping to a decode
        replica.  Drives the engine inline when no pinned loop is
        running (bench/test harnesses); under a loop it parks on the
        sequence condition like any consumer.  Idempotent by
        request_id within the done-seq TTL: a retried prefill replays
        the stashed payload instead of recomputing."""
        req = dict(request)
        req["_phase"] = "prefill"
        seq = self.submit(req)
        try:
            while True:
                with self._lock:
                    if seq.export_payload is not None:
                        return seq.export_payload
                    if seq.error is not None:
                        raise seq.error
                    if seq.done:
                        # swept (deadline/grace) before export finished
                        raise LLMOverloadedError(
                            f"prefill for {seq.request_id} was cancelled "
                            f"before its pages could be exported")
                    inline = not self._loop_running
                    if not inline:
                        (seq.cond or self._cond).wait(0.1)
                if inline:
                    if not self.step():
                        time.sleep(0.001)
        finally:
            self.release(seq)

    def step(self) -> bool:
        """One engine iteration: admit, one prefill chunk, one decode
        pass over every decoding sequence.  Returns False when there was
        nothing to do (the loop then parks on the condition)."""
        np = self._np
        now = time.monotonic()
        t_step = time.perf_counter()
        with self._lock:
            self._sweep(now)
            self._admit_locked()
            imported = self._attach_imports_locked()
            prefills = [s for s in self._active
                        if s.state == _PREFILL][:self.prefill_lanes]
            decode = [s for s in self._active if s.state == _DECODE]
            if not prefills and not decode:
                self._last_batch = 0
                self._last_step_tokens = 0
                self._set_gauges()  # idle must publish zeros, not
                # freeze the last busy step's values into the ring
                return imported  # an import that finished immediately
                # (max_new=1 / eos) still counts as work done
            prefill_args = []
            for seq in prefills:
                lo = seq.pos
                hi = min(lo + self.prefill_chunk, len(seq.prefill_tokens))
                prefill_args.append(
                    (seq, lo, hi, seq.prefill_tokens[lo:hi],
                     seq.slot_cache[lo:hi], seq.slot_cache[:hi]))
            decode_args = []
            for seq in decode[:self.max_batch]:
                last = (seq.generated[-1] if seq.generated
                        else seq.prefill_tokens[-1])
                # snapshot the block table under the lock: a concurrent
                # CoW split may rewrite entries after we release it
                decode_args.append(
                    (seq, last, seq.slot_cache[seq.pos],
                     seq.slot_cache[:seq.pos + 1],
                     list(seq.block_table), seq.pos + 1))
        step_tokens = 0
        # ---- chunked prefill, batched across lanes: up to
        # prefill_lanes sequences advance one chunk each per step — a
        # burst of N admissions costs N/lanes steps, while a LONG
        # prompt still shares the loop with in-flight decodes instead
        # of monopolizing it
        if prefill_args:
            lanes = self.prefill_lanes
            c = self.prefill_chunk
            tokens = np.zeros((lanes, c), np.int32)
            slot_arr = np.zeros((lanes, c), np.int32)
            ctx = np.zeros((lanes, self.ctx_len), np.int32)
            ctx_pos = np.zeros((lanes, self.ctx_len), np.int32)
            ctx_mask = np.zeros((lanes, self.ctx_len), bool)
            q_pos = np.zeros((lanes, c), np.int32)
            last_idx = np.zeros((lanes,), np.int32)
            for lane, (seq, lo, hi, toks, slots, ctx_slots) \
                    in enumerate(prefill_args):
                tokens[lane, :hi - lo] = toks
                slot_arr[lane, :hi - lo] = slots
                ctx[lane, :hi] = ctx_slots
                ctx_pos[lane, :hi] = self._arange[:hi]
                ctx_mask[lane, :hi] = True
                q_pos[lane, :hi - lo] = self._arange[lo:hi]
                last_idx[lane] = hi - lo - 1
            next_tok, self._pools = self._forward(
                tokens, slot_arr, ctx, ctx_pos, ctx_mask, q_pos, last_idx)
            next_tok = np.asarray(next_tok)
            chunk_tokens = sum(hi - lo for _s, lo, hi, *_r in prefill_args)
            step_tokens += chunk_tokens
            with self._lock:
                for lane, (seq, lo, hi, *_rest) in enumerate(prefill_args):
                    if seq.done:
                        continue  # cancelled mid-chunk: pages already back
                    seq.pos = hi
                    # pages this chunk completed are immutable now —
                    # enter them into the prefix index so later
                    # admissions with the same prompt prefix share them
                    self._register_prefix_pages(seq)
                    if hi == len(seq.prefill_tokens):
                        if seq.prefill_export:
                            self._export_seq_locked(
                                seq, int(next_tok[lane]))
                        else:
                            seq.state = _DECODE
                            self._emit_token(seq, int(next_tok[lane]))
            m = self.metrics()
            if m is not None:
                m["tokens"].inc(chunk_tokens, tags={"phase": "prefill"})
        # ---- token-level decode batch
        if decode_args:
            b = self.max_batch
            tokens = np.zeros((b, 1), np.int32)
            slot_arr = np.zeros((b, 1), np.int32)
            q_pos = np.zeros((b, 1), np.int32)
            last_idx = np.zeros((b,), np.int32)
            if self.attention_impl == "paged" and not self._paged_warm:
                self._paged_warm = True
                self._warm_paged_buckets()
            t_dec = time.perf_counter()
            if self.attention_impl == "paged":
                # page-granular context: block tables + context lengths
                # instead of [B, ctx_len] gather/mask arrays.  The table
                # width snaps to the smallest _paged_width_buckets()
                # entry covering the max used pages across lanes:
                # decode cost tracks USED context, and the jit retrace
                # per bucket is O(log pages_per_seq) traces total.
                max_used = max(-(-n // self.page_size)
                               for *_a, n in decode_args)
                width = next(w for w in self._paged_width_buckets()
                             if w >= max_used)
                block_tables = np.zeros((b, width), np.int32)
                context_lens = np.zeros((b,), np.int32)
                for lane, (seq, last, slot, _ctx, table, n) \
                        in enumerate(decode_args):
                    tokens[lane, 0] = last
                    slot_arr[lane, 0] = slot
                    used = -(-n // self.page_size)
                    block_tables[lane, :used] = table[:used]
                    context_lens[lane] = n
                    q_pos[lane, 0] = seq.pos
                next_tok, self._pools = self._forward(
                    tokens, slot_arr, None, None, None, q_pos, last_idx,
                    block_tables=block_tables, context_lens=context_lens)
            else:
                ctx = np.zeros((b, self.ctx_len), np.int32)
                ctx_pos = np.zeros((b, self.ctx_len), np.int32)
                ctx_mask = np.zeros((b, self.ctx_len), bool)
                for lane, (seq, last, slot, ctx_slots, _table, n) \
                        in enumerate(decode_args):
                    tokens[lane, 0] = last
                    slot_arr[lane, 0] = slot
                    ctx[lane, :n] = ctx_slots
                    ctx_pos[lane, :n] = self._arange[:n]
                    ctx_mask[lane, :n] = True
                    q_pos[lane, 0] = seq.pos
                next_tok, self._pools = self._forward(
                    tokens, slot_arr, ctx, ctx_pos, ctx_mask, q_pos,
                    last_idx)
            next_tok = np.asarray(next_tok)  # device sync: real step cost
            decode_dt = time.perf_counter() - t_dec
            self._decode_steps += 1
            self._decode_secs += decode_dt
            with self._lock:
                for lane, (seq, *_rest) in enumerate(decode_args):
                    if seq.done:
                        continue  # cancelled while we computed
                    seq.pos += 1
                    self._emit_token(seq, int(next_tok[lane]))
            step_tokens += len(decode_args)
            m = self.metrics()
            if m is not None:
                m["tokens"].inc(len(decode_args), tags={"phase": "decode"})
                m["decode_step"].observe(decode_dt)
        self._steps += 1
        self._last_batch = len(decode_args)
        self._last_step_tokens = step_tokens
        # step-cost estimate for deadline admission (prefill + one
        # decode step).  Admission wants "can this POSSIBLY finish", so
        # the estimate must be a floor-ish typical cost: a faster step
        # pulls it down immediately (the first post-compile step erases
        # the multi-second jit-compile sample), and slow outliers (a GC
        # pause, a compile for a new shape) are clamped so one huge
        # step cannot poison the estimate into shedding healthy traffic
        dt = time.perf_counter() - t_step
        if self._step_ewma == 0.0 or dt < self._step_ewma:
            self._step_ewma = dt
        else:
            self._step_ewma = 0.9 * self._step_ewma \
                + 0.1 * min(dt, 5.0 * self._step_ewma)
        self._set_gauges()
        return True

    def run_loop(self) -> Dict[str, Any]:
        """The pinned decode loop: step while there is work, park on the
        engine condition while idle.  Single-flight — a second install
        (controller restart re-ensuring loops) returns immediately."""
        with self._lock:
            if self._loop_running:
                return {"already_running": True}
            self._loop_running = True
        try:
            while not self._stopped.is_set():
                if not self.step():
                    with self._cond:
                        if not self._queued and not self._active:
                            self._cond.wait(0.05)
            return {"steps": self._steps}
        except BaseException as e:
            # a broken engine must fail its consumers, not hang them
            with self._lock:
                for seq in list(self._active) + list(self._queued):
                    if not seq.done:
                        seq.error = e
                        self._finish_seq(seq, cancelled=True)
                        if seq.cond is not None:
                            seq.cond.notify_all()
                self._cond.notify_all()
            raise
        finally:
            with self._lock:
                self._loop_running = False

    def stop(self) -> None:
        self._stopped.set()
        with self._cond:
            self._cond.notify_all()

    # ------------------------------------------------- sync (bench baseline)

    def generate_batch(self, requests: List[Dict[str, Any]]
                       ) -> List[List[int]]:
        """Static batching: admit the whole batch, run it to completion,
        disband — the ``@serve.batch`` baseline the continuous path is
        benched against.  Only for engines with no pinned loop."""
        seqs = []
        try:
            for r in requests:
                seqs.append(self.submit(r))
        except BaseException:
            # a failed admission mid-list must not strand the earlier
            # sequences: nothing will ever drive or consume them, so
            # they would hold pages and decode for nobody
            with self._lock:
                for s in seqs:
                    self._finish_seq(s, cancelled=True)
            raise
        while any(not s.done for s in seqs):
            if not self.step():
                time.sleep(0.001)
        for s in seqs:
            self.release(s)
        return [list(s.generated) for s in seqs]

    # ------------------------------------------------------- observability

    def metrics(self):
        if self._metrics is None:
            try:
                from ray_tpu._private.metrics import (llm_metrics,
                                                      llm_prefix_metrics)

                (tokens, pages, batch, ttft, queue, tps,
                 decode_step) = llm_metrics()
                prefix_hits, shipped = llm_prefix_metrics()
                self._metrics = {"tokens": tokens, "pages": pages,
                                 "batch": batch, "ttft": ttft,
                                 "queue": queue, "tps": tps,
                                 "decode_step": decode_step,
                                 "prefix_hits": prefix_hits,
                                 "shipped": shipped}
            except Exception:
                return None
        return self._metrics

    def _shared_page_count(self) -> int:
        """Lock held: pages referenced by more than one sequence."""
        return sum(1 for r in self._page_refs if r > 1)

    def _set_gauges(self) -> None:
        m = self.metrics()
        if m is None:
            return
        m["pages"].set(self.num_pages - 1 - len(self._free_pages),
                       tags={"state": "used"})
        m["pages"].set(len(self._free_pages), tags={"state": "free"})
        m["pages"].set(self._shared_page_count(), tags={"state": "shared"})
        m["batch"].set(self._last_batch)
        m["queue"].set(len(self._queued))
        m["tps"].set(self._last_step_tokens)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"steps": self._steps,
                    "attention_impl": self.attention_impl,
                    "decode_steps": self._decode_steps,
                    "decode_secs": self._decode_secs,
                    "queued": len(self._queued),
                    "active": len(self._active),
                    "cancelled": self._cancelled_total,
                    "deadline_expired": self._deadline_expired_total,
                    "live_seqs": len(self._by_rid),
                    "free_pages": len(self._free_pages),
                    "used_pages": self.num_pages - 1 - len(self._free_pages),
                    "shared_pages": self._shared_page_count(),
                    "prefix_hits": self._prefix_hits,
                    "prefix_tokens_shared": self._prefix_tokens_shared,
                    "cow_splits": self._cow_splits,
                    "pages_allocated_total": self._pages_alloc_total,
                    "kv_page_bytes": (
                        sum(int(p.nbytes) for p in self._pools["k"])
                        + sum(int(p.nbytes) for p in self._pools["v"]))
                        // self.num_pages,
                    "kv_pages_shipped_out": self._kv_pages_shipped_out,
                    "kv_pages_shipped_in": self._kv_pages_shipped_in,
                    "loop_running": self._loop_running,
                    "last_batch": self._last_batch}

    # ------------------------------------------------------- save / restore

    def save_state(self) -> Dict[str, Any]:
        """Snapshot of in-flight sequences for ``__rt_save__``: prompt +
        tokens generated so far.  Tiny (token ids only) — params and KV
        pages are reconstructed, not saved."""
        with self._lock:
            seqs = []
            for seq in list(self._active) + list(self._queued):
                if seq.done:
                    continue
                seqs.append({"request_id": seq.request_id,
                             "tokens": list(seq.prompt),
                             "generated": list(seq.generated),
                             "max_new_tokens": seq.max_new,
                             "eos": seq.eos})
            return {"seqs": seqs}

    def restore_state(self, state: Dict[str, Any]) -> None:
        """Re-admit saved sequences: each re-prefills prompt + known
        tokens and continues decoding.  Consumers re-attach by
        request_id within the grace window (their ``emit_from`` skips
        what they already saw)."""
        now = time.monotonic()
        with self._lock:
            for s in (state or {}).get("seqs", []):
                rid = s["request_id"]
                if rid in self._by_rid:
                    continue
                seq = _Seq(rid, s["tokens"], s["max_new_tokens"],
                           s.get("eos"), preknown=s.get("generated"))
                seq.cond = threading.Condition(self._lock)
                if len(seq.generated) >= seq.max_new:
                    continue  # finished before the snapshot landed
                seq.detached_at = now  # grace window for re-attach
                self._by_rid[rid] = seq
                self._queued.append(seq)
            self._cond.notify_all()


# ----------------------------------------------------------- replica target


class _LLMCallable:
    """The deployment target hosted by each ``llm_deployment`` replica.

    ``__call__`` is the streaming endpoint: it admits the request and
    yields token items as the PINNED loop (installed by the controller
    through ``__rt_dag_llm_loop__``) produces them.  The generator's
    finally detaches the consumer, so an abandoned stream (SSE
    disconnect -> generator cancel) frees its KV pages after the grace
    window instead of decoding to max_seq_len."""

    def __init__(self, warm: bool = True, **engine_kwargs):
        self._engine = LLMEngine(**engine_kwargs)
        if warm:
            # compile both jitted shapes (prefill chunk + decode) HERE,
            # inside the replica constructor: the deploy health gate
            # (serve_replica_health_timeout_s) covers it, so the first
            # real request never pays ~seconds of XLA compile while
            # reconcile health probes run against their 5s timeout
            self._engine.generate_batch(
                [{"tokens": [1], "max_new_tokens": 2}])

    def __call__(self, request):
        emit_from = 0
        kv_pack = None
        if isinstance(request, dict):
            emit_from = int(request.get("emit_from") or 0)
            if request.get("kv_ref") is not None:
                # disaggregated prefill: resolve the shipped KV pages
                # (the get pulls over the checksummed bulk plane when
                # the prefill replica lives on another node).  ANY
                # failure — pull error, pack corruption — falls back to
                # a local prefill: always correct, just slower.
                request = dict(request)
                ref = request.pop("kv_ref")
                try:
                    import ray_tpu
                    from ray_tpu._private.object_transfer import \
                        unpack_kv_pages

                    kv_pack = unpack_kv_pages(
                        ray_tpu.get(ref, timeout=30.0))
                except Exception:
                    kv_pack = None
        seq = self._engine.submit(request, kv_pack=kv_pack)
        try:
            yield from self._engine.iter_tokens(seq, emit_from)
        finally:
            self._engine.release(seq)

    def prefill(self, request):
        """Prefill-pool endpoint: run the prefill phase only, put the
        packed KV pages into the object store, and return the shipping
        metadata the handle forwards to a decode replica.  The decode
        replica's pull of the ref rides the bulk transfer plane."""
        import ray_tpu
        from ray_tpu._private.object_transfer import pack_kv_pages

        payload = self._engine.prefill_request(request)
        buf = pack_kv_pages(payload["meta"], payload["rows"])
        meta = payload["meta"]
        return {"request_id": meta["request_id"],
                "kv_ref": ray_tpu.put(buf),
                "first_token": meta["first_token"],
                "n": meta["n"], "pages": meta["pages"],
                "nbytes": len(buf)}

    def generate(self, request):
        """Non-streaming convenience: the full generation as one list
        (still continuous-batched with everything else in flight)."""
        toks: List[int] = []
        for item in self(request):
            toks.extend(item["tokens"])
        return {"request_id": None, "tokens": toks}

    def stats(self):
        return self._engine.stats()

    def __rt_save__(self):
        return self._engine.save_state()

    def __rt_restore__(self, state):
        self._engine.restore_state(state)


class _LLMBatchCallable:
    """The ``@serve.batch`` STATIC-batching baseline for bench A/B:
    requests coalesce into a fixed batch, the whole batch generates to
    completion in one call, then disbands — the exact re-dispatching
    shape continuous batching replaces.

    ``__call__`` serves the SAME streaming contract as the continuous
    path (SSE items of <= stream_flush_tokens tokens) so the A/B
    measures the batching policy, not response framing — but a static
    batch can only start emitting once the WHOLE batch finished, which
    is precisely the TTFT/utilization gap continuous batching closes."""

    def __init__(self, max_batch_size: int = 8,
                 batch_wait_timeout_s: float = 0.005, warm: bool = True,
                 **engine_kwargs):
        from ray_tpu.serve.api import batch

        self._engine = LLMEngine(**engine_kwargs)
        if warm:
            self._engine.generate_batch(
                [{"tokens": [1], "max_new_tokens": 2}])
        self._gen = batch(self._run_batch,
                          max_batch_size=max_batch_size,
                          batch_wait_timeout_s=batch_wait_timeout_s)

    def _run_batch(self, requests):
        return self._engine.generate_batch(requests)

    def __call__(self, request):
        toks = self._gen(request)  # blocks until this request's batch ends
        flush = self._engine.stream_flush_tokens
        for i in range(0, len(toks), flush):
            yield {"i": i, "tokens": toks[i:i + flush],
                   "done": i + flush >= len(toks)}


def run_llm_loop(worker, instance, *_args) -> Dict[str, Any]:
    """Worker-side entry for the ``__rt_dag_llm_loop__`` system method
    (see CoreWorker._execute_inner): pins this exec thread to the
    replica engine's decode loop until the replica dies."""
    target = getattr(instance, "_callable", instance)
    engine = getattr(target, "_engine", None)
    if not isinstance(engine, LLMEngine):
        raise TypeError(
            "__rt_dag_llm_loop__ requires an llm_deployment replica "
            f"(got {type(target).__name__})")
    return engine.run_loop()


def llm_deployment(name: str = "llm", *, num_replicas: Any = 1,
                   max_ongoing_requests: int = 64,
                   ray_actor_options: Optional[Dict[str, Any]] = None,
                   autoscaling_config: Optional[Dict[str, Any]] = None,
                   request_timeout_s: Optional[float] = None,
                   hedge_after_s: Any = None, idempotent: bool = False,
                   prefill_replicas: int = 0,
                   **engine_kwargs):
    """Build an LLM serving Application: replicas host an
    :class:`LLMEngine` and the controller installs the pinned decode
    loop on each one.  ``engine_kwargs`` go to :class:`LLMEngine`
    (model=, page_size=, num_pages=, max_batch=, prefill_chunk=,
    max_queue=, seed=, detach_grace_s=, prefix_sharing=); unset knobs
    fall back to the ``llm_*`` config defaults.

    ``prefill_replicas > 0`` disaggregates the two serving phases: a
    sibling ``{name}-prefill`` pool (same engine config) runs chunked
    prefill on dedicated replicas and ships the finished KV pages to
    this deployment's decode replicas over the bulk transfer plane;
    decode lanes never stall behind a long prompt.

    Usage::

        app = serve.llm_deployment("chat", model="tiny", max_batch=16)
        handle = serve.run(app)
        # stream over HTTP: POST /chat with Accept: text/event-stream
    """
    from ray_tpu.serve.api import Deployment

    d = Deployment(_LLMCallable, name, num_replicas=num_replicas,
                   max_ongoing_requests=max_ongoing_requests,
                   ray_actor_options=dict(ray_actor_options or {}),
                   autoscaling_config=dict(autoscaling_config)
                   if autoscaling_config else None,
                   llm=True, request_timeout_s=request_timeout_s,
                   hedge_after_s=hedge_after_s, idempotent=idempotent,
                   prefill_replicas=int(prefill_replicas))
    return d.bind(**engine_kwargs)
