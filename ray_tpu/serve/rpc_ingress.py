"""Binary RPC ingress for serve: the framework's length-prefixed
msgpack protocol instead of HTTP.

Equivalent of the reference's gRPC ingress
(reference: python/ray/serve/_private/proxy.py gRPCProxy +
grpc_util.py): a second, schema-light binary front door next to HTTP
for callers that want structured payloads without JSON overhead.  Here
it speaks the same framing as the cluster control plane (rpc.py), so
any `RpcClient`-style caller works, and `RpcIngressClient` wraps it for
applications.

    serve.run(model.bind(), name="scorer")
    addr = serve.start_rpc_ingress()
    client = serve.RpcIngressClient(*addr)
    client.invoke("scorer", {"x": [1.0, 2.0]})
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional

PROXY_NAME = "_serve_rpc_ingress"


class _RpcIngressHost:
    """RpcHost-style handler set served by the ingress actor."""

    def __init__(self, proxy: "_RpcIngress"):
        self._proxy = proxy

    async def dispatch(self, method: str, payload: Dict[str, Any]) -> Any:
        import asyncio

        if method == "healthz":
            return {"ok": True}
        if method == "routes":
            import asyncio as _aio

            def _list():
                import ray_tpu
                from ray_tpu.serve import api as serve_api

                ctrl = serve_api._controller()
                return sorted(ray_tpu.get(ctrl.list_deployments.remote(),
                                          timeout=30))

            loop = _aio.get_running_loop()
            return {"routes": await loop.run_in_executor(None, _list)}
        if method == "invoke":
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(
                None, self._proxy._call_blocking,
                payload["app"], payload.get("args", ()),
                payload.get("kwargs") or {},
                payload.get("target_method", "__call__"),
                float(payload.get("backend_timeout", 120.0)))
        from ray_tpu._private.rpc import RpcError

        raise RpcError(f"rpc ingress has no method {method!r}")

    def on_peer_disconnect(self, conn) -> None:
        pass


class _RpcIngress:
    """Actor wrapping an RpcServer on its own event loop (same shape as
    the HTTP proxy actor)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import asyncio

        self._handles: Dict[str, Any] = {}
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._addr: Optional[tuple] = None
        self._thread = threading.Thread(
            target=self._serve_forever, args=(host, port),
            name="serve-rpc-ingress", daemon=True)
        self._thread.start()
        self._started.wait(30)

    def _serve_forever(self, host: str, port: int):
        import asyncio

        from ray_tpu._private.rpc import RpcServer

        asyncio.set_event_loop(self._loop)

        async def _start():
            server = RpcServer(_RpcIngressHost(self), host, port)
            bound = await server.start()
            self._addr = (host, bound)
            self._started.set()

        self._loop.run_until_complete(_start())
        self._loop.run_forever()

    def address(self):
        return list(self._addr) if self._addr else None

    def health(self):
        return True

    def _call_blocking(self, name: str, args, kwargs, method: str,
                       timeout: float = 120.0):
        import ray_tpu
        from ray_tpu.serve import api as serve_api

        handle = self._handles.get(name)
        if handle is None:
            try:
                handle = serve_api.get_handle(name)
            except ValueError:
                from ray_tpu._private.rpc import RpcError

                raise RpcError(f"no deployment named {name!r}")
            self._handles[name] = handle
        caller = handle.remote if method == "__call__" \
            else handle.method(method)
        try:
            return ray_tpu.get(caller(*args, **kwargs), timeout=timeout)
        except (ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError,
                ray_tpu.RayWorkerError):
            # replica infrastructure failure only — an application error
            # or timeout must NOT re-execute a side-effecting request;
            # replicas may have been replaced wholesale: refresh once
            self._handles.pop(name, None)
            handle = serve_api.get_handle(name)
            self._handles[name] = handle
            caller = handle.remote if method == "__call__" \
                else handle.method(method)
            return ray_tpu.get(caller(*args, **kwargs), timeout=timeout)


def start_rpc_ingress(host: str = "127.0.0.1", port: int = 0):
    """Start (or fetch) the binary ingress actor; returns (host, port)."""
    import time

    import ray_tpu
    import ray_tpu.api as rapi

    try:
        proxy = ray_tpu.get_actor(PROXY_NAME)
    except ValueError:
        try:
            proxy = rapi.ActorClass(
                _RpcIngress, name=PROXY_NAME, lifetime="detached",
                max_concurrency=16).remote(host, port)
        except Exception as create_exc:
            deadline = time.monotonic() + 30
            while True:
                try:
                    proxy = ray_tpu.get_actor(PROXY_NAME)
                    break
                except ValueError:
                    if time.monotonic() >= deadline:
                        raise create_exc
                    time.sleep(0.2)
    addr = ray_tpu.get(proxy.address.remote(), timeout=120)
    if addr is None:
        try:
            ray_tpu.kill(proxy)
        except Exception:
            pass
        raise RuntimeError(f"RPC ingress failed to bind (port {port} in use?)")
    return (addr[0], addr[1])


def stop_rpc_ingress():
    import ray_tpu

    try:
        ray_tpu.kill(ray_tpu.get_actor(PROXY_NAME))
    except Exception:
        pass


class RpcIngressClient:
    """Blocking client for the binary ingress."""

    def __init__(self, host: str, port: int):
        from ray_tpu._private.rpc import EventLoopThread, SyncRpcClient

        self._io = EventLoopThread(name="rpc-ingress-client")
        self._client = SyncRpcClient(host, port, self._io,
                                     label="rpc-ingress")

    def invoke(self, app: str, *args, method: str = "__call__",
               timeout: float = 120.0, **kwargs) -> Any:
        # backend_timeout rides the payload so the replica-side get
        # honors the caller's deadline; the RPC deadline sits just above
        return self._client.call("invoke", app=app, args=list(args),
                                 kwargs=kwargs, target_method=method,
                                 backend_timeout=timeout,
                                 timeout=timeout + 10.0)

    def routes(self) -> list:
        return self._client.call("routes")["routes"]

    def healthz(self) -> bool:
        return bool(self._client.call("healthz").get("ok"))

    def close(self):
        try:
            self._client.close()
        except Exception:
            pass
        self._io.stop()
