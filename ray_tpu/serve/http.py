"""HTTP ingress for ray_tpu.serve.

Equivalent of the reference's per-node proxy actors
(reference: python/ray/serve/_private/proxy.py — uvicorn HTTP ingress
routing to DeploymentHandles via the router).  This proxy is an actor
hosting a minimal asyncio HTTP/1.1 server (no third-party deps in the
image): requests to ``/<deployment>`` are routed through a
DeploymentHandle, so they get the same least-outstanding-requests
balancing, replica refresh, and autoscaling metrics as in-cluster
callers.

The data plane is ASYNC END TO END: every request runs as one coroutine
on the proxy event loop — handle routing via ``remote_async`` /
``stream_async`` and value resolution via awaitable object refs
(``worker.get_async``), so in-flight capacity is bounded by the
configurable shed gate (503 beyond ``serve_max_inflight_requests``),
not by an executor thread pool.  Trace context rides contextvars (one
asyncio task per request isolates them); connections are keep-alive
with HTTP/1.1 pipelining, and chunked/SSE responses leave the
connection open.  The pre-async executor-thread dispatch survives as
``legacy_threads=True`` purely as the bench baseline for serve_rps.

Routing convention:
  GET  /<name>            -> callable invoked with the query dict ({} if none)
  POST /<name>  (json)    -> callable invoked with the parsed JSON body
  POST /<name>  (other)   -> callable invoked with the raw body bytes
  GET  /-/healthz         -> 200 "ok" (proxy liveness)
Responses are JSON-encoded when possible, else ``str()``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional
from urllib.parse import parse_qsl, urlsplit

PROXY_NAME = "_serve_http_proxy"

# sentinel first element of a _read_request error result
_PARSE_ERR = "_err"

# sentinel for "stream produced no first item" in the prefetch path
_NO_ITEM = object()


def _is_overload_error(e) -> bool:
    """Replica-side admission shed (serve/llm.py LLMOverloadedError)
    riding inside a RayTaskError chain — matched structurally so the
    proxy can answer 503 without importing the llm module on the hot
    path."""
    return _chain_has(e, "LLMOverloadedError")


def _is_deadline_error(e) -> bool:
    """DeadlineExceededError — raised proxy-side by a bounded await, or
    replica-side (LLM admission, a bounded nested get) and carried in a
    RayTaskError chain.  Mapped to 504 Gateway Timeout: the budget is
    spent, retrying the same request cannot help."""
    return _chain_has(e, "DeadlineExceededError")


def _chain_has(e, name: str) -> bool:
    seen = set()
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if type(e).__name__ == name or name in str(e):
            return True
        e = getattr(e, "cause", None) or e.__cause__
    return False


class _GateCharge:
    """Once-only holder of one admission-gate slot.  Released by the
    gated stream's finally on any consumed path; the __del__ fallback
    covers a stream dropped before its first iteration — an unstarted
    async generator's finally never runs, so GC of the wrapper (which
    pins this object in its closure) is the only signal left."""

    __slots__ = ("_proxy", "_lock", "_released")

    def __init__(self, proxy):
        self._proxy = proxy
        self._lock = threading.Lock()
        self._released = False

    def release(self) -> None:
        with self._lock:
            if self._released:
                return
            self._released = True
        proxy = self._proxy

        def dec():
            proxy._inflight -= 1

        try:
            if threading.get_ident() == proxy._loop_thread_ident:
                dec()
            else:
                proxy._loop.call_soon_threadsafe(dec)
        except RuntimeError:
            pass  # loop closed: the proxy is going away

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass


class _HttpProxy:
    """Actor wrapping the asyncio HTTP server (one per ingress port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_inflight: Optional[int] = None,
                 legacy_threads: bool = False):
        import asyncio

        from ray_tpu._private.config import config
        from ray_tpu._private.metrics import (default_registry,
                                              serve_proxy_inflight_gauge,
                                              serve_request_latency_histogram,
                                              serve_sheds_counter)

        self._handles: Dict[str, Any] = {}
        self._legacy = legacy_threads
        self._max_inflight = int(
            max_inflight if max_inflight is not None
            else config.serve_max_inflight_requests)
        self._inflight = 0  # loop-confined: touched only on the proxy loop
        self._latency = serve_request_latency_histogram()
        # 503s by reason — the serve autoscaler's SLO-pressure signal
        self._sheds = serve_sheds_counter()
        # inflight gauge sampled at metrics render — zero cost on the
        # request hot path (see metrics.serve_proxy_inflight_gauge).
        # The collector is deregistered when the serve loop exits so a
        # recycled worker process hosting successive proxies doesn't
        # accumulate closures over dead instances.
        inflight_g = serve_proxy_inflight_gauge()
        self._inflight_collector = lambda: inflight_g.set(self._inflight)
        default_registry.add_collector(self._inflight_collector)
        self._loop = asyncio.new_event_loop()
        self._loop_thread_ident = None  # set by the serve thread
        self._started = threading.Event()
        self._addr: Optional[tuple] = None
        self._thread = threading.Thread(
            target=self._serve_forever, args=(host, port),
            name="serve-http", daemon=True)
        self._thread.start()
        self._started.wait(30)

    def _serve_forever(self, host: str, port: int):
        import asyncio

        self._loop_thread_ident = threading.get_ident()
        asyncio.set_event_loop(self._loop)

        from ray_tpu._private.config import config

        # stream buffer comfortably above the header cap so the 431
        # path (not a raw ValueError from readline) handles long lines
        limit = max(2 ** 16, 2 * int(config.serve_max_header_bytes))

        probe_task = None

        async def _start():
            nonlocal probe_task
            from ray_tpu._private.profiling import loop_lag_probe

            server = await asyncio.start_server(self._client, host, port,
                                                limit=limit)
            self._addr = server.sockets[0].getsockname()[:2]
            # health probe for the proxy's own loop: request handling is
            # loop-confined, so lag here IS added request latency
            probe_task = asyncio.ensure_future(loop_lag_probe("serve_proxy"))
            self._started.set()
            return server

        server = self._loop.run_until_complete(_start())
        try:
            self._loop.run_forever()
        finally:
            # a forever-task left pending when the loop dies spews
            # "Task was destroyed but it is pending!" at teardown
            if probe_task is not None:
                probe_task.cancel()
            from ray_tpu._private.metrics import default_registry

            default_registry.remove_collector(self._inflight_collector)
            server.close()

    def address(self):
        return list(self._addr) if self._addr else None

    def health(self):
        return True

    # ---- connection handling ----------------------------------------------

    async def _client(self, reader, writer):
        """Per-connection driver: a parse loop feeds an ordered queue of
        response slots consumed by one writer coroutine — request N+1 is
        parsed and ROUTED while N is still executing (HTTP/1.1
        pipelining), responses always leave in request order.  The
        bounded queue is the per-connection pipelining backpressure."""
        import asyncio

        from ray_tpu._private.config import config

        slots: "asyncio.Queue" = asyncio.Queue(
            maxsize=max(1, int(config.serve_pipeline_depth)))
        wtask = asyncio.ensure_future(self._response_writer(slots, writer))
        tasks = []
        try:
            while not wtask.done():
                req = await self._read_request(reader)
                if req is None:
                    break  # clean EOF / client went away
                if req[0] is _PARSE_ERR:
                    # framing is untrustworthy after a parse error:
                    # respond and close
                    slot = asyncio.get_running_loop().create_future()
                    slot.set_result((req[1], req[2], None, False))
                    await self._put_slot(slots, slot, wtask)
                    break
                method, target, headers, body, keep = req
                slot = asyncio.get_running_loop().create_future()
                if not await self._put_slot(slots, slot, wtask):
                    break  # writer died with the queue full: tear down
                tasks.append(asyncio.ensure_future(self._handle_request(
                    method, target, headers, body, keep, slot)))
                tasks = [t for t in tasks if not t.done()]
                if not keep:
                    break  # last request on this connection
            # end-of-responses sentinel
            await self._put_slot(slots, None, wtask)
            try:
                await wtask
            except Exception:
                pass
        except (ConnectionError, TimeoutError):
            pass  # peer went away: normal
        except asyncio.IncompleteReadError:
            pass
        except Exception as e:
            import sys

            print(f"[serve.http] connection handler error: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
        finally:
            wtask.cancel()
            for t in tasks:
                t.cancel()
            try:
                writer.close()
            except Exception:
                pass

    @staticmethod
    async def _put_slot(slots, slot, wtask) -> bool:
        """Enqueue a response slot, raced against writer-task exit: a
        full pipeline queue with a dead writer (peer reset mid-burst)
        must never park the connection coroutine forever.  Returns False
        when the writer is gone."""
        import asyncio

        put = asyncio.ensure_future(slots.put(slot))
        await asyncio.wait({put, wtask},
                           return_when=asyncio.FIRST_COMPLETED)
        if put.done():
            return True
        put.cancel()
        return False

    async def _read_request(self, reader):
        """Parse one request.  Returns (method, target, headers, body,
        keep), an (``_PARSE_ERR``, status, payload) triple for requests
        answered with an error + close, or None on EOF.

        Defensive by design (one misbehaving client must not take the
        proxy down): malformed Content-Length -> 400, header bytes
        beyond serve_max_header_bytes -> 431, bodies beyond
        serve_max_body_bytes -> 413.  HTTP/1.0 is close-by-default —
        keep-alive only on explicit opt-in."""
        import asyncio

        from ray_tpu._private.config import config

        max_head = int(config.serve_max_header_bytes)
        try:
            while True:  # tolerate stray blank lines between requests
                line = await reader.readline()
                if not line:
                    return None
                if line not in (b"\r\n", b"\n"):
                    break
            if len(line) > max_head:
                return (_PARSE_ERR, "431 Request Header Fields Too Large",
                        b'{"error": "request line too long"}')
            try:
                method, target, version = line.decode("latin1").split(" ", 2)
            except ValueError:
                return (_PARSE_ERR, "400 Bad Request",
                        b'{"error": "malformed request line"}')
            headers: Dict[str, str] = {}
            total = len(line)
            while True:
                h = await reader.readline()
                if not h:
                    return None  # EOF mid-headers
                if h in (b"\r\n", b"\n"):
                    break
                total += len(h)
                if total > max_head:
                    return (_PARSE_ERR,
                            "431 Request Header Fields Too Large",
                            b'{"error": "headers too large"}')
                k, _, v = h.decode("latin1").partition(":")
                headers[k.strip().lower()] = v.strip()
        except (ConnectionError, asyncio.IncompleteReadError):
            return None
        except ValueError:
            # readline overran the stream buffer: a single header line
            # beyond even the raised limit
            return (_PARSE_ERR, "431 Request Header Fields Too Large",
                    b'{"error": "header line too long"}')
        http10 = version.strip().upper().startswith("HTTP/1.0")
        conn = headers.get("connection", "").lower()
        keep = (conn == "keep-alive") if http10 else (conn != "close")
        te = headers.get("transfer-encoding", "").lower()
        if te and te != "identity":
            # a chunked body we don't de-frame would be re-parsed as
            # pipelined requests — the classic smuggling vector; refuse
            # instead of desyncing
            return (_PARSE_ERR, "501 Not Implemented",
                    b'{"error": "transfer-encoding not supported"}')
        body = b""
        cl = headers.get("content-length")
        if cl:
            try:
                length = int(cl)
                if length < 0:
                    raise ValueError(cl)
            except ValueError:
                return (_PARSE_ERR, "400 Bad Request",
                        b'{"error": "invalid content-length"}')
            if length > int(config.serve_max_body_bytes):
                return (_PARSE_ERR, "413 Payload Too Large",
                        b'{"error": "request body too large"}')
            if length:
                try:
                    body = await reader.readexactly(length)
                except (ConnectionError, asyncio.IncompleteReadError):
                    return None
        return method, target, headers, body, keep

    async def _handle_request(self, method, target, headers, body, keep,
                              slot):
        import asyncio

        try:
            status, payload, stream = await self._route(method, target,
                                                        headers, body)
        except asyncio.CancelledError:
            # connection teardown cancelled us: wake a writer parked on
            # this slot, then stay cancelled (never fabricate a 500)
            if not slot.done():
                slot.cancel()
            raise
        except Exception as e:
            status, payload, stream = (
                "500 Internal Server Error",
                json.dumps({"error": f"{type(e).__name__}: {e}"}).encode(),
                None)
        if not slot.done():
            slot.set_result((status, payload, stream, keep))

    async def _response_writer(self, slots, writer):
        """Drain response slots in request order (the pipelining
        contract), writing chunked/SSE responses item by item.  The
        connection stays alive after a chunked response — its framing
        is self-terminating (``0\\r\\n\\r\\n``)."""
        while True:
            slot = await slots.get()
            if slot is None:
                return
            status, payload, stream, keep = await slot
            if stream is not None:
                if hasattr(stream, "__anext__"):
                    await self._write_chunked(writer, stream, keep)
                else:
                    # legacy baseline: blocking generator, force-close
                    await self._write_chunked_legacy(writer, stream)
                    return
            else:
                writer.write(
                    b"HTTP/1.1 " + status.encode() + b"\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(payload)).encode() +
                    b"\r\n"
                    b"Connection: " + (b"keep-alive" if keep else b"close") +
                    b"\r\n\r\n" + payload)
                await writer.drain()
            if not keep:
                return

    # ---- routing ----------------------------------------------------------

    async def _route(self, method: str, target: str, headers, body: bytes):
        """Tracing + admission wrapper around the actual routing: an
        inbound W3C ``traceparent`` header continues the external
        caller's trace (reference: serve's OTel middleware); a malformed
        header is ignored — the request proceeds untraced-from-outside
        but still starts its own sampled root.  The span context is
        activated on the request's contextvars (each request is its own
        asyncio task, so contexts are isolated) and flows through
        remote_async/stream_async into the replica spans.

        Admission: beyond ``serve_max_inflight_requests`` concurrently
        routed requests the proxy sheds load with 503 instead of
        queueing — memory stays bounded and the caller gets an
        actionable signal (health checks bypass the gate)."""
        from ray_tpu._private import tracing

        path = urlsplit(target).path
        if path.strip("/") == "-/healthz":
            return await self._route_inner(method, target, headers, body)
        if not self._legacy and self._inflight >= self._max_inflight:
            self._latency.observe(0.0, tags={"code": "503"})
            self._sheds.inc(tags={"reason": "proxy"})
            self._note_shed(path.strip("/"))
            return ("503 Service Unavailable",
                    b'{"error": "proxy overloaded, try again"}', None)
        self._inflight += 1
        stream = None
        t0 = time.perf_counter()
        span = tracing.start_span(
            f"http {method} {path}", kind=tracing.KIND_SERVER,
            parent=tracing.parse_traceparent(headers.get("traceparent")))
        token = tracing.activate(span.context()) if span else None
        # an absolute X-Request-Deadline-Ms header becomes the ambient
        # deadline for this request's whole coroutine tree: the handle
        # call stamps it into the replica task spec, so every nested
        # .remote()/get() downstream spends only the caller's remaining
        # budget (deadlines.py — the W3C-traceparent of latency bounds)
        from ray_tpu._private import deadlines

        dl = deadlines.from_header(headers.get(deadlines.DEADLINE_HEADER))
        dl_token = deadlines.activate(dl) if dl is not None else None
        try:
            status, payload, stream = await self._route_inner(
                method, target, headers, body)
        except BaseException as e:
            if span is not None:
                span.end(error=f"{type(e).__name__}: {e}")
            raise
        finally:
            if stream is not None and hasattr(stream, "__anext__"):
                # a live stream keeps its in-flight charge until it
                # finishes — otherwise long-lived SSE streams would
                # escape the shed gate microseconds after admission
                stream = self._gated_stream(stream, _GateCharge(self))
            else:
                self._inflight -= 1
            if dl_token is not None:
                deadlines.restore(dl_token)
            if token is not None:
                tracing.restore(token)
        self._latency.observe(time.perf_counter() - t0,
                              tags={"code": status.split(" ", 1)[0]})
        if span is not None:
            span.set_attribute("http.status", status.split(" ", 1)[0])
            span.end(error="" if status.startswith("2") else status)
        return status, payload, stream

    def _note_shed(self, name: str) -> None:
        """Report a shed against the deployment's handle so the
        metrics pusher carries it to the controller — the replica
        autoscaler's scale-up trigger (declared headroom (c))."""
        handle = self._handles.get(name)
        if handle is not None:
            try:
                handle.note_shed()
            except Exception:
                pass

    @staticmethod
    def _gated_stream(agen, charge: _GateCharge):
        """Pass stream items through; the charge releases when the
        stream ends (exhausted, errored, generator finalized) — and,
        because the unstarted wrapper pins `charge` in its closure, via
        _GateCharge.__del__ if the stream is dropped before its first
        iteration (where no finally could ever run)."""
        async def _gen():
            try:
                async for item in agen:
                    yield item
            finally:
                charge.release()
                # close the chain explicitly: GC finalization of the
                # inner generators is too late for disconnect-cancel
                try:
                    await agen.aclose()
                except Exception:
                    pass

        return _gen()

    async def _route_inner(self, method: str, target: str, headers,
                           body: bytes):
        parts = urlsplit(target)
        path = parts.path.strip("/")
        if path == "-/healthz":
            return "200 OK", b'"ok"', None
        if not path or "/" in path:
            return "404 Not Found", json.dumps(
                {"error": f"no route {parts.path!r}"}).encode(), None
        if method == "GET":
            arg: Any = dict(parse_qsl(parts.query))
        elif headers.get("content-type", "").startswith("application/json"):
            try:
                arg = json.loads(body or b"null")
            except ValueError:
                return "400 Bad Request", b'{"error": "invalid json"}', None
        else:
            arg = body
        # streaming negotiation (reference: serve streaming responses via
        # StreamingResponse): Accept: text/event-stream opts the request
        # into a chunked response fed by the replica's generator
        want_stream = headers.get("accept", "").startswith(
            "text/event-stream")
        if self._legacy:
            return await self._route_legacy(path, arg, want_stream)
        try:
            if want_stream:
                gen = await self._stream_async_values(path, arg)
                # prefetch the FIRST item before committing a status
                # line: replica-side admission errors (the LLM tier's
                # 503 shed, bad requests) become real status codes
                # instead of an error chunk behind a 200 — and TTFT for
                # token streams was always going to wait for this item
                try:
                    first = await gen.__anext__()
                except StopAsyncIteration:
                    first = _NO_ITEM
                except Exception as e:
                    with_suppress = getattr(gen, "aclose", None)
                    if with_suppress is not None:
                        try:
                            await with_suppress()
                        except Exception:
                            pass
                    if _is_overload_error(e):
                        self._sheds.inc(tags={"reason": "replica"})
                        self._note_shed(path)
                        return ("503 Service Unavailable", json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}).encode(),
                            None)
                    if _is_deadline_error(e):
                        # budget gone before the first item (LLM
                        # admission refusal, expired while queued): a
                        # real status line, not an error chunk
                        return ("504 Gateway Timeout", json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}).encode(),
                            None)
                    raise
                return "200 OK", b"", self._chain_first(first, gen)
            result = await self._call_async(path, arg)
        except KeyError:
            return "404 Not Found", json.dumps(
                {"error": f"no deployment named {path!r}"}).encode(), None
        except Exception as e:
            if _is_overload_error(e):
                # replica-side admission shed on the unary path: a real
                # 503 (retriable), not a 500 — and autoscale pressure
                self._sheds.inc(tags={"reason": "replica"})
                self._note_shed(path)
                return "503 Service Unavailable", json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}).encode(), None
            if _is_deadline_error(e):
                # the request's end-to-end deadline expired inside the
                # cluster: 504, the budget is spent
                return "504 Gateway Timeout", json.dumps(
                    {"error": f"{type(e).__name__}: {e}"}).encode(), None
            return "500 Internal Server Error", json.dumps(
                {"error": f"{type(e).__name__}: {e}"}).encode(), None
        try:
            payload = json.dumps(result).encode()
        except TypeError:
            payload = json.dumps(str(result)).encode()
        return "200 OK", payload, None

    @staticmethod
    def _chain_first(first, agen):
        """Re-attach a prefetched first item in front of the remaining
        stream; closing the chain closes the underlying stream (the
        disconnect-cancel path rides these aclose hops)."""
        async def _gen():
            try:
                if first is not _NO_ITEM:
                    yield first
                async for item in agen:
                    yield item
            finally:
                await agen.aclose()

        return _gen()

    async def _call_async(self, name: str, arg: Any):
        """The hot path: submit + await through the handle's
        dead-replica-retrying call_async on this loop — no executor
        thread anywhere.  A request whose replica died mid-flight (node
        churn) is transparently re-sent to a surviving replica inside
        the handle; the proxy-level fallback below additionally covers
        wholesale replica replacement (stale cached handle) by
        refreshing the handle once, like the sync path always did."""
        import ray_tpu

        from ray_tpu._private.errors import DeadlineExceededError

        handle = await self._resolve_handle_async(name)
        try:
            return await handle.call_async(arg, _timeout=120)
        except ray_tpu.RayTaskError:
            raise  # user exception: retrying cannot change the outcome
        except DeadlineExceededError:
            raise  # budget spent: a retry would just spend more
        except ray_tpu.RayError:
            handle = await self._resolve_handle_async(name, fresh=True)
            return await handle.call_async(arg, _timeout=120)

    async def _stream_async_values(self, name: str, arg: Any):
        """Async iterator of ITEM VALUES for an SSE response.  The
        replica call is submitted EAGERLY, here in the route coroutine
        — the ingress span is still active, so the serve.stream span
        parents correctly (the returned generator first runs later, in
        the writer task's context).  A stale cached handle refreshes
        once — safe to restart the stream unconditionally only before
        any item was consumed.

        Mid-stream replica death is additionally survivable for
        RESUMABLE streams — ones whose every item is a dict carrying an
        integer generation index "i" (the LLM serving contract): the
        request is re-sent once with ``emit_from`` = last delivered
        index + 1 (and the original dict arg, so a ``request_id``
        re-attaches to live sequence state on a surviving replica).
        The client sees at most one duplicated token boundary; greedy
        decode is deterministic, so a re-prefill on a survivor yields
        identical tokens."""
        import ray_tpu

        info: Dict[str, Any] = {}
        if isinstance(arg, dict) and not arg.get("request_id"):
            # stamp the rid HERE, before the first submit: the
            # disaggregated-prefill hop (handle._maybe_prefill) and any
            # mid-stream resume then address the same engine sequence —
            # shipped KV pages and re-attach both key on request_id
            import uuid

            arg["request_id"] = uuid.uuid4().hex
        handle = await self._resolve_handle_async(name)
        agen = await handle.stream_async(arg, _info=info)

        async def _values():
            import asyncio

            from ray_tpu._private.config import config
            from ray_tpu._private.errors import (ActorDiedError,
                                                 ActorUnavailableError,
                                                 DeadlineExceededError,
                                                 RayWorkerError)

            dead_errors = (ActorDiedError, ActorUnavailableError,
                           RayWorkerError)
            nonlocal handle, agen
            yielded = False
            resumable = isinstance(arg, dict)
            last_i = None
            # pre-first-item restarts keep the old once-only budget;
            # mid-stream RESUMES get the dead-replica retry budget,
            # excluding replicas this stream already saw die (a fresh
            # roster may briefly still list them, and their zero
            # inflight would draw the least-outstanding pick back)
            attempts = 1 + max(0, int(config.serve_dead_replica_retries))
            retries = 0
            dead: set = set()
            try:
                while True:
                    try:
                        try:
                            ref = await agen.__anext__()
                        except StopAsyncIteration:
                            return
                        value = await ray_tpu.get_async(ref, timeout=120)
                    except ray_tpu.RayTaskError:
                        raise  # user/application error: never retried
                    except DeadlineExceededError:
                        # the stream's budget expired mid-decode: close
                        # with the typed error chunk (the chunk writer's
                        # producer-error path), never resume-retry
                        raise
                    except ray_tpu.RayError as e:
                        retries += 1
                        if isinstance(e, dead_errors) and info.get("rid"):
                            # only replica DEATH blacklists the replica;
                            # transient runtime errors must not strip a
                            # healthy roster
                            dead.add(info["rid"])
                            handle._drop_replica(info["rid"])
                        if not yielded:
                            if retries > 1:
                                raise
                            handle = await self._resolve_handle_async(
                                name, fresh=True)
                            agen = await handle.stream_async(
                                arg, _exclude=dead, _info=info)
                            continue
                        if resumable and last_i is not None \
                                and retries <= attempts:
                            await asyncio.sleep(0.25 * retries)
                            handle = await self._resolve_handle_async(
                                name, fresh=True)
                            agen = await handle.stream_async(
                                {**arg, "emit_from": last_i + 1},
                                _exclude=dead, _info=info)
                            continue
                        raise  # mid-stream death, not resumable
                    yielded = True
                    if resumable and isinstance(value, dict) \
                            and isinstance(value.get("i"), int):
                        # coalesced items cover [i, i+len(tokens)-1]
                        span = value.get("tokens")
                        last_i = value["i"] + (
                            len(span) - 1 if isinstance(span, list)
                            and span else 0)
                    else:
                        resumable = False
                    yield value
            finally:
                # closing the value stream closes the handle stream,
                # whose finally cancels an unfinished replica-side
                # generator — the disconnect -> free-KV-pages path
                try:
                    await agen.aclose()
                except Exception:
                    pass

        return _values()

    async def _write_chunked(self, writer, agen, keep: bool):
        """One HTTP/1.1 chunk per streamed item (JSON + newline), pulled
        off the async value iterator on this loop.  Chunked framing is
        self-terminating, so the connection stays alive afterwards.

        Client disconnect (write/drain raising a connection error) stops
        the pull loop IMMEDIATELY — no error chunk is owed to a dead
        peer — and the finally's aclose cascades down the stream chain:
        gate charge released, handle inflight released, replica-side
        generator cancelled (an abandoned LLM decode frees its KV pages
        instead of generating to max_seq_len)."""
        try:
            writer.write(b"HTTP/1.1 200 OK\r\n"
                         b"Content-Type: text/event-stream\r\n"
                         b"Transfer-Encoding: chunked\r\n"
                         b"Connection: " +
                         (b"keep-alive" if keep else b"close") +
                         b"\r\n\r\n")
            await writer.drain()
            while True:
                # the PRODUCER pull gets its own try: any replica-side
                # failure (including timeouts, which share bases with
                # connection errors) is reported to the still-live peer
                # as an error chunk — only WRITER failures below mean
                # the peer itself is gone
                try:
                    item = await agen.__anext__()
                except StopAsyncIteration:
                    break
                except Exception as e:
                    data = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    writer.write(hex(len(data))[2:].encode() + b"\r\n"
                                 + data + b"\r\n")
                    break
                try:
                    data = json.dumps(item).encode() + b"\n"
                except TypeError:
                    data = json.dumps(str(item)).encode() + b"\n"
                writer.write(hex(len(data))[2:].encode() + b"\r\n"
                             + data + b"\r\n")
                await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()
        except (ConnectionError, TimeoutError, OSError):
            pass  # disconnect: the finally tears the producer down
        finally:
            # explicit close, not GC: a peer that vanished mid-stream
            # must release the admission-gate charge NOW (asyncgen
            # finalization can sit behind a traceback cycle until a
            # full GC pass)
            try:
                await agen.aclose()
            except Exception:
                pass

    # ---- handle cache -----------------------------------------------------

    async def _resolve_handle_async(self, name: str, fresh: bool = False):
        """Cached-handle lookup (the hot path: one dict read).  A cache
        miss resolves through the controller on an executor thread —
        explicitly NOT the request hot path (first request per
        deployment, or a post-RayError refresh)."""
        import asyncio

        if not fresh and name in self._handles:
            return self._handles[name]
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._resolve_handle, name, fresh)

    def _resolve_handle(self, name: str, fresh: bool = False):
        from ray_tpu.serve import api as serve_api

        if fresh:
            self._handles.pop(name, None)
        handle = self._handles.get(name)
        if handle is None:
            try:
                handle = serve_api.get_handle(name)
            except ValueError:
                raise KeyError(name)
            self._handles[name] = handle
        return handle

    # ---- legacy executor-thread dispatch (bench baseline only) -------------

    async def _route_legacy(self, path: str, arg: Any, want_stream: bool):
        """The pre-async data plane, kept verbatim as the measurable
        baseline for bench.py's serve_rps comparison: two thread hops
        per request, concurrency capped by the executor pool."""
        import asyncio

        from ray_tpu._private import tracing

        trace_ctx = tracing.current_context()
        loop = asyncio.get_running_loop()
        try:
            if want_stream:
                gen = await loop.run_in_executor(
                    None, self._stream_blocking, path, arg, trace_ctx)
                return "200 OK", b"", gen
            result = await loop.run_in_executor(
                None, self._call_blocking, path, arg, trace_ctx)
        except KeyError:
            return "404 Not Found", json.dumps(
                {"error": f"no deployment named {path!r}"}).encode(), None
        except Exception as e:
            return "500 Internal Server Error", json.dumps(
                {"error": f"{type(e).__name__}: {e}"}).encode(), None
        try:
            payload = json.dumps(result).encode()
        except TypeError:
            payload = json.dumps(str(result)).encode()
        return "200 OK", payload, None

    async def _write_chunked_legacy(self, writer, gen):
        """Chunk writer for the legacy blocking generator: items pulled
        in the executor; the connection closes afterwards (the old
        force-close behavior, preserved for baseline fidelity)."""
        import asyncio

        loop = asyncio.get_running_loop()
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Transfer-Encoding: chunked\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()
        _end = object()
        try:
            while True:
                item = await loop.run_in_executor(None, next, gen, _end)
                if item is _end:
                    break
                try:
                    data = json.dumps(item).encode() + b"\n"
                except TypeError:
                    data = json.dumps(str(item)).encode() + b"\n"
                writer.write(hex(len(data))[2:].encode() + b"\r\n"
                             + data + b"\r\n")
                await writer.drain()
        except Exception as e:
            data = json.dumps({"error": f"{type(e).__name__}: {e}"}).encode()
            writer.write(hex(len(data))[2:].encode() + b"\r\n"
                         + data + b"\r\n")
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    @staticmethod
    def _with_trace(trace_ctx, fn, *args):
        """Run fn with the ingress span active, restoring the thread's
        context immediately after — the window is kept tight because
        executor threads are shared across requests (and a generator
        frame resuming on one must not leak its context)."""
        if trace_ctx is None:
            return fn(*args)
        from ray_tpu._private import tracing

        token = tracing.activate(trace_ctx)
        try:
            return fn(*args)
        finally:
            tracing.restore(token)

    def _stream_blocking(self, name: str, arg: Any, trace_ctx=None):
        """Resolve the handle and return an iterator of ITEM VALUES
        (refs resolved here, off the event loop).  Like _call_blocking,
        a stale cached handle (replicas replaced wholesale) refreshes
        once — safe to restart the stream only before any item was
        consumed."""
        import ray_tpu

        handle = self._resolve_handle(name)

        def _values():
            nonlocal handle
            gen = self._with_trace(trace_ctx, handle.stream, arg)
            yielded = retried = False
            while True:
                try:
                    ref = next(gen, None)
                    if ref is None:
                        return
                    value = ray_tpu.get(ref, timeout=120)
                except ray_tpu.RayError:
                    if yielded or retried:
                        raise  # mid-stream death: cannot transparently restart
                    retried = True
                    handle = self._resolve_handle(name, fresh=True)
                    gen = self._with_trace(trace_ctx, handle.stream, arg)
                    continue
                yielded = True
                yield value

        return _values()

    def _call_blocking(self, name: str, arg: Any, trace_ctx=None):
        import ray_tpu

        handle = self._resolve_handle(name)
        try:
            return ray_tpu.get(
                self._with_trace(trace_ctx, handle.remote, arg),
                timeout=120)
        except ray_tpu.RayError:
            # replicas may have been replaced wholesale: refresh once
            handle = self._resolve_handle(name, fresh=True)
            return ray_tpu.get(
                self._with_trace(trace_ctx, handle.remote, arg),
                timeout=120)


def _proxy_name(node_id: str) -> str:
    return f"{PROXY_NAME}:{node_id[:12]}"


def start_http(host: str = "127.0.0.1", port: int = 0,
               max_inflight: Optional[int] = None,
               legacy_threads: bool = False):
    """Start (or fetch) the primary HTTP ingress; returns (host, port).

    One proxy per node (reference: _private/proxy.py runs per-node
    ingress actors): each proxy is pinned to its node via the implicit
    ``node:<id>`` resource and binds its own port, so requests enter on
    any node and route to replicas anywhere with locality-aware
    balancing.  Returns the primary (first node) proxy's address; use
    `proxy_addresses()` for all of them.

    ``max_inflight`` overrides the serve_max_inflight_requests shed
    gate; ``legacy_threads`` starts the executor-thread baseline data
    plane (bench comparisons only).  Both apply only to proxies CREATED
    by this call — an already-running proxy keeps its settings (use
    shutdown_http() first to change them).
    """
    addrs = start_per_node_http(host, port, max_inflight=max_inflight,
                                legacy_threads=legacy_threads)
    if not addrs:
        raise RuntimeError("HTTP proxy failed to bind")
    return addrs[0]


def start_per_node_http(host: str = "127.0.0.1", port: int = 0,
                        max_inflight: Optional[int] = None,
                        legacy_threads: bool = False):
    """Ensure a proxy on every node; returns [(host, port), ...].

    A fixed `port` applies only when nodes live on distinct hosts;
    multi-node-on-one-box tests must use port=0.
    """
    import ray_tpu
    import ray_tpu.api as rapi

    addrs = []
    for node in ray_tpu.nodes():
        nid = node["node_id"]
        pname = _proxy_name(nid)
        try:
            proxy = ray_tpu.get_actor(pname)
        except ValueError:
            try:
                proxy = rapi.ActorClass(
                    _HttpProxy, name=pname, lifetime="detached",
                    max_concurrency=16,
                    resources={f"node:{nid[:12]}": 0.001},
                ).remote(host, port, max_inflight, legacy_threads)
            except Exception as create_exc:
                # most likely a name collision (an RpcError, not a
                # RayError): another driver is creating this proxy
                # concurrently — wait for the winner to register the name
                deadline = time.monotonic() + 30
                while True:
                    try:
                        proxy = ray_tpu.get_actor(pname)
                        break
                    except ValueError:
                        if time.monotonic() >= deadline:
                            raise create_exc
                        time.sleep(0.2)
        addr = ray_tpu.get(proxy.address.remote(), timeout=120)
        if addr is None:
            # never leave a bind-failed proxy registered under the node
            # name — it would shadow every future start attempt
            try:
                ray_tpu.kill(proxy)
            except Exception:
                pass
            raise RuntimeError(
                f"HTTP proxy failed to bind on node {nid[:12]} "
                f"(port {port} in use?)")
        addrs.append((addr[0], addr[1]))
    return addrs


def proxy_addresses():
    """Addresses of every live per-node proxy."""
    import ray_tpu

    out = []
    for node in ray_tpu.nodes():
        try:
            proxy = ray_tpu.get_actor(_proxy_name(node["node_id"]))
            addr = ray_tpu.get(proxy.address.remote(), timeout=30)
            if addr is not None:
                out.append((addr[0], addr[1]))
        except Exception:
            continue
    return out


def shutdown_http():
    import ray_tpu

    killed = []
    for node in ray_tpu.nodes():
        pname = _proxy_name(node["node_id"])
        try:
            proxy = ray_tpu.get_actor(pname)
            ray_tpu.kill(proxy)
            killed.append(pname)
        except Exception:
            continue
    # wait (bounded) for the names to deregister so an immediate
    # restart — bench alternates data planes proxy-by-proxy — can't
    # race a stale name into a dead-actor handle
    deadline = time.monotonic() + 10
    for pname in killed:
        while time.monotonic() < deadline:
            try:
                ray_tpu.get_actor(pname)
            except ValueError:
                break  # name gone
            except Exception:
                break  # head unreachable: nothing more to wait on
            time.sleep(0.05)
