"""HTTP ingress for ray_tpu.serve.

Equivalent of the reference's per-node proxy actors
(reference: python/ray/serve/_private/proxy.py — uvicorn HTTP ingress
routing to DeploymentHandles via the router).  This proxy is an actor
hosting a minimal asyncio HTTP/1.1 server (no third-party deps in the
image): requests to ``/<deployment>`` are routed through a
DeploymentHandle, so they get the same least-outstanding-requests
balancing, replica refresh, and autoscaling metrics as in-cluster
callers.

Routing convention:
  GET  /<name>            -> callable invoked with the query dict ({} if none)
  POST /<name>  (json)    -> callable invoked with the parsed JSON body
  POST /<name>  (other)   -> callable invoked with the raw body bytes
  GET  /-/healthz         -> 200 "ok" (proxy liveness)
Responses are JSON-encoded when possible, else ``str()``.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, Optional
from urllib.parse import parse_qsl, urlsplit

PROXY_NAME = "_serve_http_proxy"


class _HttpProxy:
    """Actor wrapping the asyncio HTTP server (one per ingress port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import asyncio

        self._handles: Dict[str, Any] = {}
        self._loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._addr: Optional[tuple] = None
        self._thread = threading.Thread(
            target=self._serve_forever, args=(host, port),
            name="serve-http", daemon=True)
        self._thread.start()
        self._started.wait(30)

    def _serve_forever(self, host: str, port: int):
        import asyncio

        asyncio.set_event_loop(self._loop)

        async def _start():
            server = await asyncio.start_server(self._client, host, port)
            self._addr = server.sockets[0].getsockname()[:2]
            self._started.set()
            return server

        server = self._loop.run_until_complete(_start())
        try:
            self._loop.run_forever()
        finally:
            server.close()

    def address(self):
        return list(self._addr) if self._addr else None

    def health(self):
        return True

    # ---- request handling --------------------------------------------------

    async def _client(self, reader, writer):
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
                try:
                    method, target, _ = line.decode("latin1").split(" ", 2)
                except ValueError:
                    break
                headers: Dict[str, str] = {}
                while True:
                    h = await reader.readline()
                    if not h or h in (b"\r\n", b"\n"):
                        break
                    k, _, v = h.decode("latin1").partition(":")
                    headers[k.strip().lower()] = v.strip()
                body = b""
                length = int(headers.get("content-length", 0) or 0)
                if length:
                    body = await reader.readexactly(length)
                status, payload, stream = await self._route(method, target,
                                                            headers, body)
                keep = headers.get("connection", "keep-alive") != "close"
                if stream is not None:
                    await self._write_chunked(writer, stream)
                    break  # chunked responses close the connection
                writer.write(
                    b"HTTP/1.1 " + status.encode() + b"\r\n"
                    b"Content-Type: application/json\r\n"
                    b"Content-Length: " + str(len(payload)).encode() + b"\r\n"
                    b"Connection: " + (b"keep-alive" if keep else b"close") +
                    b"\r\n\r\n" + payload)
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, TimeoutError) as e:
            pass  # peer went away: normal
        except Exception as e:
            import asyncio
            import sys

            if not isinstance(e, asyncio.IncompleteReadError):
                print(f"[serve.http] connection handler error: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _route(self, method: str, target: str, headers, body: bytes):
        """Tracing wrapper around the actual routing: an inbound W3C
        ``traceparent`` header continues the external caller's trace
        (reference: serve's OTel middleware); a malformed header is
        ignored — the request proceeds untraced-from-outside but still
        starts its own sampled root.  The ingress span context is handed
        to the executor-thread handle call explicitly because
        run_in_executor does not carry contextvars."""
        from ray_tpu._private import tracing

        path = urlsplit(target).path
        if path.strip("/") == "-/healthz":
            return await self._route_inner(method, target, headers, body,
                                           None)
        span = tracing.start_span(
            f"http {method} {path}", kind=tracing.KIND_SERVER,
            parent=tracing.parse_traceparent(headers.get("traceparent")))
        if span is None:
            return await self._route_inner(method, target, headers, body,
                                           None)
        try:
            status, payload, stream = await self._route_inner(
                method, target, headers, body, span.context())
        except BaseException as e:
            span.end(error=f"{type(e).__name__}: {e}")
            raise
        span.set_attribute("http.status", status.split(" ", 1)[0])
        span.end(error="" if status.startswith("2") else status)
        return status, payload, stream

    async def _route_inner(self, method: str, target: str, headers,
                           body: bytes, trace_ctx):
        import asyncio

        parts = urlsplit(target)
        path = parts.path.strip("/")
        if path == "-/healthz":
            return "200 OK", b'"ok"', None
        if not path or "/" in path:
            return "404 Not Found", json.dumps(
                {"error": f"no route {parts.path!r}"}).encode(), None
        if method == "GET":
            arg: Any = dict(parse_qsl(parts.query))
        elif headers.get("content-type", "").startswith("application/json"):
            try:
                arg = json.loads(body or b"null")
            except ValueError:
                return "400 Bad Request", b'{"error": "invalid json"}', None
        else:
            arg = body
        # streaming negotiation (reference: serve streaming responses via
        # StreamingResponse): Accept: text/event-stream opts the request
        # into a chunked response fed by the replica's generator
        want_stream = headers.get("accept", "").startswith(
            "text/event-stream")
        loop = asyncio.get_running_loop()
        try:
            if want_stream:
                gen = await loop.run_in_executor(
                    None, self._stream_blocking, path, arg, trace_ctx)
                return "200 OK", b"", gen
            result = await loop.run_in_executor(
                None, self._call_blocking, path, arg, trace_ctx)
        except KeyError:
            return "404 Not Found", json.dumps(
                {"error": f"no deployment named {path!r}"}).encode(), None
        except Exception as e:
            return "500 Internal Server Error", json.dumps(
                {"error": f"{type(e).__name__}: {e}"}).encode(), None
        try:
            payload = json.dumps(result).encode()
        except TypeError:
            payload = json.dumps(str(result)).encode()
        return "200 OK", payload, None

    async def _write_chunked(self, writer, gen):
        """Write one HTTP/1.1 chunk per streamed item (JSON + newline),
        pulling items off the blocking generator in the executor."""
        import asyncio

        loop = asyncio.get_running_loop()
        writer.write(b"HTTP/1.1 200 OK\r\n"
                     b"Content-Type: text/event-stream\r\n"
                     b"Transfer-Encoding: chunked\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()
        _end = object()
        try:
            while True:
                item = await loop.run_in_executor(None, next, gen, _end)
                if item is _end:
                    break
                try:
                    data = json.dumps(item).encode() + b"\n"
                except TypeError:
                    data = json.dumps(str(item)).encode() + b"\n"
                writer.write(hex(len(data))[2:].encode() + b"\r\n"
                             + data + b"\r\n")
                await writer.drain()
        except Exception as e:
            data = json.dumps({"error": f"{type(e).__name__}: {e}"}).encode()
            writer.write(hex(len(data))[2:].encode() + b"\r\n"
                         + data + b"\r\n")
        writer.write(b"0\r\n\r\n")
        await writer.drain()

    @staticmethod
    def _with_trace(trace_ctx, fn, *args):
        """Run fn with the ingress span active, restoring the thread's
        context immediately after — the window is kept tight because
        executor threads are shared across requests (and a generator
        frame resuming on one must not leak its context)."""
        if trace_ctx is None:
            return fn(*args)
        from ray_tpu._private import tracing

        token = tracing.activate(trace_ctx)
        try:
            return fn(*args)
        finally:
            tracing.restore(token)

    def _stream_blocking(self, name: str, arg: Any, trace_ctx=None):
        """Resolve the handle and return an iterator of ITEM VALUES
        (refs resolved here, off the event loop).  Like _call_blocking,
        a stale cached handle (replicas replaced wholesale) refreshes
        once — safe to restart the stream only before any item was
        consumed."""
        import ray_tpu

        handle = self._resolve_handle(name)

        def _values():
            nonlocal handle
            gen = self._with_trace(trace_ctx, handle.stream, arg)
            yielded = retried = False
            while True:
                try:
                    ref = next(gen, None)
                    if ref is None:
                        return
                    value = ray_tpu.get(ref, timeout=120)
                except ray_tpu.RayError:
                    if yielded or retried:
                        raise  # mid-stream death: cannot transparently restart
                    retried = True
                    handle = self._resolve_handle(name, fresh=True)
                    gen = self._with_trace(trace_ctx, handle.stream, arg)
                    continue
                yielded = True
                yield value

        return _values()

    def _resolve_handle(self, name: str, fresh: bool = False):
        from ray_tpu.serve import api as serve_api

        if fresh:
            self._handles.pop(name, None)
        handle = self._handles.get(name)
        if handle is None:
            try:
                handle = serve_api.get_handle(name)
            except ValueError:
                raise KeyError(name)
            self._handles[name] = handle
        return handle

    def _call_blocking(self, name: str, arg: Any, trace_ctx=None):
        import ray_tpu

        handle = self._resolve_handle(name)
        try:
            return ray_tpu.get(
                self._with_trace(trace_ctx, handle.remote, arg),
                timeout=120)
        except ray_tpu.RayError:
            # replicas may have been replaced wholesale: refresh once
            handle = self._resolve_handle(name, fresh=True)
            return ray_tpu.get(
                self._with_trace(trace_ctx, handle.remote, arg),
                timeout=120)


def _proxy_name(node_id: str) -> str:
    return f"{PROXY_NAME}:{node_id[:12]}"


def start_http(host: str = "127.0.0.1", port: int = 0):
    """Start (or fetch) the primary HTTP ingress; returns (host, port).

    One proxy per node (reference: _private/proxy.py runs per-node
    ingress actors): each proxy is pinned to its node via the implicit
    ``node:<id>`` resource and binds its own port, so requests enter on
    any node and route to replicas anywhere with locality-aware
    balancing.  Returns the primary (first node) proxy's address; use
    `proxy_addresses()` for all of them.
    """
    addrs = start_per_node_http(host, port)
    if not addrs:
        raise RuntimeError("HTTP proxy failed to bind")
    return addrs[0]


def start_per_node_http(host: str = "127.0.0.1", port: int = 0):
    """Ensure a proxy on every node; returns [(host, port), ...].

    A fixed `port` applies only when nodes live on distinct hosts;
    multi-node-on-one-box tests must use port=0.
    """
    import ray_tpu
    import ray_tpu.api as rapi

    addrs = []
    for node in ray_tpu.nodes():
        nid = node["node_id"]
        pname = _proxy_name(nid)
        try:
            proxy = ray_tpu.get_actor(pname)
        except ValueError:
            try:
                proxy = rapi.ActorClass(
                    _HttpProxy, name=pname, lifetime="detached",
                    max_concurrency=16,
                    resources={f"node:{nid[:12]}": 0.001},
                ).remote(host, port)
            except Exception as create_exc:
                # most likely a name collision (an RpcError, not a
                # RayError): another driver is creating this proxy
                # concurrently — wait for the winner to register the name
                deadline = time.monotonic() + 30
                while True:
                    try:
                        proxy = ray_tpu.get_actor(pname)
                        break
                    except ValueError:
                        if time.monotonic() >= deadline:
                            raise create_exc
                        time.sleep(0.2)
        addr = ray_tpu.get(proxy.address.remote(), timeout=120)
        if addr is None:
            # never leave a bind-failed proxy registered under the node
            # name — it would shadow every future start attempt
            try:
                ray_tpu.kill(proxy)
            except Exception:
                pass
            raise RuntimeError(
                f"HTTP proxy failed to bind on node {nid[:12]} "
                f"(port {port} in use?)")
        addrs.append((addr[0], addr[1]))
    return addrs


def proxy_addresses():
    """Addresses of every live per-node proxy."""
    import ray_tpu

    out = []
    for node in ray_tpu.nodes():
        try:
            proxy = ray_tpu.get_actor(_proxy_name(node["node_id"]))
            addr = ray_tpu.get(proxy.address.remote(), timeout=30)
            if addr is not None:
                out.append((addr[0], addr[1]))
        except Exception:
            continue
    return out


def shutdown_http():
    import ray_tpu

    for node in ray_tpu.nodes():
        try:
            proxy = ray_tpu.get_actor(_proxy_name(node["node_id"]))
            ray_tpu.kill(proxy)
        except Exception:
            continue
