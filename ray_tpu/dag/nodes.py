"""DAG node types and the dynamic (uncompiled) executor.

A DAG is built driver-side from ``.bind()`` calls and executed either
dynamically — every node becomes a regular task/actor call, refs flow as
arguments — or through ``CompiledDAG`` (compiled.py) which pre-resolves
the actor call chain once and replays it per input.

Reference: python/ray/dag/dag_node.py:1 (DAGNode + traversal),
function_node.py (FunctionNode), class_node.py (ClassNode /
ClassMethodNode), input_node.py (InputNode context manager).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple


def _map_args(obj, fn):
    """Apply fn to every DAGNode inside (nested) args structures."""
    if isinstance(obj, DAGNode):
        return fn(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_map_args(x, fn) for x in obj)
    if isinstance(obj, dict):
        return {k: _map_args(v, fn) for k, v in obj.items()}
    return obj


def _collect_children(args: tuple, kwargs: dict) -> List["DAGNode"]:
    out: List[DAGNode] = []

    def visit(node):
        out.append(node)
        return node

    _map_args(list(args), visit)
    _map_args(dict(kwargs), visit)
    return out


class DAGNode:
    """Base: an operation plus (possibly nested) upstream dependencies."""

    def __init__(self, args: tuple = (), kwargs: Optional[dict] = None):
        self._bound_args = args
        self._bound_kwargs = kwargs or {}
        self._channel_opts: Dict[str, int] = {}

    def with_channel_options(self, *, max_in_flight: Optional[int] = None,
                             buffer_size_bytes: Optional[int] = None
                             ) -> "DAGNode":
        """Per-channel ring overrides for channel-compiled execution.

        On a ClassMethodNode this sizes the node's OUTPUT channel; on an
        InputNode, the driver's input channel.  Unset fields inherit the
        compile-wide ``max_in_flight`` / ``buffer_size_bytes`` — so one
        deep edge (e.g. pipeline activations) can coexist with shallow
        control edges without raising the global ring size.  Returns
        ``self`` for chaining; ignored by dynamic execution."""
        if max_in_flight is not None:
            if max_in_flight < 1:
                raise ValueError("max_in_flight must be >= 1")
            self._channel_opts["max_in_flight"] = int(max_in_flight)
        if buffer_size_bytes is not None:
            if buffer_size_bytes < 1:
                raise ValueError("buffer_size_bytes must be >= 1")
            self._channel_opts["buffer_size_bytes"] = int(buffer_size_bytes)
        return self

    # ------------------------------------------------------------- traversal

    def _children(self) -> List["DAGNode"]:
        return _collect_children(self._bound_args, self._bound_kwargs)

    def topological(self) -> List["DAGNode"]:
        """All reachable nodes, dependencies before dependents, in a
        deterministic order (stable across processes for the same DAG —
        workflow step keys rely on this)."""
        order: List[DAGNode] = []
        seen = set()

        def visit(node: DAGNode):
            if id(node) in seen:
                return
            seen.add(id(node))
            for child in node._children():
                visit(child)
            order.append(node)

        visit(self)
        return order

    # ------------------------------------------------------------- execution

    def execute(self, *input_args, **input_kwargs):
        """Run the DAG dynamically; returns the root's ObjectRef (or a
        list for MultiOutputNode).  Each call creates fresh tasks; actors
        in the DAG are created once per execute."""
        import weakref

        memo: Dict[int, Any] = {}
        order = self.topological()
        for node in order:
            memo[id(node)] = node._apply(memo, input_args, input_kwargs)
        out = memo[id(self)]
        # actors created for this execute must outlive the returned refs:
        # an owning ActorHandle kills its actor on GC, which would fail
        # still-running method tasks.  finalize() pins the handles to the
        # result refs' lifetime.
        handles = [memo[id(n)] for n in order if isinstance(n, ClassNode)]
        if handles:
            for ref in (out if isinstance(out, list) else [out]):
                weakref.finalize(ref, lambda _h: None, tuple(handles))
        return out

    def _apply(self, memo, input_args, input_kwargs):
        raise NotImplementedError

    def _resolved_args(self, memo) -> Tuple[tuple, dict]:
        args = _map_args(list(self._bound_args), lambda n: memo[id(n)])
        kwargs = _map_args(dict(self._bound_kwargs), lambda n: memo[id(n)])
        return tuple(args), kwargs

    # --------------------------------------------------------------- compile

    def experimental_compile(self, max_in_flight: int = 8,
                             use_channels: bool = False,
                             buffer_size_bytes: Optional[int] = None):
        """Freeze this DAG into a replayable plan.

        ``use_channels=False`` (default) returns the dynamic
        :class:`~ray_tpu.dag.compiled.CompiledDAG`: actors are created
        once, but every ``execute()`` still submits real tasks.

        ``use_channels=True`` returns a
        :class:`~ray_tpu.dag.execution.CompiledGraph`: actor-method
        graphs replay over pre-allocated mutable shm channels with a
        pinned per-actor execution loop — no per-call task submission,
        scheduling, or object refs (``execute()`` hands back a
        ``CompiledDAGRef``; call ``.get()`` on it, not ``ray_tpu.get``).
        ``buffer_size_bytes`` overrides the per-version channel payload
        capacity (config ``dag_channel_buffer_bytes``)."""
        if use_channels:
            from ray_tpu.dag.execution import CompiledGraph

            return CompiledGraph(self, max_in_flight=max_in_flight,
                                 buffer_size_bytes=buffer_size_bytes)
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, max_in_flight=max_in_flight)


class InputNode(DAGNode):
    """Placeholder for the runtime input, usable as a context manager:

        with InputNode() as inp:
            dag = f.bind(inp)
        dag.execute(5)
    """

    _tls = threading.local()

    def __init__(self):
        super().__init__()
        self._attrs: Dict[Any, InputAttributeNode] = {}

    def __enter__(self):
        if getattr(self._tls, "active", None) is not None:
            raise RuntimeError("InputNode contexts cannot nest")
        self._tls.active = self
        return self

    def __exit__(self, *exc):
        self._tls.active = None

    def __getitem__(self, key) -> "InputAttributeNode":
        if key not in self._attrs:
            self._attrs[key] = InputAttributeNode(self, key, kind="item")
        return self._attrs[key]

    def __getattr__(self, name) -> "InputAttributeNode":
        if name.startswith("_"):
            raise AttributeError(name)
        key = ("attr", name)
        if key not in self._attrs:
            self._attrs[key] = InputAttributeNode(self, name, kind="attr")
        return self._attrs[key]

    def _apply(self, memo, input_args, input_kwargs):
        if input_kwargs:
            raise TypeError("InputNode DAGs take positional input only; "
                            "use inp.key for structured inputs")
        if len(input_args) != 1:
            raise TypeError(
                f"this DAG expects exactly one input, got {len(input_args)}")
        return input_args[0]


class InputAttributeNode(DAGNode):
    """``inp[0]`` / ``inp.field`` — projects part of the runtime input."""

    def __init__(self, parent: InputNode, key, kind: str):
        super().__init__(args=(parent,))
        self._key = key
        self._kind = kind

    def _apply(self, memo, input_args, input_kwargs):
        value = memo[id(self._bound_args[0])]
        if self._kind == "attr":
            return getattr(value, self._key)
        return value[self._key]


class FunctionNode(DAGNode):
    """``remote_fn.bind(...)`` — a task invocation."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    @property
    def name(self) -> str:
        return self._remote_fn._name

    def _apply(self, memo, input_args, input_kwargs):
        args, kwargs = self._resolved_args(memo)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """``ActorCls.bind(...)`` — an actor to be created at execute time.
    Method bind on a ClassNode yields ClassMethodNodes sharing the
    actor instance within one execute."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundMethod(self, name)

    def _apply(self, memo, input_args, input_kwargs):
        args, kwargs = self._resolved_args(memo)
        return self._actor_cls.remote(*args, **kwargs)


class _UnboundMethod:
    def __init__(self, cls_node: ClassNode, method: str):
        self._cls_node = cls_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._cls_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    """``actor_node.method.bind(...)`` — an actor method invocation."""

    def __init__(self, cls_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._cls_node = cls_node
        self._method = method

    @property
    def name(self) -> str:
        return f"{self._cls_node._actor_cls._cls.__name__}.{self._method}"

    def _children(self):
        return [self._cls_node] + super()._children()

    def _apply(self, memo, input_args, input_kwargs):
        handle = memo[id(self._cls_node)]
        args, kwargs = self._resolved_args(memo)
        return getattr(handle, self._method).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Terminal node returning several leaves: execute() -> list of refs."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(args=(tuple(outputs),))
        self._outputs = list(outputs)

    def _apply(self, memo, input_args, input_kwargs):
        return [memo[id(n)] for n in self._outputs]
