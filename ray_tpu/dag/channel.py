"""Mutable shared-memory channels for compiled DAG execution.

Equivalent role to the reference's accelerated-DAG channels
(reference: python/ray/experimental/channel/shared_memory_channel.py):
a single-writer / multi-reader mutable slot, allocated ONCE at compile
time and reused for every ``execute()``, so the steady state pays zero
object creation, zero scheduler visits, and zero control RPCs per hop.

Layout — one pre-allocated, permanently pinned shm slot holding a
seq-numbered ring of ``max_in_flight`` versions:

    header:
      [u64 magic][u64 flags][u64 max_in_flight][u64 slot_size]
      [u64 n_readers][u64 write_seq][u64 error_len]
      [error region: ERROR_CAP bytes]          (poison payload)
      [cursors: n_readers x u64]               (last seq consumed)
    ring (64-aligned), max_in_flight slots of stride align64(24+slot_size):
      [u64 seq][u64 length][u64 vflags][payload...]

The writer publishes version ``seq`` by writing the payload + version
header into ring slot ``(seq-1) % max_in_flight`` and THEN storing the
header's ``write_seq`` word (an aligned 8-byte store; readers that catch
a torn intermediate state re-validate against the slot's own seq word
and keep polling).  Readers are fan-out: every reader consumes every
version, in order, and advertises progress through its cursor word.
The writer blocks (bounded ring backpressure) until every reader's
cursor clears the slot it is about to overwrite — versions are never
dropped.

Remote readers: the writer knows its reader set at compile time, so
versions are PUSHED — the writer writes the version bytes straight into
the reader node's mirror slot over the PR-4 bulk transfer plane (a
write-flagged range request on the same raw-stream protocol; see
object_transfer.py), then pushes the 8-byte ``write_seq`` word.  No pull
round-trip exists on the data path.  When the bulk plane is unavailable
(no listener, filtered port) the writer falls back to the compat
control-RPC path (``channel_write`` on the reader's node agent), which
is also how agents without a transfer plane interoperate.

Error model: a version can carry ``VF_ERROR`` (payload = pickled
exception) — readers surface it as a value-level error the executor
forwards downstream.  Whole-channel failure (actor death) POISONS the
slot: the flags word plus a pickled exception in the error region; every
blocked reader and writer wakes and raises it.  ``CLOSED`` is the clean
variant used by teardown.
"""

from __future__ import annotations

import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import cloudpickle

from ray_tpu._private.errors import RayError

MAGIC = 0x0052544348414E31  # "RTCHAN1"

# header offsets
_OFF_MAGIC = 0
_OFF_FLAGS = 8
_OFF_MIF = 16
_OFF_SLOT = 24
_OFF_NREADERS = 32
OFF_SEQ = 40          # published write_seq (pushed to mirrors per version)
_OFF_ERRLEN = 48
_OFF_ERR = 56

FLAG_CLOSED = 1
FLAG_POISONED = 2

VF_ERROR = 1  # version payload is a pickled exception

ERROR_CAP = 16384  # poison-payload region size (fixed across the fleet)

_ALIGN = 64
_VHDR = 24  # per-version header: seq, length, vflags


class ChannelError(RayError):
    pass


class ChannelClosedError(ChannelError):
    """The channel was torn down cleanly; no more versions will arrive."""


class ChannelTimeoutError(ChannelError):
    pass


def _align(n: int) -> int:
    return (n + _ALIGN - 1) & ~(_ALIGN - 1)


def _get_u64(view, off: int) -> int:
    return int.from_bytes(view[off:off + 8], "little")


def _put_u64(view, off: int, value: int) -> None:
    view[off:off + 8] = value.to_bytes(8, "little")


@dataclass
class ChannelSpec:
    """Picklable channel descriptor, shared by the driver and every
    participating actor.  The SAME oid names the writer-node slot and
    every reader-node mirror (store entries are per-node)."""

    oid: str
    max_in_flight: int
    slot_size: int                 # payload capacity per version
    n_readers: int
    writer_node: str = ""          # node_id the writer lives on
    reader_nodes: List[str] = field(default_factory=list)  # index -> node_id
    # node_id -> {"agent": [host, port], "xfer_port": int}; covers the
    # writer node and every reader node
    nodes: Dict[str, Dict[str, Any]] = field(default_factory=dict)

    # ---- layout ----------------------------------------------------------

    def cursors_off(self) -> int:
        return _OFF_ERR + ERROR_CAP

    def cursor_off(self, index: int) -> int:
        return self.cursors_off() + 8 * index

    def ring_off(self) -> int:
        return _align(self.cursors_off() + 8 * self.n_readers)

    def stride(self) -> int:
        return _align(_VHDR + self.slot_size)

    def total_size(self) -> int:
        return self.ring_off() + self.max_in_flight * self.stride()

    def slot_off(self, seq: int) -> int:
        return self.ring_off() + ((seq - 1) % self.max_in_flight) * self.stride()

    def header_wire(self) -> Dict[str, int]:
        return {"max_in_flight": self.max_in_flight,
                "slot_size": self.slot_size, "n_readers": self.n_readers,
                "error_cap": ERROR_CAP}


def init_view(view, header: Dict[str, int]) -> None:
    """Initialize a freshly zeroed channel slot's static header fields
    (called on the node that owns the slot, under its store's loop)."""
    _put_u64(view, _OFF_MAGIC, MAGIC)
    _put_u64(view, _OFF_MIF, int(header["max_in_flight"]))
    _put_u64(view, _OFF_SLOT, int(header["slot_size"]))
    _put_u64(view, _OFF_NREADERS, int(header["n_readers"]))


def close_view(view) -> None:
    _put_u64(view, _OFF_FLAGS, _get_u64(view, _OFF_FLAGS) | FLAG_CLOSED)


def poison_view(view, error_bytes: bytes) -> None:
    """Record a pickled exception and wake every blocked party.  The
    error region is written BEFORE the flags word so a reader that
    observes POISONED always finds a complete payload."""
    err = error_bytes[:ERROR_CAP]
    view[_OFF_ERR:_OFF_ERR + len(err)] = err
    _put_u64(view, _OFF_ERRLEN, len(err))
    _put_u64(view, _OFF_FLAGS,
             _get_u64(view, _OFF_FLAGS) | FLAG_POISONED | FLAG_CLOSED)


def pickle_error(exc: BaseException) -> bytes:
    try:
        return cloudpickle.dumps(exc)
    except Exception:
        return cloudpickle.dumps(
            RayError(f"{type(exc).__name__}: {exc}"))


def _raise_poison(view) -> None:
    n = _get_u64(view, _OFF_ERRLEN)
    try:
        exc = pickle.loads(bytes(view[_OFF_ERR:_OFF_ERR + n]))
    except Exception:
        exc = ChannelError("channel poisoned (error payload unreadable)")
    raise exc


# --------------------------------------------------------------------- attach


_io_lock = threading.Lock()
_io_thread = None


def _get_io():
    """An EventLoopThread for RPC fallback clients: the in-process
    worker's IO thread when attached to a cluster, else one lazily
    created module-level thread (channel unit tests, bare agents)."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    if w is not None:
        return w._io
    global _io_thread
    with _io_lock:
        if _io_thread is None:
            from ray_tpu._private.rpc import EventLoopThread

            _io_thread = EventLoopThread(name="rt-dag-channel-io")
        return _io_thread


def attach_local_view(spec: ChannelSpec):
    """Map this process's local copy of the channel slot (writer-node
    slot or reader-node mirror) from the node's shm arena, zero-copy."""
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    if w is None or getattr(w.plasma, "arena", None) is None:
        raise ChannelError(
            "compiled-graph channels need a local shm arena "
            "(client-mode drivers cannot run channel-compiled DAGs)")
    r = w.agent.call("channel_map", oid=spec.oid)
    if not r.get("found"):
        raise ChannelError(f"channel {spec.oid} not present on this node")
    if r["size"] != spec.total_size():
        raise ChannelError(f"channel {spec.oid} size mismatch")
    off = r["offset"]
    view = w.plasma.arena.view[off:off + r["size"]]
    if _get_u64(view, _OFF_MAGIC) != MAGIC:
        raise ChannelError(f"channel {spec.oid} slot has no channel header")
    return view


# ----------------------------------------------------------------- poll loop


def _poll_step(spins: int) -> int:
    """Adaptive wait: burn a few hundred GIL-released-free spins (the
    common case is a peer publishing within microseconds), then sleep
    with exponential backoff capped by dag_channel_poll_max_s."""
    from ray_tpu._private.config import config

    if spins < 200:
        return spins + 1
    delay = min(20e-6 * (1 << min(spins - 200, 7)),
                float(config.dag_channel_poll_max_s))
    time.sleep(delay)
    return spins + 1


# ------------------------------------------------------------- remote target


class _RemoteTarget:
    """Writer-side forwarder to ONE remote reader node: pushes version
    bytes over the bulk transfer plane, falling back permanently to the
    compat control-RPC path on transport failure, and reads the mirror's
    cursor words for backpressure."""

    def __init__(self, spec: ChannelSpec, node_id: str):
        info = spec.nodes[node_id]
        self.spec = spec
        self.node_id = node_id
        self.agent_addr = tuple(info["agent"])
        self.xfer_port = int(info.get("xfer_port") or 0)
        self.bulk_ok = self.xfer_port > 0
        self._xfer = None
        self._rpc = None

    def _client(self):
        if self._xfer is None:
            from ray_tpu._private.object_transfer import ObjectTransferClient

            self._xfer = ObjectTransferClient(self.agent_addr[0],
                                              self.xfer_port)
        return self._xfer

    def _agent(self):
        if self._rpc is None:
            from ray_tpu._private.rpc import SyncRpcClient

            self._rpc = SyncRpcClient(
                self.agent_addr[0], self.agent_addr[1], _get_io(),
                label=f"dag-ch-{self.agent_addr[1]}")
        return self._rpc

    def push_range(self, offset: int, data) -> None:
        """Write `data` at `offset` of the remote mirror slot."""
        from ray_tpu._private.object_transfer import TransferError

        if self.bulk_ok:
            try:
                self._client().write_range(self.spec.oid, offset, data)
                return
            except (TransferError, OSError):
                # bulk listener unreachable while control RPC works:
                # permanently drop to the compat path for this target
                self.bulk_ok = False
        r = self._agent().call("channel_write", oid=self.spec.oid,
                               offset=offset, data=bytes(data))
        if not r.get("ok"):
            raise ChannelError(
                f"channel {self.spec.oid[:16]} write rejected by "
                f"{self.agent_addr}: {r.get('error')}")

    def push_version(self, view, base: int, length: int) -> None:
        self.push_range(base, view[base:base + length])
        self.push_range(OFF_SEQ, view[OFF_SEQ:OFF_SEQ + 8])

    def read_cursors(self) -> bytes:
        from ray_tpu._private.object_transfer import TransferError

        off = self.spec.cursors_off()
        n = 8 * self.spec.n_readers
        if self.bulk_ok:
            try:
                return bytes(self._client().read_range(self.spec.oid, off, n))
            except (TransferError, OSError):
                self.bulk_ok = False
        r = self._agent().call("channel_read", oid=self.spec.oid,
                               offset=off, length=n)
        if not r.get("ok"):
            raise ChannelError(
                f"channel {self.spec.oid[:16]} cursor read failed: "
                f"{r.get('error')}")
        return bytes(r["data"])

    def close(self) -> None:
        if self._xfer is not None:
            self._xfer.close()
        if self._rpc is not None:
            try:
                self._rpc.close()
            except Exception:
                pass


# -------------------------------------------------------------------- writer


class ChannelWriter:
    """Single writer of a channel.  Not thread-safe (one writer by
    contract).  `view` injection is for node-local tests/agents; normal
    use attaches through the local arena."""

    def __init__(self, spec: ChannelSpec, view=None):
        self.spec = spec
        self._view = view if view is not None else attach_local_view(spec)
        self._seq = _get_u64(self._view, OFF_SEQ)
        self._targets = [
            _RemoteTarget(spec, nid)
            for nid in dict.fromkeys(spec.reader_nodes)
            if nid != spec.writer_node and nid in spec.nodes]
        # reader cursors last fetched from remote mirrors (by index)
        self._remote_cache: Dict[int, int] = {
            i: 0 for i, nid in enumerate(spec.reader_nodes)
            if nid != spec.writer_node}
        self._target_by_node = {t.node_id: t for t in self._targets}

    @property
    def seq(self) -> int:
        return self._seq

    def _check_flags(self) -> None:
        flags = _get_u64(self._view, _OFF_FLAGS)
        if flags & FLAG_POISONED:
            _raise_poison(self._view)
        if flags & FLAG_CLOSED:
            raise ChannelClosedError(
                f"channel {self.spec.oid[:16]} is closed")

    def _min_cursor(self, refresh_remote: bool) -> int:
        if refresh_remote and self._remote_cache:
            for t in self._targets:
                raw = t.read_cursors()
                for i, nid in enumerate(self.spec.reader_nodes):
                    if nid == t.node_id:
                        self._remote_cache[i] = int.from_bytes(
                            raw[8 * i:8 * i + 8], "little")
        lo = None
        for i, nid in enumerate(self.spec.reader_nodes):
            if nid == self.spec.writer_node:
                cur = _get_u64(self._view, self.spec.cursor_off(i))
            else:
                cur = self._remote_cache[i]
            lo = cur if lo is None else min(lo, cur)
        return 0 if lo is None else lo

    def _wait_space(self, seq: int, timeout: Optional[float],
                    check: Optional[Callable[[], None]]) -> None:
        need = seq - self.spec.max_in_flight  # every cursor must reach this
        if need <= 0:
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        refresh = bool(self._remote_cache)
        while True:
            self._check_flags()
            if self._min_cursor(refresh_remote=refresh and spins > 0) >= need:
                return
            if check is not None:
                check()
            if deadline is not None and time.monotonic() >= deadline:
                raise ChannelTimeoutError(
                    f"channel {self.spec.oid[:16]} ring full for "
                    f"{timeout:.3f}s (slow reader; backpressure)")
            spins = _poll_step(spins)

    def write(self, value: Any, *, error: bool = False,
              timeout: Optional[float] = None,
              check: Optional[Callable[[], None]] = None) -> int:
        """Publish the next version; blocks under ring backpressure.
        With error=True, `value` is an exception to serialize into the
        version (readers surface it instead of a value)."""
        from ray_tpu._private import serialization
        from ray_tpu._private.metrics import (dag_channel_occupancy_gauge,
                                              dag_metrics)

        if error:
            frames = [memoryview(pickle_error(value))]
            total = frames[0].nbytes
            packed = False
        else:
            frames, total = serialization.serialize(value)
            packed = True
        if total > self.spec.slot_size:
            raise ChannelError(
                f"serialized value ({total} B) exceeds the channel slot "
                f"({self.spec.slot_size} B); recompile with a larger "
                f"buffer_size_bytes or raise dag_channel_buffer_bytes")
        seq = self._seq + 1
        self._check_flags()  # closed/poisoned channels reject writes even
        # when the ring has space (wait_space may not poll at all)
        self._wait_space(seq, timeout, check)
        view = self._view
        base = self.spec.slot_off(seq)
        if packed:
            serialization.pack_into(frames, view[base + _VHDR:
                                                 base + _VHDR + total])
        else:
            view[base + _VHDR:base + _VHDR + total] = frames[0]
        _put_u64(view, base + 8, total)
        _put_u64(view, base + 16, VF_ERROR if error else 0)
        _put_u64(view, base, seq)
        _put_u64(view, OFF_SEQ, seq)  # publish: local readers wake now
        self._seq = seq
        for t in self._targets:
            t.push_version(view, base, _VHDR + total)
        dag_metrics()[1].inc(tags={"op": "write"})
        # ring occupancy = published versions the slowest reader hasn't
        # consumed (cached cursors; no remote refresh on the hot path).
        # Pinned at max_in_flight == this stage's readers are the
        # pipeline bottleneck.
        dag_channel_occupancy_gauge().set(
            seq - self._min_cursor(refresh_remote=False),
            tags={"channel": self.spec.oid[:12]})
        return seq

    def close(self, propagate: bool = True) -> None:
        close_view(self._view)
        if propagate:
            for t in self._targets:
                try:
                    t.push_range(_OFF_FLAGS,
                                 self._view[_OFF_FLAGS:_OFF_FLAGS + 8])
                except Exception:
                    pass

    def poison(self, error_bytes: bytes, propagate: bool = True) -> None:
        poison_view(self._view, error_bytes)
        if propagate:
            end = _OFF_ERR + min(len(error_bytes), ERROR_CAP)
            for t in self._targets:
                try:
                    # error region + errlen first, flags last (ordering
                    # within one stream/RPC sequence)
                    t.push_range(_OFF_ERRLEN, self._view[_OFF_ERRLEN:end])
                    t.push_range(_OFF_FLAGS,
                                 self._view[_OFF_FLAGS:_OFF_FLAGS + 8])
                except Exception:
                    pass

    def detach(self) -> None:
        for t in self._targets:
            t.close()


# -------------------------------------------------------------------- reader


class ChannelReader:
    """One fan-out reader of a channel; reads versions strictly in
    order.  `advance(seq)` releases the slot back to the writer — call
    it only once the read value is no longer needed (zero-copy reads
    alias the ring memory)."""

    def __init__(self, spec: ChannelSpec, index: int, view=None):
        if not (0 <= index < spec.n_readers):
            raise ValueError(f"reader index {index} out of range")
        self.spec = spec
        self.index = index
        self._view = view if view is not None else attach_local_view(spec)
        self.consumed = _get_u64(self._view, spec.cursor_off(index))

    @property
    def next_seq(self) -> int:
        return self.consumed + 1

    def read(self, seq: int, timeout: Optional[float] = None,
             check: Optional[Callable[[], None]] = None,
             copy: bool = False) -> Tuple[Any, bool]:
        """Block until version `seq` is published; returns (value,
        is_error).  copy=True detaches the payload from the ring before
        deserializing (driver-side reads, where the value escapes to
        user code that may outlive the slot)."""
        from ray_tpu._private import serialization
        from ray_tpu._private.metrics import dag_metrics

        view = self._view
        spec = self.spec
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        base = spec.slot_off(seq)
        while True:
            if _get_u64(view, OFF_SEQ) >= seq \
                    and _get_u64(view, base) == seq:
                break
            flags = _get_u64(view, _OFF_FLAGS)
            if flags & FLAG_POISONED:
                _raise_poison(view)
            if flags & FLAG_CLOSED and _get_u64(view, OFF_SEQ) < seq:
                raise ChannelClosedError(
                    f"channel {spec.oid[:16]} closed before version {seq}")
            if check is not None:
                check()
            if deadline is not None and time.monotonic() >= deadline:
                raise ChannelTimeoutError(
                    f"timed out waiting for channel {spec.oid[:16]} "
                    f"version {seq}")
            spins = _poll_step(spins)
        length = _get_u64(view, base + 8)
        vflags = _get_u64(view, base + 16)
        payload = view[base + _VHDR:base + _VHDR + length]
        dag_metrics()[1].inc(tags={"op": "read"})
        if vflags & VF_ERROR:
            return pickle.loads(bytes(payload)), True
        if copy:
            payload = memoryview(bytes(payload))
        return serialization.deserialize(payload), False

    def advance(self, seq: int) -> None:
        if seq > self.consumed:
            self.consumed = seq
            _put_u64(self._view, self.spec.cursor_off(self.index), seq)
