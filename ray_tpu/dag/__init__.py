"""DAG authoring and execution.

Equivalent of the reference's ``ray.dag`` (reference:
python/ray/dag/dag_node.py:1, compiled_dag_node.py:174).

Build a graph driver-side with ``.bind()``:

    with InputNode() as inp:
        dag = post.process.bind(model.infer.bind(prep.load.bind(inp)))

then run it one of three ways, in increasing order of per-call cost
removed:

* ``dag.execute(x)`` — **dynamic**: every node becomes a regular
  task/actor call and refs flow as arguments.  Fresh actors per call;
  full scheduling per node.  Works for any mix of FunctionNodes and
  actor methods.
* ``dag.experimental_compile()`` — **dynamic replay**
  (:class:`~ray_tpu.dag.compiled.CompiledDAG`): actors and their
  constructor dependencies resolve once at compile time; each
  ``execute()`` still submits real tasks, pipelined up to
  ``max_in_flight`` with backpressure.  Returns normal ObjectRefs
  (use ``ray_tpu.get``).
* ``dag.experimental_compile(use_channels=True)`` — **channel-compiled**
  (:class:`~ray_tpu.dag.execution.CompiledGraph`): actor-method graphs
  only.  Compilation pre-allocates one mutable shared-memory channel
  (:mod:`ray_tpu.dag.channel`) per cross-process edge and pins a
  persistent execution loop inside every actor; ``execute()`` writes
  the input channel and returns a
  :class:`~ray_tpu.dag.execution.CompiledDAGRef` whose ``.get()`` reads
  the output channel — zero task specs, scheduler visits, or object
  refs per call.  Remote readers get versions pushed over the bulk
  transfer plane.  ``node.with_channel_options(max_in_flight=…,
  buffer_size_bytes=…)`` overrides one edge's ring depth/payload
  capacity (deep data edges + shallow control edges in one graph —
  the MPMD training pipeline in ``train/pipeline.py`` rides this
  sizing model).  Errors serialize into channel versions and re-raise
  from ``.get()``; actor death poisons the pipeline (bounded by
  ``dag_monitor_interval_s``) instead of hanging it; ``teardown()`` is
  synchronous and idempotent.

Exports: ``DAGNode`` (base), ``FunctionNode`` (``fn.bind``),
``ClassNode`` (``Actor.bind``), ``ClassMethodNode``
(``actor_node.method.bind``), ``InputNode`` / ``InputAttributeNode``
(runtime input and its projections), ``MultiOutputNode`` (multi-leaf
root), ``CompiledDAG`` (dynamic replay), ``CompiledGraph`` /
``CompiledDAGRef`` (channel-compiled execution).
"""

from ray_tpu.dag.nodes import (ClassMethodNode, ClassNode, DAGNode,
                               FunctionNode, InputAttributeNode, InputNode,
                               MultiOutputNode)
from ray_tpu.dag.compiled import CompiledDAG
from ray_tpu.dag.execution import CompiledDAGRef, CompiledGraph

__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "InputNode", "InputAttributeNode", "MultiOutputNode",
           "CompiledDAG", "CompiledGraph", "CompiledDAGRef"]
