"""DAG authoring API: build task/actor graphs with ``.bind()``, run them
lazily with ``.execute()``, or compile them (``experimental_compile``)
into a reusable pipeline over pre-allocated object channels.

Equivalent of the reference's ``ray.dag``
(reference: python/ray/dag/dag_node.py:1, function_node.py,
class_node.py, input_node.py, compiled_dag_node.py:174).
"""

from ray_tpu.dag.nodes import (ClassMethodNode, ClassNode, DAGNode,
                               FunctionNode, InputAttributeNode, InputNode,
                               MultiOutputNode)
from ray_tpu.dag.compiled import CompiledDAG

__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "InputNode", "InputAttributeNode", "MultiOutputNode",
           "CompiledDAG"]
