"""Compiled DAG: freeze a bound task/actor graph into a replayable plan.

Equivalent of the reference's accelerated DAG
(reference: python/ray/dag/compiled_dag_node.py:174).  The reference
pre-allocates mutable plasma channels between GPU actors and replays
the graph without per-call scheduling.  Here compilation:

  * creates every ``ClassNode`` actor exactly once (dynamic ``execute``
    re-creates them per call);
  * exports every task function/actor class once so replays skip the
    function-table round trip;
  * pipelines successive ``execute`` calls up to ``max_in_flight``
    before applying backpressure — the driver can keep a TPU serving
    pipeline full without unbounded queue growth.

Cross-actor data still flows through the object store (refs as task
args, owner-resolved), which on TPU is the right substrate: device
arrays stay device-side inside each actor's jitted step and only
host-level handles cross the wire.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.dag.nodes import (ClassNode, DAGNode, InputNode,
                               MultiOutputNode)


class CompiledDAG:
    def __init__(self, root: DAGNode, max_in_flight: int = 8):
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        self._root = root
        self._order = root.topological()
        self._max_in_flight = max_in_flight
        self._in_flight: List[Any] = []
        self._torn_down = False
        inputs = [n for n in self._order if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG can reference at most one InputNode")
        # actors AND their constructor dependencies are part of the
        # compiled plan: resolved once at compile, reused every execute
        # (re-running a constructor dep per call would repeat its work
        # and side effects)
        self._plan_memo: Dict[int, Any] = {}
        self._actors: Dict[int, Any] = {}
        for node in self._order:
            if isinstance(node, ClassNode):
                for dep in node.topological():
                    if id(dep) not in self._plan_memo:
                        if isinstance(dep, (InputNode, MultiOutputNode)):
                            raise ValueError(
                                "actor constructor args cannot depend on "
                                "the runtime input")
                        self._plan_memo[id(dep)] = dep._apply(
                            self._plan_memo, (), {})
                self._actors[id(node)] = self._plan_memo[id(node)]

    def execute(self, *input_args):
        """Submit one traversal; returns the root ref (or list of refs).
        Blocks only when ``max_in_flight`` prior executions are still
        unfinished."""
        import ray_tpu

        if self._torn_down:
            raise ray_tpu.RayError(
                "this CompiledDAG has been torn down; rebuild and "
                "recompile the DAG to execute again")
        self._apply_backpressure(ray_tpu)
        memo: Dict[int, Any] = dict(self._plan_memo)
        for node in self._order:
            if id(node) not in memo:
                memo[id(node)] = node._apply(memo, input_args, {})
        out = memo[id(self._root)]
        # one in-flight *group* per execute: every output ref counts, so
        # a slow branch of a MultiOutputNode still exerts backpressure
        self._in_flight.append(list(out) if isinstance(out, list) else [out])
        return out

    def _apply_backpressure(self, ray_tpu):
        # drop groups whose every ref already finished
        if self._in_flight:
            flat = [r for g in self._in_flight for r in g]
            ready, _ = ray_tpu.wait(flat, num_returns=len(flat), timeout=0)
            done = set(ready)
            self._in_flight = [g for g in self._in_flight
                               if not all(r in done for r in g)]
        while len(self._in_flight) >= self._max_in_flight:
            oldest = self._in_flight[0]
            # short wait rounds instead of one 300s block: a DAG actor
            # dying mid-pipeline resolves the oldest group's refs with
            # ActorDiedError, which must surface here — silently
            # re-blocking would wedge the caller for minutes per round
            ready, _ = ray_tpu.wait(oldest, num_returns=len(oldest),
                                    timeout=1.0)
            if len(ready) < len(oldest):
                continue  # stragglers: the cap stays real, block again
            self._in_flight.pop(0)
            try:
                ray_tpu.get(ready, timeout=0)
            except ray_tpu.ActorDiedError:
                raise
            except Exception:
                # app-level task errors keep dynamic-execute semantics:
                # they surface at the caller's own get(), not here
                pass

    def teardown(self, timeout: float = 10.0):
        """Kill the plan's actors and wait for them to die.  Synchronous
        and idempotent: a second call (or a call after the actors have
        already crashed) is a no-op, and ``execute()`` afterwards raises
        instead of replaying over dead actors."""
        if self._torn_down:
            return
        self._torn_down = True
        import time as _time

        import ray_tpu
        from ray_tpu import api as _api

        from ray_tpu._private.rpc import ConnectionLost, RpcError

        actors, self._actors = self._actors, {}
        for handle in actors.values():
            try:
                ray_tpu.kill(handle)
            except (ray_tpu.RayError, RpcError, ConnectionLost, OSError):
                pass  # already dead / cluster shutting down
        w = _api._worker()
        deadline = _time.monotonic() + timeout
        for handle in actors.values():
            while _time.monotonic() < deadline:
                try:
                    info = w.head.call("get_actor_info",
                                       actor_id=handle._actor_id)
                except Exception:
                    return  # head unreachable: nothing left to wait on
                if info.get("state") == "DEAD":
                    break
                _time.sleep(0.05)
