"""Compiled DAG: freeze a bound task/actor graph into a replayable plan.

Equivalent of the reference's accelerated DAG
(reference: python/ray/dag/compiled_dag_node.py:174).  The reference
pre-allocates mutable plasma channels between GPU actors and replays
the graph without per-call scheduling.  Here compilation:

  * creates every ``ClassNode`` actor exactly once (dynamic ``execute``
    re-creates them per call);
  * exports every task function/actor class once so replays skip the
    function-table round trip;
  * pipelines successive ``execute`` calls up to ``max_in_flight``
    before applying backpressure — the driver can keep a TPU serving
    pipeline full without unbounded queue growth.

Cross-actor data still flows through the object store (refs as task
args, owner-resolved), which on TPU is the right substrate: device
arrays stay device-side inside each actor's jitted step and only
host-level handles cross the wire.
"""

from __future__ import annotations

from typing import Any, Dict, List

from ray_tpu.dag.nodes import (ClassNode, DAGNode, InputNode,
                               MultiOutputNode)


class CompiledDAG:
    def __init__(self, root: DAGNode, max_in_flight: int = 8):
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        self._root = root
        self._order = root.topological()
        self._max_in_flight = max_in_flight
        self._in_flight: List[Any] = []
        inputs = [n for n in self._order if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG can reference at most one InputNode")
        # actors AND their constructor dependencies are part of the
        # compiled plan: resolved once at compile, reused every execute
        # (re-running a constructor dep per call would repeat its work
        # and side effects)
        self._plan_memo: Dict[int, Any] = {}
        self._actors: Dict[int, Any] = {}
        for node in self._order:
            if isinstance(node, ClassNode):
                for dep in node.topological():
                    if id(dep) not in self._plan_memo:
                        if isinstance(dep, (InputNode, MultiOutputNode)):
                            raise ValueError(
                                "actor constructor args cannot depend on "
                                "the runtime input")
                        self._plan_memo[id(dep)] = dep._apply(
                            self._plan_memo, (), {})
                self._actors[id(node)] = self._plan_memo[id(node)]

    def execute(self, *input_args):
        """Submit one traversal; returns the root ref (or list of refs).
        Blocks only when ``max_in_flight`` prior executions are still
        unfinished."""
        import ray_tpu

        self._apply_backpressure(ray_tpu)
        memo: Dict[int, Any] = dict(self._plan_memo)
        for node in self._order:
            if id(node) not in memo:
                memo[id(node)] = node._apply(memo, input_args, {})
        out = memo[id(self._root)]
        # one in-flight *group* per execute: every output ref counts, so
        # a slow branch of a MultiOutputNode still exerts backpressure
        self._in_flight.append(list(out) if isinstance(out, list) else [out])
        return out

    def _apply_backpressure(self, ray_tpu):
        # drop groups whose every ref already finished
        if self._in_flight:
            flat = [r for g in self._in_flight for r in g]
            ready, _ = ray_tpu.wait(flat, num_returns=len(flat), timeout=0)
            done = set(ready)
            self._in_flight = [g for g in self._in_flight
                               if not all(r in done for r in g)]
        while len(self._in_flight) >= self._max_in_flight:
            oldest = self._in_flight[0]
            ray_tpu.wait(oldest, num_returns=len(oldest), timeout=300)
            ready, _ = ray_tpu.wait(oldest, num_returns=len(oldest),
                                    timeout=0)
            if len(ready) == len(oldest):
                self._in_flight.pop(0)
            # else: stragglers past the wait timeout — keep the group so
            # the cap stays real, and block again

    def teardown(self):
        """Kill the plan's actors."""
        import ray_tpu

        for handle in self._actors.values():
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
        self._actors.clear()
