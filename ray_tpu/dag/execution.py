"""Compiled-graph execution: pinned actor loops over mutable channels.

Equivalent of the reference's accelerated DAG execution
(reference: python/ray/dag/compiled_dag_node.py:174 — CompiledDAG
`_execute_until` / the per-actor `do_exec_tasks` loop): compilation
creates the DAG's actors once, pre-allocates a mutable channel
(channel.py) per cross-process edge, and installs ONE persistent
execution-loop task per actor.  The loop blocks on its input channels,
runs its bound methods, writes its output channels, and repeats —
steady-state ``execute()`` involves **no task spec, no scheduler visit,
no new object refs**: the driver writes the input channel and hands
back a :class:`CompiledDAGRef` that reads the output channel, with
backpressure from the bounded version ring.

Error model:
  * a method raising inside the loop serializes the exception into its
    output channel version; downstream nodes forward it and
    ``CompiledDAGRef.get()`` re-raises it;
  * actor death fails the actor's loop-task ref; a driver-side monitor
    observes that within ``dag_monitor_interval_s`` and POISONS every
    channel (writer-node slots and mirrors), so all in-flight
    ``get()``/``execute()`` calls raise (``ActorDiedError``) instead of
    hanging;
  * ``teardown()`` is synchronous and idempotent: channels close, loops
    drain and exit, actors are killed and waited on, slots are freed.

Observability: every execute emits a ``dag.execute`` trace span (and
``get`` completion a ``dag.get`` span) through the PR-2 tracing store,
and the driver observes ``ray_tpu_dag_execute_latency_seconds`` per
result; the channels count reads/writes in
``ray_tpu_dag_channel_ops_total``.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.dag import channel as ch
from ray_tpu.dag.nodes import (ClassMethodNode, ClassNode, DAGNode,
                               FunctionNode, InputAttributeNode, InputNode,
                               MultiOutputNode, _map_args)
from ray_tpu._private.errors import ActorDiedError, RayError

# special actor-method names dispatched by the worker's executor to this
# module (see CoreWorker._execute_inner) — they must start with an
# underscore so ActorHandle.__getattr__ can never shadow user methods
DAG_EXEC_METHOD = "__rt_dag_exec_loop__"
DAG_INFO_METHOD = "__rt_dag_node_info__"

_INPUT_KEY = "__input__"

# channel slots claimed by live (compiled, not-yet-torn-down) graphs in
# this process, keyed by graph identity.  The worker's memory summary
# reports them so the head's channel-leak tripwire can tell a slot a
# running pipeline still owns from one a dead/teardown-skipped graph
# left pinned in the store forever.
_live_channels: Dict[int, List[str]] = {}
_live_channels_lock = threading.Lock()


def live_channel_oids() -> List[str]:
    with _live_channels_lock:
        return [oid for oids in _live_channels.values() for oid in oids]


def _register_live_channels(graph_key: int, oids: List[str]) -> None:
    with _live_channels_lock:
        _live_channels[graph_key] = list(dict.fromkeys(oids))


def _unregister_live_channels(graph_key: int) -> None:
    with _live_channels_lock:
        _live_channels.pop(graph_key, None)


class _ArgRef:
    """Marker inside a step's arg template: replaced at loop runtime by
    the execute input, a projection of it, or another node's result."""

    __slots__ = ("kind", "key")

    def __init__(self, kind: str, key=None):
        self.kind = kind  # "input" | "input_attr" | "node"
        self.key = key

    def __reduce__(self):
        return (_ArgRef, (self.kind, self.key))

    def __repr__(self):
        return f"_ArgRef({self.kind}, {self.key!r})"


class _ErrValue:
    """An upstream error flowing through the loop's value context."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


# --------------------------------------------------------------- worker side


_node_info_cache: Optional[Dict[str, Any]] = None


def collect_node_info(worker) -> Dict[str, Any]:
    """Executed as a (special) actor task during compile: report where
    this actor lives so the driver can place channel slots and mirrors."""
    global _node_info_cache
    if _node_info_cache is None:
        try:
            xfer_port = int(worker.agent.call("node_info").get(
                "xfer_port") or 0)
        except Exception:
            xfer_port = 0
        _node_info_cache = {"node_id": worker.node_id,
                            "agent": list(worker.agent_addr),
                            "xfer_port": xfer_port}
    return dict(_node_info_cache)


def _resolve_template(template, ctx: Dict[str, Any]):
    """Substitute _ArgRef markers; returns (value, first_error|None)."""
    err: List[BaseException] = []

    def sub(obj):
        if isinstance(obj, _ArgRef):
            if obj.kind == "input":
                val = ctx[_INPUT_KEY]
            elif obj.kind == "input_attr":
                val = ctx[_INPUT_KEY]
                if not isinstance(val, _ErrValue):
                    kind, key = obj.key
                    val = getattr(val, key) if kind == "attr" else val[key]
            else:
                val = ctx[obj.key]
            if isinstance(val, _ErrValue) and not err:
                err.append(val.exc)
            return val
        if isinstance(obj, (list, tuple)):
            return type(obj)(sub(x) for x in obj)
        if isinstance(obj, dict):
            return {k: sub(v) for k, v in obj.items()}
        return obj

    out = sub(template)
    return out, (err[0] if err else None)


def _write_result(writer: ch.ChannelWriter, result: Any) -> None:
    """Publish one node result, degrading VALUE-level write failures
    (unserializable result, value larger than the channel slot) to an
    error version — only channel-level failures (closed/poisoned,
    transport death) may escape and take the loop down."""
    if isinstance(result, _ErrValue):
        writer.write(result.exc, error=True)
        return
    try:
        writer.write(result)
    except (ch.ChannelClosedError, ch.ChannelTimeoutError):
        raise
    except ch.ChannelError as e:  # e.g. oversized value
        writer.write(e, error=True)
    except Exception as e:
        from ray_tpu._private.serialization import SerializationError

        if not isinstance(e, SerializationError):
            raise
        writer.write(e, error=True)


def run_actor_loop(worker, instance, plan: Dict[str, Any]) -> Dict[str, Any]:
    """The pinned per-actor execution loop (runs ON the actor's exec
    thread, occupying it until the DAG is torn down).

    Equivalent of the reference's ``do_exec_tasks``
    (reference: python/ray/dag/compiled_dag_node.py:129): one blocking
    iteration per execute — read every input channel version, run this
    actor's bound methods in topological order, write output channels,
    then release the input slots."""
    readers: List[Tuple[ch.ChannelReader, str]] = [
        (ch.ChannelReader(ch.ChannelSpec(**r["spec"]), r["index"]), r["key"])
        for r in plan["inputs"]]
    writers: List[Tuple[str, ch.ChannelWriter]] = [
        (o["key"], ch.ChannelWriter(ch.ChannelSpec(**o["spec"])))
        for o in plan["outputs"]]
    steps = plan["steps"]
    seq = 0
    iterations = 0
    try:
        while True:
            seq += 1
            ctx: Dict[str, Any] = {}
            try:
                for reader, key in readers:
                    value, is_err = reader.read(seq)
                    ctx[key] = _ErrValue(value) if is_err else value
                for step in steps:
                    try:
                        args, err = _resolve_template(step["args"], ctx)
                        kwargs, kerr = _resolve_template(step["kwargs"],
                                                         ctx)
                        err = err or kerr
                    except Exception as e:  # bad input projection etc.
                        err = e
                    if err is not None:
                        ctx[step["key"]] = _ErrValue(err)
                        continue
                    try:
                        ctx[step["key"]] = getattr(
                            instance, step["method"])(*args, **kwargs)
                    except Exception as e:  # noqa: BLE001 — serialized
                        ctx[step["key"]] = _ErrValue(e)
                for key, writer in writers:
                    _write_result(writer, ctx[key])
                # inputs released only now: zero-copy reads alias the
                # ring until the iteration's compute and writes finish
                for reader, _key in readers:
                    reader.advance(seq)
                iterations += 1
            except ch.ChannelClosedError:
                break
    finally:
        # teardown (or failure): closing our outputs wakes downstream
        # loops so shutdown propagates along the pipeline
        for _key, writer in writers:
            try:
                writer.close()
            except Exception:
                pass
            writer.detach()
    return {"iterations": iterations}


# --------------------------------------------------------------- driver side


class ChannelHost:
    """Driver-side owner of a set of channel slots spread over nodes:
    allocates the writer-node slot + reader-node mirrors for each spec,
    and provides fleet-wide poison/destroy for failure and teardown.

    Shared by :class:`CompiledGraph` and the MPMD training pipeline
    (train/pipeline.py) — both need identical slot lifecycle handling
    (create on every involved node, poison on death, destroy on
    teardown, pooled agent clients)."""

    def __init__(self):
        self._agent_clients: Dict[tuple, Any] = {}
        self._created: List[Tuple[tuple, str]] = []

    def agent(self, addr) -> Any:
        from ray_tpu import api as _api
        from ray_tpu._private.rpc import SyncRpcClient

        addr = tuple(addr)
        w = _api._worker()
        if addr == tuple(w.agent_addr):
            return w.agent
        client = self._agent_clients.get(addr)
        if client is None:
            client = SyncRpcClient(addr[0], addr[1], w._io,
                                   label=f"dag-agent-{addr[1]}")
            self._agent_clients[addr] = client
        return client

    def create(self, spec: ch.ChannelSpec) -> None:
        """Allocate the slot on the writer node and a mirror on every
        distinct reader node."""
        for node_id in dict.fromkeys([spec.writer_node]
                                     + spec.reader_nodes):
            agent = self.agent(spec.nodes[node_id]["agent"])
            agent.call("channel_create", oid=spec.oid,
                       size=spec.total_size(),
                       header=spec.header_wire())
            self._created.append(
                (tuple(spec.nodes[node_id]["agent"]), spec.oid))

    def oids(self) -> List[str]:
        return [oid for _addr, oid in self._created]

    def for_each_slot(self, fn) -> None:
        for addr, oid in self._created:
            try:
                fn(self.agent(addr), oid)
            except Exception:
                pass

    def poison_all(self, error_bytes: bytes = b"",
                   close_only: bool = False) -> None:
        self.for_each_slot(lambda agent, oid: agent.call(
            "channel_poison", oid=oid, error=error_bytes,
            close_only=close_only))

    def destroy_all(self) -> None:
        self.for_each_slot(lambda agent, oid: agent.call(
            "channel_destroy", oid=oid))
        self._created.clear()

    def close(self) -> None:
        for client in self._agent_clients.values():
            try:
                client.close()
            except Exception:
                pass
        self._agent_clients.clear()


class CompiledDAGRef:
    """Result handle for one ``execute()``: reads the output channel
    version instead of resolving an object ref.  ``get()`` may be
    called more than once (the value is cached) and out of submission
    order (earlier versions are read through and cached on the DAG)."""

    __slots__ = ("_dag", "seq", "_value", "_have")

    def __init__(self, dag: "CompiledGraph", seq: int):
        self._dag = dag
        self.seq = seq
        self._value = None
        self._have = False

    def get(self, timeout: Optional[float] = None):
        if not self._have:
            self._value = self._dag._result(self.seq, timeout)
            self._have = True
        return self._value

    def __repr__(self):
        return f"CompiledDAGRef(seq={self.seq})"


class CompiledGraph:
    """A frozen actor-method DAG replayed over pre-allocated channels.

    Build with ``dag.experimental_compile(use_channels=True)``.  Only
    actor-method graphs compile (ClassMethodNodes over ClassNodes, plus
    an optional InputNode and a MultiOutputNode root); task
    (FunctionNode) graphs keep using dynamic execution or the dynamic
    :class:`~ray_tpu.dag.compiled.CompiledDAG`.
    """

    def __init__(self, root: DAGNode, max_in_flight: int = 8,
                 buffer_size_bytes: Optional[int] = None,
                 compile_timeout: float = 120.0):
        from ray_tpu._private.config import config

        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        self._root = root
        self._max_in_flight = max_in_flight
        self._buffer = int(buffer_size_bytes
                           or config.dag_channel_buffer_bytes)
        self._dag_id = uuid.uuid4().hex[:12]
        self._torn_down = False
        self._teardown_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._next_seq = 1
        self._exec_started: Dict[int, float] = {}
        self._out_cache: Dict[int, Any] = {}
        self._channels = ChannelHost()
        self._monitor_stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._in_writer: Optional[ch.ChannelWriter] = None
        self._loop_refs: Dict[int, Any] = {}
        self._plan(root)
        try:
            self._setup(compile_timeout)
        except BaseException:
            # half-built pipelines must not leak pinned slots or actors
            try:
                self.teardown(timeout=5.0)
            except Exception:
                pass
            raise

    # ------------------------------------------------------------- planning

    def _plan(self, root: DAGNode) -> None:
        order = root.topological()
        inputs = [n for n in order if isinstance(n, InputNode)]
        if len(inputs) > 1:
            raise ValueError("a DAG can reference at most one InputNode")
        self._input_node = inputs[0] if inputs else None

        # actors and their constructor dependencies resolve ONCE at
        # compile (same plan-memo rule as the dynamic CompiledDAG)
        self._plan_memo: Dict[int, Any] = {}
        self._actors: Dict[int, Any] = {}       # id(ClassNode) -> handle
        self._class_nodes: Dict[int, ClassNode] = {}
        ctor_nodes: set = set()
        for node in order:
            if isinstance(node, ClassNode):
                for dep in node.topological():
                    ctor_nodes.add(id(dep))
                    if id(dep) not in self._plan_memo:
                        if isinstance(dep, (InputNode, InputAttributeNode,
                                            MultiOutputNode)):
                            raise ValueError(
                                "actor constructor args cannot depend on "
                                "the runtime input")
                        self._plan_memo[id(dep)] = dep._apply(
                            self._plan_memo, (), {})
                self._actors[id(node)] = self._plan_memo[id(node)]
                self._class_nodes[id(node)] = node

        self._method_nodes: List[ClassMethodNode] = []
        for node in order:
            if isinstance(node, ClassMethodNode) \
                    and id(node) not in ctor_nodes:
                self._method_nodes.append(node)
            elif isinstance(node, FunctionNode) and id(node) not in ctor_nodes:
                raise ValueError(
                    "channel-compiled DAGs support actor-method graphs "
                    "only; FunctionNode tasks need dynamic execute() or "
                    "experimental_compile() without use_channels")
        if not self._method_nodes:
            raise ValueError("nothing to compile: the DAG has no actor "
                             "method calls")
        terminal = (root._outputs if isinstance(root, MultiOutputNode)
                    else [root])
        for leaf in terminal:
            if not isinstance(leaf, ClassMethodNode):
                raise ValueError(
                    "compiled-graph outputs must be actor method calls")
        self._terminal = terminal
        self._multi_output = isinstance(root, MultiOutputNode)

        # node keys + per-actor step lists (topological order per actor)
        self._node_key = {id(n): f"n{i}" for i, n in
                          enumerate(self._method_nodes)}
        steps_of: Dict[int, List[ClassMethodNode]] = {}
        for node in self._method_nodes:
            steps_of.setdefault(id(node._cls_node), []).append(node)
        self._steps_of = steps_of

        # cross-process consumers of each method node
        consumers: Dict[int, set] = {id(n): set() for n in self._method_nodes}
        self._uses_input: Dict[int, bool] = {}
        for node in self._method_nodes:
            aid = id(node._cls_node)
            uses_input = False
            for dep in node._children():
                if isinstance(dep, (InputNode, InputAttributeNode)):
                    uses_input = True
                elif isinstance(dep, ClassMethodNode) \
                        and id(dep) not in ctor_nodes \
                        and id(dep._cls_node) != aid:
                    consumers[id(dep)].add(aid)
            self._uses_input[aid] = self._uses_input.get(aid, False) \
                or uses_input
        self._consumers = consumers

        # channels: one per method node with a cross-process reader
        # ("driver" marks the driver as a reader); plus the input channel
        self._channel_readers: Dict[int, List[Any]] = {}
        for node in self._method_nodes:
            readers = sorted(consumers[id(node)], key=lambda a: str(a))
            if node in terminal:
                readers = readers + ["driver"]
            if readers:
                self._channel_readers[id(node)] = readers
        # actors with no channel inputs still need a per-execute trigger:
        # they subscribe to the driver's input channel as a tick
        input_readers: List[Any] = []
        for aid in steps_of:
            has_chan_input = any(
                aid in consumers[id(n)] for n in self._method_nodes)
            if self._uses_input.get(aid) or not has_chan_input:
                input_readers.append(aid)
        self._input_readers = sorted(input_readers, key=lambda a: str(a))

    # -------------------------------------------------------------- setup

    def _agent(self, addr) -> Any:
        return self._channels.agent(addr)

    def _setup(self, timeout: float) -> None:
        import ray_tpu
        from ray_tpu import api as _api

        w = _api._worker()
        # 1. where does everybody live?
        info_refs = {aid: w.submit_actor_task(
            handle._actor_id, DAG_INFO_METHOD, (), {})[0]
            for aid, handle in self._actors.items()
            if aid in self._steps_of}
        infos = dict(zip(info_refs,
                         ray_tpu.get(list(info_refs.values()),
                                     timeout=timeout)))
        try:
            xfer_port = int(w.agent.call("node_info").get("xfer_port") or 0)
        except Exception:
            xfer_port = 0
        driver_info = {"node_id": w.node_id, "agent": list(w.agent_addr),
                       "xfer_port": xfer_port}
        self._node_info = {"driver": driver_info,
                           **{aid: infos[aid] for aid in infos}}

        def node_of(entity) -> str:
            return self._node_info[entity]["node_id"]

        node_table = {info["node_id"]: {"agent": info["agent"],
                                        "xfer_port": info["xfer_port"]}
                      for info in self._node_info.values()}

        # 2. channel specs (per-channel ring overrides from
        #    node.with_channel_options win over the compile-wide sizes)
        def make_spec(name: str, writer_entity, reader_entities,
                      opts: Optional[Dict[str, int]] = None
                      ) -> ch.ChannelSpec:
            opts = opts or {}
            wnode = node_of(writer_entity)
            rnodes = [node_of(r) for r in reader_entities]
            involved = dict.fromkeys([wnode] + rnodes)
            return ch.ChannelSpec(
                oid=f"dagch-{self._dag_id}-{name}",
                max_in_flight=int(opts.get("max_in_flight")
                                  or self._max_in_flight),
                slot_size=int(opts.get("buffer_size_bytes")
                              or self._buffer),
                n_readers=len(reader_entities),
                writer_node=wnode, reader_nodes=rnodes,
                nodes={nid: node_table[nid] for nid in involved})

        self._input_spec = make_spec(
            "in", "driver", self._input_readers,
            getattr(self._input_node, "_channel_opts", None))
        self._out_specs: Dict[int, ch.ChannelSpec] = {}
        for nid, readers in self._channel_readers.items():
            node = next(n for n in self._method_nodes if id(n) == nid)
            self._out_specs[nid] = make_spec(
                self._node_key[nid], id_to_actor(nid, self), readers,
                node._channel_opts)

        # 3. allocate slots (writer node) and mirrors (reader nodes)
        for spec in [self._input_spec] + list(self._out_specs.values()):
            self._channels.create(spec)
        _register_live_channels(id(self), self._channels.oids())

        # 4. driver-side endpoints
        self._in_writer = ch.ChannelWriter(self._input_spec)
        self._out_readers: List[Tuple[int, ch.ChannelReader]] = []
        for leaf in self._terminal:
            spec = self._out_specs[id(leaf)]
            idx = spec_reader_index(spec, self._channel_readers[id(leaf)],
                                    "driver")
            self._out_readers.append(
                (id(leaf), ch.ChannelReader(spec, idx)))

        # 5. install the pinned loops
        for aid, steps in self._steps_of.items():
            plan = self._actor_plan(aid, steps)
            handle = self._actors[aid]
            self._loop_refs[aid] = w.submit_actor_task(
                handle._actor_id, DAG_EXEC_METHOD, (plan,), {})[0]

        # 6. death watch
        self._monitor = threading.Thread(
            target=self._monitor_loop, name=f"rt-dag-monitor-{self._dag_id}",
            daemon=True)
        self._monitor.start()

    def _actor_plan(self, aid: int, steps: List[ClassMethodNode]) -> Dict:
        import dataclasses

        inputs = []
        if aid in self._input_readers:
            inputs.append({
                "spec": dataclasses.asdict(self._input_spec),
                "index": spec_reader_index(self._input_spec,
                                           self._input_readers, aid),
                "key": _INPUT_KEY})
        seen_chan = set()
        for node in steps:
            for dep in node._children():
                if isinstance(dep, ClassMethodNode) \
                        and id(dep) in self._out_specs \
                        and id(dep._cls_node) != aid \
                        and id(dep) not in seen_chan:
                    seen_chan.add(id(dep))
                    spec = self._out_specs[id(dep)]
                    inputs.append({
                        "spec": dataclasses.asdict(spec),
                        "index": spec_reader_index(
                            spec, self._channel_readers[id(dep)], aid),
                        "key": self._node_key[id(dep)]})
        outputs = [{"spec": dataclasses.asdict(self._out_specs[id(n)]),
                    "key": self._node_key[id(n)]}
                   for n in steps if id(n) in self._out_specs]
        plan_steps = []
        for node in steps:
            args_t = _map_args(list(node._bound_args[0:]),
                               lambda d: self._arg_ref(d, aid))
            kwargs_t = _map_args(dict(node._bound_kwargs),
                                 lambda d: self._arg_ref(d, aid))
            plan_steps.append({"key": self._node_key[id(node)],
                               "method": node._method,
                               "args": tuple(args_t), "kwargs": kwargs_t})
        return {"dag_id": self._dag_id, "inputs": inputs,
                "outputs": outputs, "steps": plan_steps}

    def _arg_ref(self, dep: DAGNode, aid: int):
        if isinstance(dep, InputNode):
            return _ArgRef("input")
        if isinstance(dep, InputAttributeNode):
            return _ArgRef("input_attr", (dep._kind, dep._key))
        if isinstance(dep, ClassMethodNode) and id(dep) in self._node_key:
            return _ArgRef("node", self._node_key[id(dep)])
        if isinstance(dep, ClassNode) and id(dep) in self._actors:
            return self._actors[id(dep)]  # resolved handle as a constant
        if id(dep) in self._plan_memo:
            return self._plan_memo[id(dep)]  # compile-time constant
        raise ValueError(f"unsupported dependency {type(dep).__name__} "
                         "in a channel-compiled DAG")

    # ------------------------------------------------------------ execution

    def _check_failure(self) -> None:
        if self._error is not None:
            raise self._error
        if self._torn_down:
            raise RayError("this CompiledGraph has been torn down")

    def execute(self, *input_args) -> CompiledDAGRef:
        """Write the input channel; returns a :class:`CompiledDAGRef`
        reading the output channel.  Bounded: at most ``max_in_flight``
        executes may be outstanding (un-``get``) at once."""
        from ray_tpu._private import tracing
        from ray_tpu._private.metrics import dag_metrics

        self._check_failure()
        if self._input_node is not None:
            if len(input_args) != 1:
                raise TypeError(f"this DAG expects exactly one input, "
                                f"got {len(input_args)}")
            value = input_args[0]
        else:
            if input_args:
                raise TypeError("this DAG takes no input")
            value = None
        delivered = min(r.consumed for _nid, r in self._out_readers)
        if self._next_seq - delivered > self._max_in_flight:
            raise RayError(
                f"cannot execute: {self._max_in_flight} results are "
                "already in flight — get() them before submitting more, "
                "or compile with a larger max_in_flight")
        span = tracing.start_span("dag.execute", kind=tracing.KIND_CLIENT)
        seq = self._next_seq
        try:
            self._in_writer.write(value, check=self._check_failure)
        except BaseException as e:
            if span is not None:
                span.end(error=f"{type(e).__name__}: {e}")
            raise
        self._next_seq = seq + 1
        self._exec_started[seq] = time.perf_counter()
        if span is not None:
            span.set_attribute("dag_id", self._dag_id)
            span.set_attribute("seq", seq)
            span.end()
        dag_metrics()[1].inc(tags={"op": "execute"})
        return CompiledDAGRef(self, seq)

    def _result(self, seq: int, timeout: Optional[float]):
        from ray_tpu._private import tracing
        from ray_tpu._private.metrics import dag_metrics

        if seq in self._out_cache:
            return self._finish(seq, self._out_cache[seq])
        if seq >= self._next_seq:
            raise ValueError(f"no execution with seq {seq}")
        deadline = None if timeout is None else time.monotonic() + timeout
        span = tracing.start_span("dag.get", kind=tracing.KIND_CLIENT)
        try:
            while True:
                done = min(r.consumed for _nid, r in self._out_readers)
                if done >= seq:
                    break
                want = done + 1
                values = []
                for _nid, reader in self._out_readers:
                    left = None if deadline is None \
                        else max(0.0, deadline - time.monotonic())
                    values.append(reader.read(
                        want, timeout=left, check=self._check_failure,
                        copy=True))
                    reader.advance(want)
                self._out_cache[want] = values
                if len(self._out_cache) > 2 * self._max_in_flight:
                    self._out_cache.pop(min(self._out_cache))
        except BaseException as e:
            if span is not None:
                span.end(error=f"{type(e).__name__}: {e}")
            raise
        if span is not None:
            span.set_attribute("dag_id", self._dag_id)
            span.set_attribute("seq", seq)
            span.end()
        t0 = self._exec_started.pop(seq, None)
        if t0 is not None:
            dag_metrics()[0].observe(time.perf_counter() - t0)
        values = self._out_cache.get(seq)
        if values is None:
            raise RayError(
                f"result for execution {seq} was evicted from the "
                "out-of-order cache (too many un-got CompiledDAGRefs)")
        return self._finish(seq, values)

    def _finish(self, seq: int, values: List[Tuple[Any, bool]]):
        # error results stay cached so a retried get() re-raises the
        # original exception instead of a misleading eviction error
        for value, is_err in values:
            if is_err:
                raise value
        self._out_cache.pop(seq, None)
        out = [v for v, _err in values]
        return out if self._multi_output else out[0]

    # ---------------------------------------------------------- death watch

    def _monitor_loop(self) -> None:
        import ray_tpu
        from ray_tpu._private.config import config

        interval = float(config.dag_monitor_interval_s)
        refs = list(self._loop_refs.values())
        while refs and not self._monitor_stop.is_set():
            try:
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=interval)
            except Exception:
                return  # worker shut down under us
            if self._torn_down or self._monitor_stop.is_set():
                return
            for ref in ready:
                try:
                    ray_tpu.get(ref, timeout=0)
                except Exception as e:  # noqa: BLE001 — loop death
                    self._fail(e if isinstance(e, RayError) else
                               ActorDiedError(f"compiled-DAG actor loop "
                                              f"failed: {e}"))
                    return
                refs.remove(ref)  # clean exit (teardown elsewhere)

    def _fail(self, error: BaseException) -> None:
        """Poison every channel on every involved node so all blocked
        readers/writers (driver and actors) raise promptly."""
        if self._error is not None:
            return
        self._error = error
        self._channels.poison_all(ch.pickle_error(error))

    # ------------------------------------------------------------- teardown

    def teardown(self, timeout: Optional[float] = None) -> None:
        """Synchronous, idempotent: close channels, drain loops, kill and
        wait out the plan's actors, free the pinned slots."""
        import ray_tpu
        from ray_tpu import api as _api
        from ray_tpu._private.config import config

        with self._teardown_lock:
            if self._torn_down:
                return
            self._torn_down = True
        # this graph no longer claims its slots: if the destroys below
        # fail, the accounting layer flags them leaked (correctly)
        _unregister_live_channels(id(self))
        self._monitor_stop.set()
        timeout = (float(config.dag_teardown_timeout_s)
                   if timeout is None else timeout)
        deadline = time.monotonic() + timeout
        # 1. wake every loop: close all channels everywhere
        self._channels.poison_all(close_only=True)
        # 2. loops drain and return; a wedged loop is force-killed so
        #    teardown stays bounded
        refs = list(self._loop_refs.values())
        if refs:
            ready, pending = ray_tpu.wait(
                refs, num_returns=len(refs),
                timeout=max(0.1, deadline - time.monotonic()))
            for ref in pending:
                try:
                    ray_tpu.cancel(ref, force=True)
                except Exception:
                    pass
        # 3. kill the compiled plan's actors and wait for death
        for handle in self._actors.values():
            try:
                ray_tpu.kill(handle)
            except Exception:
                pass
        w = _api._worker()
        for handle in self._actors.values():
            while time.monotonic() < deadline:
                try:
                    info = w.head.call("get_actor_info",
                                       actor_id=handle._actor_id)
                except Exception:
                    break
                if info.get("state") == "DEAD":
                    break
                time.sleep(0.05)
        self._actors.clear()
        # 4. free the pinned slots
        self._channels.destroy_all()
        if self._in_writer is not None:
            self._in_writer.detach()
        self._channels.close()
        if self._monitor is not None \
                and self._monitor is not threading.current_thread():
            self._monitor.join(timeout=1.0)

    def __del__(self):
        try:
            if not self._torn_down:
                self.teardown(timeout=2.0)
        except Exception:
            pass


def spec_reader_index(spec: ch.ChannelSpec, readers: List[Any],
                      entity) -> int:
    return readers.index(entity)


def id_to_actor(nid: int, dag: CompiledGraph) -> int:
    """The actor (ClassNode id) that owns method node `nid`."""
    for node in dag._method_nodes:
        if id(node) == nid:
            return id(node._cls_node)
    raise KeyError(nid)
