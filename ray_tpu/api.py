"""Public API: init/shutdown, @remote, get/put/wait, actors.

Equivalent of the reference's driver API surface
(reference: python/ray/_private/worker.py — ray.init :1217, ray.get
:2533, ray.put :2665, ray.wait :2730, ray.remote :3123;
python/ray/remote_function.py:276 RemoteFunction._remote;
python/ray/actor.py:857 ActorClass._remote).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu._private.errors import RayError
from ray_tpu._private.object_ref import ObjectRef

_state_lock = threading.RLock()
# RT_* env vars exported by init(_system_config=...) -> their PRIOR
# value (None = absent before): shutdown() restores rather than pops,
# so one cluster's overrides never leak into the next AND an
# operator-exported RT_* setting survives an init/shutdown cycle
_config_env_prior: Dict[str, Any] = {}
_global_node: Optional[Dict[str, Any]] = None  # procs + addrs when we own them


def is_initialized() -> bool:
    from ray_tpu._private.worker import global_worker_or_none

    return global_worker_or_none() is not None


def _worker():
    from ray_tpu._private.worker import global_worker_or_none

    w = global_worker_or_none()
    if w is None:
        raise RayError("ray_tpu.init() has not been called")
    return w


def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         object_store_memory: Optional[int] = None,
         runtime_env: Optional[Dict[str, Any]] = None,
         log_to_driver: Optional[bool] = None,
         _system_config: Optional[Dict[str, Any]] = None,
         ignore_reinit_error: bool = False):
    """Start (or connect to) a cluster and attach this process as a driver.

    With no address, spawns a head service and one node agent locally
    (reference: worker.py:1217 bootstrap path). With address="host:port",
    connects to an existing head and uses the head node's agent.

    ``log_to_driver`` (default: config ``log_to_driver``, on) streams
    worker stdout/stderr to this driver's console with
    ``(pid=..., node=...)`` prefixes via the node agents' log monitors.
    """
    import os as _os

    from ray_tpu._private import node as node_mod
    from ray_tpu._private.config import config
    from ray_tpu._private.rpc import EventLoopThread, SyncRpcClient
    from ray_tpu._private.worker import CoreWorker, MODE_DRIVER, \
        global_worker_or_none, set_global_worker

    if address is None:
        # the environment wins when a job/driver was launched by the CLI
        # or job supervisor (reference: RAY_ADDRESS)
        address = _os.environ.get("RT_ADDRESS") or None
    if address == "local":
        address = None
    client_mode = False
    if address and address.startswith("rt://"):
        # client mode (reference: ray.init("ray://...") — the driver may
        # run on a machine with no access to the node's shm arena; object
        # data proxies through the agent RPC instead of mmap)
        client_mode = True
        address = address[len("rt://"):]

    global _global_node
    with _state_lock:
        if global_worker_or_none() is not None:
            if ignore_reinit_error:
                return
            raise RayError("ray_tpu.init() called twice")
        config.initialize(_system_config)
        env = {}
        if _system_config:
            env = config.deserialize_into_env(config.serialize())
            import os

            # prior values recorded so shutdown() can restore them:
            # without this a stale RT_* var from one cluster's
            # _system_config leaks into every LATER cluster's spawned
            # daemons (env has precedence over fresh overrides)
            for k in env:
                _config_env_prior.setdefault(k, os.environ.get(k))
            os.environ.update(env)
        if address is None:
            session_dir = node_mod.new_session_dir()
            head_proc, head_addr = node_mod.start_head(session_dir, env=env)
            res = node_mod.default_resources(num_cpus, resources)
            agent_proc, info = node_mod.start_node_agent(
                session_dir, head_addr, res,
                object_store_memory=object_store_memory,
                is_head_node=True, env=env)
            _global_node = {"procs": [agent_proc, head_proc],
                            "session_dir": session_dir}
        else:
            host, port_s = address.rsplit(":", 1)
            head_addr = (host, int(port_s))
            io = EventLoopThread(name="rt-init")
            try:
                head = SyncRpcClient(head_addr[0], head_addr[1], io, label="head")
                table = head.call("node_table")
                head.close()
            finally:
                io.stop()
            entry = next((v for v in table.values() if v.get("is_head_node")),
                         next(iter(table.values()), None))
            if entry is None:
                raise RayError(f"no node agents registered at {address}")
            info = {"addr": tuple(entry["addr"]), "node_id": entry["node_id"],
                    "arena_path": entry["arena_path"]}
            _global_node = None
        worker = CoreWorker(MODE_DRIVER, head_addr, info["addr"],
                            None if client_mode else info["arena_path"],
                            info["node_id"], log_to_driver=log_to_driver)
        if runtime_env:
            # job-level default: every task/actor of this driver inherits
            # it unless overridden (reference: job_config.runtime_env)
            from ray_tpu._private import runtime_env as renv_mod

            try:
                worker.job_runtime_env = renv_mod.normalize(
                    runtime_env, worker.head)
            except BaseException:
                set_global_worker(None)
                worker.shutdown()
                _teardown_global_node()
                raise
        set_global_worker(worker)
        return


def _teardown_global_node():
    global _global_node
    if _global_node is not None:
        for p in _global_node["procs"]:
            p.terminate()
        _global_node = None


def shutdown():
    from ray_tpu._private.worker import global_worker_or_none, set_global_worker

    global _global_node
    with _state_lock:
        w = global_worker_or_none()
        if w is not None:
            if _global_node is not None:
                try:
                    w.head.call("shutdown_cluster", timeout=2.0)
                except Exception:
                    pass
            set_global_worker(None)
            w.shutdown()
        _renv_cache.clear()
        _teardown_global_node()
        # _system_config overrides die with the cluster: initialize()
        # merges into the live override dict and init() exported RT_*
        # env vars, so without this cleanup a stale key from one init()
        # (e.g. a test's memory_monitor usage file) silently leaks into
        # the NEXT cluster's spawned daemons
        from ray_tpu._private.config import config as _config

        _config._overrides.clear()
        import os as _os2

        for k, prior in _config_env_prior.items():
            if prior is None:
                _os2.environ.pop(k, None)
            else:
                _os2.environ[k] = prior
        _config_env_prior.clear()


def put(value: Any) -> ObjectRef:
    return _worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    w = _worker()
    if isinstance(refs, ObjectRef):
        return w.get([refs], timeout=timeout)[0]
    return w.get(list(refs), timeout=timeout)


async def get_async(refs: Union[ObjectRef, Sequence[ObjectRef]],
                    *, timeout: Optional[float] = None) -> Any:
    """Awaitable ray_tpu.get: resolves on the calling event loop via
    owner-side completion futures — no thread blocked per caller, so an
    event-loop server (the async Serve ingress) can await thousands of
    refs concurrently.  ``await ref`` and ``ref.future()`` are sugar
    over the same path."""
    w = _worker()
    if isinstance(refs, ObjectRef):
        return (await w.get_async([refs], timeout=timeout))[0]
    return await w.get_async(list(refs), timeout=timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None
         ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return _worker().wait(refs, num_returns=num_returns, timeout=timeout)


def kill(actor: "ActorHandle", *, no_restart: bool = True):
    _worker().kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref, *, force: bool = False):
    """Cancel a submitted task (reference: ray.cancel,
    python/ray/_private/worker.py:2942).  Accepts any return ref of the
    task or its ObjectRefGenerator.  Non-force interrupts the running
    body (async tasks are cancelled; sync bodies get TaskCancelledError
    at the next bytecode); force=True kills the executing worker.
    Waiters observe TaskCancelledError.  No-op on finished tasks."""
    _worker().cancel(ref, force=force)


def get_actor(name: str) -> "ActorHandle":
    w = _worker()
    reply = w.head.call("get_named_actor", name=name)
    if not reply.get("found"):
        raise ValueError(f"no actor named {name!r}")
    return ActorHandle(reply["actor_id"],
                       method_num_returns=reply.get("method_num_returns"))


def cluster_resources() -> Dict[str, float]:
    return _worker().head.call("cluster_resources")["total"]


def available_resources() -> Dict[str, float]:
    return _worker().head.call("cluster_resources")["available"]


def nodes() -> List[Dict[str, Any]]:
    table = _worker().head.call("node_table")
    return list(table.values())


# --------------------------------------------------------------------- tasks


class RemoteFunction:
    """Handle produced by @remote on a function
    (reference: python/ray/remote_function.py)."""

    _OPT_KEYS = ("num_returns", "num_cpus", "num_gpus", "num_tpus",
                 "memory", "resources", "max_retries", "name",
                 "runtime_env", "scheduling_strategy", "timeout_s",
                 "placement_group", "placement_group_bundle_index")

    def __init__(self, fn, **opts):
        bad = set(opts) - set(self._OPT_KEYS)
        if bad:
            raise TypeError(f"unknown @remote option(s): {sorted(bad)}")
        self._fn = fn
        self._opts = opts
        self._num_returns = opts.get("num_returns") or 1
        self._resources = _build_resources(
            opts.get("num_cpus"), opts.get("num_gpus"), opts.get("num_tpus"),
            opts.get("resources"), default_cpu=1,
            memory=opts.get("memory"))
        self._max_retries = opts.get("max_retries", 3)
        self._name = opts.get("name") or getattr(
            fn, "__qualname__", getattr(fn, "__name__", "fn"))
        # (cluster worker_id -> function table id): the table is per-head,
        # so a new init() after shutdown() must re-export
        self._function_ids: Dict[str, str] = {}
        self.__doc__ = getattr(fn, "__doc__", None)

    def options(self, **opts) -> "RemoteFunction":
        """New handle with the given options overriding, others inherited."""
        return RemoteFunction(self._fn, **{**self._opts, **opts})

    def _fid(self, w) -> str:
        fid = self._function_ids.get(w.worker_id)
        if fid is None:
            fid = w.functions.export(self._fn)
            self._function_ids = {w.worker_id: fid}
        return fid

    def remote(self, *args, **kwargs):
        w = _worker()
        pg = self._opts.get("placement_group")
        refs = w.submit_task(
            self._fid(w), args, kwargs, num_returns=self._num_returns,
            resources=self._resources, max_retries=self._max_retries,
            name=self._name, runtime_env=_normalized_renv(self, w),
            scheduling_strategy=_strategy_wire(self._opts),
            placement_group_id=pg.id if pg is not None else "",
            bundle_index=self._opts.get("placement_group_bundle_index", -1),
            timeout_s=self._opts.get("timeout_s"))
        if self._num_returns == 1 or self._num_returns == "streaming":
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of submitting now
        (reference: python/ray/dag/function_node.py)."""
        from ray_tpu.dag.nodes import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._name} cannot be called directly; "
            f"use {self._name}.remote(...)")


def _strategy_wire(opts: Dict[str, Any]) -> Dict[str, Any]:
    from ray_tpu.util.scheduling_strategies import strategy_to_wire

    return strategy_to_wire(opts.get("scheduling_strategy"))


_renv_cache: Dict[tuple, Dict[str, Any]] = {}


def _normalized_renv(handle, w) -> Dict[str, Any]:
    """Normalize (package + upload) a handle's runtime_env option once
    per (cluster connection, env content) — NOT per handle: options()
    mints a fresh handle per call, and re-zipping a working_dir on every
    submission would cost seconds of CPU each."""
    import json

    renv = handle._opts.get("runtime_env")
    if not renv:
        return {}
    key = (w.worker_id, json.dumps(renv, sort_keys=True, default=str))
    cached = _renv_cache.get(key)
    if cached is None:
        from ray_tpu._private import runtime_env as renv_mod

        if len(_renv_cache) > 256:  # old connections / envs
            _renv_cache.clear()
        cached = _renv_cache[key] = renv_mod.normalize(renv, w.head)
    return cached


def _build_resources(num_cpus, num_gpus, num_tpus, resources,
                     default_cpu: float,
                     memory=None) -> Dict[str, float]:
    out: Dict[str, float] = dict(resources or {})
    out["CPU"] = float(num_cpus) if num_cpus is not None else float(default_cpu)
    if num_gpus is not None:
        out["GPU"] = float(num_gpus)
    if num_tpus is not None:
        out["TPU"] = float(num_tpus)
    if memory is not None:
        # bytes, bin-packed against the node's `memory` total (the
        # watchdog's virtual envelope when configured, else MemTotal) —
        # declared memory is a real reservation, not a hint
        out["memory"] = float(memory)
    return out


# -------------------------------------------------------------------- actors


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        num_returns = self._handle._method_num_returns.get(self._name, 1)
        return self._remote_n(num_returns, None, *args, **kwargs)

    def options(self, *, num_returns: Union[int, str, None] = None,
                timeout_s: Optional[float] = None):
        m = ActorMethod(self._handle, self._name)

        def call(*a, **kw):
            # None = keep the @method(num_returns=...) annotation —
            # options(timeout_s=...) alone must not reset return shape
            nr = num_returns if num_returns is not None \
                else self._handle._method_num_returns.get(self._name, 1)
            return self._remote_n(nr, timeout_s, *a, **kw)

        m.remote = call
        return m

    def _remote_n(self, num_returns, timeout_s, *args, **kwargs):
        w = _worker()
        if timeout_s is None:
            timeout_s = self._handle._timeout_s
        refs = w.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=num_returns,
            max_retries=self._handle._max_task_retries,
            timeout_s=timeout_s)
        return refs[0] if num_returns in (1, "streaming") else refs

    def __call__(self, *a, **kw):
        raise TypeError(f"Actor method {self._name} must be called with .remote()")


class ActorHandle:
    """Serializable handle to a remote actor
    (reference: python/ray/actor.py ActorHandle).  The handle returned by
    `.remote()` owns the actor's lifetime: when it is garbage collected
    the actor is terminated (reference: out-of-scope actor GC).  Copies
    obtained by serialization or get_actor do not own the actor."""

    def __init__(self, actor_id: str, max_task_retries: int = 0,
                 method_num_returns: Optional[Dict[str, int]] = None,
                 _owner: bool = False, timeout_s: Optional[float] = None):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries
        self._method_num_returns = method_num_returns or {}
        self._owner = _owner
        # default per-call deadline budget for every method of this
        # handle (ActorClass.options(timeout_s=...)); a per-call
        # ActorMethod.options(timeout_s=...) overrides it
        self._timeout_s = timeout_s

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __reduce__(self):
        return (ActorHandle,
                (self._actor_id, self._max_task_retries,
                 self._method_num_returns, False, self._timeout_s))

    def __del__(self):
        if getattr(self, "_owner", False):
            try:
                from ray_tpu._private.worker import global_worker_or_none

                w = global_worker_or_none()
                if w is not None:
                    w.kill_actor_async(self._actor_id)
            except Exception:
                pass

    def __repr__(self):
        return f"ActorHandle({self._actor_id[:16]}…)"


class ActorClass:
    _OPT_KEYS = ("num_cpus", "num_gpus", "num_tpus", "memory", "resources",
                 "max_restarts", "max_task_retries", "max_concurrency",
                 "name", "lifetime", "runtime_env", "scheduling_strategy",
                 "timeout_s",
                 "placement_group", "placement_group_bundle_index")

    def __init__(self, cls, **opts):
        bad = set(opts) - set(self._OPT_KEYS)
        if bad:
            raise TypeError(f"unknown actor option(s): {sorted(bad)}")
        self._cls = cls
        self._opts = opts
        # actors hold 0 CPUs while alive unless explicitly requested
        # (reference: ray actor default num_cpus=0 post-creation, so many
        # actors coexist on few cores)
        self._resources = _build_resources(
            opts.get("num_cpus"), opts.get("num_gpus"), opts.get("num_tpus"),
            opts.get("resources"), default_cpu=0,
            memory=opts.get("memory"))
        self._max_restarts = opts.get("max_restarts", 0)
        self._max_task_retries = opts.get("max_task_retries", 0)
        self._max_concurrency = opts.get("max_concurrency", 1)
        self._name = opts.get("name", "")
        self._lifetime = opts.get("lifetime", "")
        self._class_ids: Dict[str, str] = {}
        self.__doc__ = getattr(cls, "__doc__", None)

    def options(self, **opts) -> "ActorClass":
        """New handle with the given options overriding, others inherited."""
        return ActorClass(self._cls, **{**self._opts, **opts})

    def remote(self, *args, **kwargs) -> ActorHandle:
        w = _worker()
        cid = self._class_ids.get(w.worker_id)
        if cid is None:
            cid = w.functions.export(self._cls)
            self._class_ids = {w.worker_id: cid}
        pg = self._opts.get("placement_group")
        actor_id = w.create_actor(
            cid, args, kwargs, resources=self._resources,
            max_restarts=self._max_restarts,
            max_task_retries=self._max_task_retries,
            max_concurrency=self._max_concurrency, name=self._name,
            runtime_env=_normalized_renv(self, w),
            scheduling_strategy=_strategy_wire(self._opts),
            placement_group_id=pg.id if pg is not None else "",
            bundle_index=self._opts.get("placement_group_bundle_index", -1),
            method_num_returns=self._method_num_returns())
        owner = self._lifetime != "detached"
        return ActorHandle(actor_id, max_task_retries=self._max_task_retries,
                           method_num_returns=self._method_num_returns(),
                           _owner=owner,
                           timeout_s=self._opts.get("timeout_s"))

    def _method_num_returns(self) -> Dict[str, Any]:
        """Collect @method(num_returns=...) annotations off the class
        (reference: python/ray/actor.py method decorator)."""
        out: Dict[str, Any] = {}
        for name in dir(self._cls):
            fn = getattr(self._cls, name, None)
            nr = getattr(fn, "__rt_num_returns__", None)
            if nr is not None:
                out[name] = nr
        return out

    def bind(self, *args, **kwargs):
        """Build an actor DAG node instead of creating the actor now
        (reference: python/ray/dag/class_node.py)."""
        from ray_tpu.dag.nodes import ClassNode

        return ClassNode(self, args, kwargs)

    def __call__(self, *a, **kw):
        raise TypeError("Actor classes must be instantiated with .remote()")


# ------------------------------------------------------------------- remote


def method(*, num_returns: Union[int, str] = 1):
    """Annotate an actor method's return shape, e.g. streaming:

        @ray_tpu.remote
        class A:
            @ray_tpu.method(num_returns="streaming")
            def gen(self): yield ...

    (reference: python/ray/actor.py:42 @ray.method)."""
    def mark(fn):
        fn.__rt_num_returns__ = num_returns
        return fn
    return mark


def remote(*args, **kwargs):
    """@remote decorator for functions and classes
    (reference: python/ray/_private/worker.py:3123)."""

    def make(target):
        if isinstance(target, type):
            return ActorClass(target, **kwargs)
        return RemoteFunction(target, **kwargs)

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")
    return make
